"""Whole-pipeline fused serving compilation.

Flare-style native compilation of the fitted stage DAG (ROADMAP item 1;
PAPERS.md: Flare, arXiv 1703.08219 compiles whole Spark query plans
instead of interpreting operators; arXiv 1810.09868 compiles full
model-plus-preprocessing graphs to one XLA executable): every fitted
stage that implements the ``lower()`` seam (stages/base.Lowering)
contributes one pure array function, and the :class:`PipelineCompiler`
fuses the topologically-ordered plan into ONE closed-over program -
raw record dicts decode straight into dense input arrays, flow through
the fused steps as a flat ``dict[str, np.ndarray]`` environment, and
come out as result dicts.  No Column/Dataset boxing, no per-stage
``to_list``/``column_from_list`` round trips (enforced by the style
gate in tests/test_style.py: this module must stay columnar end to
end - statement loops are forbidden; the only per-record python is
the single-pass decode/assembly comprehensions at the boundary).

Compilation is per shape bucket: the first batch of a given length
through :meth:`FusedPipeline.score_batch` warms every stage closure
(one-hot code memos, native-kernel dispatch) for exactly that shape
and records the compile/warm wall time, which serving telemetry
surfaces per bucket.  A pipeline with any non-lowerable stage raises
:class:`FusionError` at compile time and the caller (LocalScorer)
serves through the interpreted path for the life of the pipeline -
the fused/interpreted choice is per-pipeline, never per-batch.
"""
from __future__ import annotations

import threading
import time
from functools import lru_cache, reduce
from operator import itemgetter
from typing import Any, Mapping, Sequence

import numpy as np

from ..stages.base import MASK_SUFFIX, PROB_SUFFIX, RAW_SUFFIX
from ..types.columns import (
    ListColumn,
    NumericColumn,
    TextColumn,
    decode_numeric,
    decode_text,
    list_values,
    present_nan_slots,
    text_values,
)
from ..types.feature_types import Prediction

#: raw-feature kinds the fused decoder can turn into env arrays
DECODABLE_KINDS = ("numeric", "text", "textlist", "datelist",
                   "multipicklist")

#: compiled shape-bucket entries kept per pipeline (endpoints pad to a
#: handful of buckets; a caller submitting arbitrary batch lengths must
#: not grow the program cache without bound)
_MAX_SHAPE_PROGRAMS = 64


class FusionError(Exception):
    """The fitted pipeline cannot be compiled into one fused program;
    carries the human-readable reason (surfaced in serving telemetry)."""


# -- record decoding --------------------------------------------------------
# decode_numeric / decode_text / text_values / present_nan_slots live in
# types/columns.py next to the from_list semantics they mirror (and so
# schema/drift.py can share them without importing this package).

_NAN = float("nan")


def _object_array(values: list) -> np.ndarray:
    """list -> object [n] without numpy's auto-2D collapse of
    equal-length tuples."""
    out = np.empty(len(values), dtype=object)
    out[:] = values
    return out


def _list_values(values, as_set: bool) -> np.ndarray:
    """Raw values -> object [n] of tuples (order kept) or frozensets,
    through the canonical ``list_values`` conversion in types/columns.py
    that column_from_list also uses, so the two can never drift apart."""
    return _object_array(list_values(values, as_set))


class RecordDecoder:
    """Per-pipeline compiled decoder: raw record dicts -> env arrays
    (fused path) or Columns (the interpreted path reuses the same
    extraction, skipping the per-element ``column_from_list`` loop).

    The env hot path extracts ALL features from the batch in one
    C-speed ``itemgetter`` pass (tuple rows, then ``zip(*rows)`` to
    per-feature columns), and converts every numeric feature together
    as one [k, n] object matrix - the per-feature ``dict.get``
    comprehensions were the top line of the fused profile at ~2.6us
    of a 3.6us/row total."""

    def __init__(self, features: Sequence) -> None:
        self.features = tuple(features)
        self._names = tuple(f.name for f in self.features)
        self._numeric = tuple(
            (i, f.name) for i, f in enumerate(self.features)
            if f.ftype.kind == "numeric"
        )
        self._other = tuple(
            (i, f) for i, f in enumerate(self.features)
            if f.ftype.kind != "numeric"
        )
        self._getter = (
            itemgetter(*self._names) if self._names else None
        )

    # -- env arrays (fused hot path) ----------------------------------------
    def _columns(self, records: Sequence[Mapping[str, Any]]) -> list:
        """Per-feature value tuples, order matching ``self.features``."""
        if all(type(r) is dict for r in records):
            try:
                rows = list(map(self._getter, records))
            except KeyError:
                rows = None  # records missing keys: tolerant path below
            if rows is not None:
                if len(self._names) == 1:  # itemgetter returns bare values
                    return [tuple(rows)]
                return list(zip(*rows))
        # Mapping subtypes (a defaultdict's __missing__ would fabricate a
        # present value AND insert it into the caller's record under
        # itemgetter) and key-missing records: per-key Mapping.get, same
        # None-as-missing semantics as the interpreted decode
        return [tuple(r.get(nm) for r in records) for nm in self._names]

    def decode_env(self, records: Sequence[Mapping[str, Any]]) -> dict:
        if not self._names:
            return {}
        cols = self._columns(records)
        env: dict = {}
        if self._numeric:
            sub = np.array([cols[i] for i, _ in self._numeric],
                           dtype=object)
            if sub.ndim != 2:  # equal-length list values would build 3D
                raise TypeError("numeric feature values are not scalars")
            mask2d = sub != None  # noqa: E711 - elementwise over objects
            sub[~mask2d] = _NAN
            vals2d = sub.astype(np.float64)
            nan2d = np.isnan(vals2d) & mask2d
            mask2d &= ~nan2d
            if nan2d.any():
                # from_list parity: NaN-valued non-float inputs (str
                # "nan", np.float32 NaN) stay PRESENT as NaN for the
                # output guard; only python-float NaN is missing
                flat = np.flatnonzero(nan2d.ravel()).tolist()
                present = present_nan_slots(flat, sub.ravel())
                mask2d.ravel()[present] = True
            vals2d = np.where(mask2d, vals2d, 0.0)
            env.update({
                key: arr
                for j, (_, name) in enumerate(self._numeric)
                for key, arr in ((name, vals2d[j]),
                                 (name + MASK_SUFFIX, mask2d[j]))
            })
        env.update({
            key: val
            for i, f in self._other
            for key, val in self._env_other(cols[i], f)
        })
        return env

    @staticmethod
    def _env_other(values: tuple, f) -> tuple:
        kind = f.ftype.kind
        if kind == "text":
            return ((f.name, text_values(values)),)
        if kind in ("textlist", "datelist"):
            return ((f.name, _list_values(values, as_set=False)),)
        if kind == "multipicklist":
            return ((f.name, _list_values(values, as_set=True)),)
        raise FusionError(  # pragma: no cover - compiler rejects upfront
            f"raw feature {f.name!r} has undecodable kind {kind!r}"
        )

    # -- Columns (interpreted path) -----------------------------------------
    def decode_columns(self, records: Sequence[Mapping[str, Any]]) -> dict:
        return {f.name: self._column_one(records, f) for f in self.features}

    def _column_one(self, records, f):
        kind = f.ftype.kind
        if kind == "numeric":
            vals, mask = decode_numeric(records, f.name)
            return NumericColumn(vals, mask, f.ftype)
        if kind == "text":
            return TextColumn(decode_text(records, f.name), f.ftype)
        if kind in ("textlist", "datelist"):
            return ListColumn(
                list(_list_values([r.get(f.name) for r in records],
                                  as_set=False)), f.ftype
            )
        if kind == "multipicklist":
            return ListColumn(
                list(_list_values([r.get(f.name) for r in records],
                                  as_set=True)), f.ftype
            )
        # map/geolocation/vector kinds ride the caller's column_from_list
        # slow path - duplicating those per-element builds here bought no
        # speedup and risked semantic drift from the canonical versions
        raise TypeError(f"cannot decode column for kind {kind!r}")


# -- result assembly --------------------------------------------------------

@lru_cache(maxsize=256)
def _prediction_keys(raw_w: int, prob_w: int) -> tuple:
    """PredictionColumn.to_list's key layout for given raw/prob widths,
    memoized (key-list rebuild showed up at ~2us/batch x every batch)."""
    return (
        (Prediction.KEY_PREDICTION,)
        + tuple(f"{Prediction.KEY_RAW}_{j}" for j in range(raw_w))
        + tuple(f"{Prediction.KEY_PROB}_{j}" for j in range(prob_w))
    )


@lru_cache(maxsize=256)
def _row_builder(name: str, keys: tuple):
    """Compile a per-row result constructor for one (feature, keys)
    signature: a generated dict-literal lambda builds both dict levels
    in ONE python call (dict(zip(...)) allocated a 2-tuple per key per
    row - measured ~0.9us/row on the RF-winner batch surface).  The
    generated source contains no interpolated VALUES: feature/key
    strings bind through the eval globals."""
    binds = {f"_k{i}": k for i, k in enumerate(keys)}
    binds["_nm"] = name
    body = ", ".join(f"_k{i}: r[{i}]" for i in range(len(keys)))
    return eval(  # noqa: S307 - generated from our own constants
        f"lambda r: {{_nm: {{{body}}}}}", binds
    )


def _prediction_stack_arrays(env: dict, name: str) -> tuple:
    """Prediction env arrays -> (key layout, [n, k] float array): the
    ONE place the prediction column order (prediction, raw_*, prob_*)
    is stacked, shared by _assemble_prediction, the score_batch
    single-result fast path and the bulk job's columnar line encoder
    so the three can never diverge."""
    pred = env[name]
    raw = env.get(name + RAW_SUFFIX)
    prob = env.get(name + PROB_SUFFIX)
    keys = _prediction_keys(
        raw.shape[1] if raw is not None else 0,
        prob.shape[1] if prob is not None else 0,
    )
    parts = [pred[:, None]] + [a for a in (raw, prob) if a is not None]
    return keys, np.concatenate(parts, axis=1)


def _prediction_stack(env: dict, name: str) -> tuple:
    """:func:`_prediction_stack_arrays` with per-row value lists."""
    keys, stacked = _prediction_stack_arrays(env, name)
    return keys, stacked.tolist()


def _assemble_prediction(env: dict, name: str) -> list:
    """Prediction env arrays -> per-row dicts matching
    PredictionColumn.to_list exactly (same keys, same float values)."""
    keys, stacked = _prediction_stack(env, name)
    return [dict(zip(keys, row)) for row in stacked]


def _assemble_numeric(env: dict, name: str) -> list:
    vals = env[name].tolist()
    mask = env[name + MASK_SUFFIX].tolist()
    return [v if m else None for v, m in zip(vals, mask)]


def _assemble_vector(env: dict, name: str) -> list:
    return env[name].tolist()


def _assemble_text(env: dict, name: str) -> list:
    return list(env[name])


_ASSEMBLERS = {
    "prediction": _assemble_prediction,
    "numeric": _assemble_numeric,
    "vector": _assemble_vector,
    "text": _assemble_text,
}


# -- the fused program ------------------------------------------------------

def _apply_step(env: dict, fn) -> dict:
    env.update(fn(env))
    return env


def _nonfinite_mask(env: dict, name: str, n: int) -> np.ndarray:
    """Per-row bool [n]: any non-finite float among this result
    feature's arrays (pred + raw + prob for predictions; mask-aware for
    numerics - a masked slot serves as None, never as a bad float)."""
    arrays = [a for a in (
        env.get(name), env.get(name + RAW_SUFFIX),
        env.get(name + PROB_SUFFIX),
    ) if isinstance(a, np.ndarray) and a.dtype.kind == "f"]
    if not arrays:
        return np.zeros(n, dtype=bool)
    masks = [
        ~np.isfinite(a) if a.ndim == 1 else (~np.isfinite(a)).any(axis=1)
        for a in arrays
    ]
    bad = reduce(np.logical_or, masks)
    present = env.get(name + MASK_SUFFIX)
    return bad & present if present is not None else bad


class FusedPipeline:
    """One compiled array program over the whole fitted plan.

    ``score_batch`` is the hot path: decode -> fused steps -> assemble.
    The first batch of each distinct length is that shape bucket's
    compile/warm execution; its wall time is kept in ``compile_ms``
    keyed by batch length (serving telemetry exports it per bucket).
    """

    def __init__(self, decoder: RecordDecoder, step_fns: Sequence,
                 result_plan: Sequence, describe: Sequence) -> None:
        self._decoder = decoder
        self._step_fns = tuple(step_fns)
        #: (feature name, assembler) per result feature, in result order
        self._result_plan = tuple(result_plan)
        #: per-stage (uid, operation_name, inputs, outputs, signature)
        self.plan = tuple(describe)
        #: shape bucket (batch length) -> first-execution wall ms
        self.compile_ms: dict[int, float] = {}
        #: single-Prediction-result fast path marker (score_batch)
        self._single_prediction = (
            result_plan[0][0]
            if len(result_plan) == 1
            and result_plan[0][1] is _assemble_prediction
            else None
        )
        # row indices of the last scored batch whose float results are
        # non-finite, computed columnar (np.isfinite over the result
        # arrays) so the serving NaN/Inf guard need not re-walk every
        # result dict in python.  Thread-local: the scheduler worker and
        # any number of direct endpoint callers each read back the mask
        # of THEIR batch (valid between their score_batch return and
        # their next call), never a concurrent caller's.
        self._nonfinite_tl = threading.local()

    @property
    def last_nonfinite_rows(self) -> tuple:
        """Non-finite row indices of the calling thread's last batch."""
        return getattr(self._nonfinite_tl, "rows", ())

    @last_nonfinite_rows.setter
    def last_nonfinite_rows(self, rows: tuple) -> None:
        self._nonfinite_tl.rows = rows

    def score_batch(
        self, records: Sequence[Mapping[str, Any]]
    ) -> list[dict[str, Any]]:
        n = len(records)
        if n == 0:
            self.last_nonfinite_rows = ()
            return []
        # beyond the cap, new shapes run fine but are no longer timed:
        # evicting would both break the endpoint's len()-based new-
        # bucket push detection and re-record a warm bucket's next
        # ordinary execution as compile cost
        cold = (n not in self.compile_ms
                and len(self.compile_ms) < _MAX_SHAPE_PROGRAMS)
        t0 = time.perf_counter() if cold else 0.0
        out = self.score_env(self._decoder.decode_env(records), n)
        if cold:
            self.compile_ms[n] = (time.perf_counter() - t0) * 1e3
        return out

    def score_env(self, env: dict, n: int) -> list[dict[str, Any]]:
        """Columnar entry (ISSUE 18): run the fused steps + assembly
        over a PRE-BUILT decode env - the bulk job feeds pipelined
        chunk columns here directly, skipping per-record decode.  The
        env must hold every decoder feature's keys (``name`` +
        ``name@mask`` for numerics) with the decode_env missing-value
        conventions; ``score_batch`` is exactly this after decode."""
        if n == 0:
            self.last_nonfinite_rows = ()
            return []
        env = reduce(_apply_step, self._step_fns, env)
        if self._single_prediction is not None:
            # the dominant serving shape (one Prediction result): build
            # the row dicts in ONE pass instead of column-then-wrap
            name = self._single_prediction
            keys, stacked = _prediction_stack(env, name)
            out = list(map(_row_builder(name, keys), stacked))
        elif len(self._result_plan) == 1:
            (name, fn), = self._result_plan
            out = [{name: v} for v in fn(env, name)]
        else:
            names = [name for name, _ in self._result_plan]
            columns = [fn(env, name) for name, fn in self._result_plan]
            out = [dict(zip(names, row)) for row in zip(*columns)]
        self.last_nonfinite_rows = tuple(
            np.flatnonzero(
                reduce(
                    np.logical_or,
                    [_nonfinite_mask(env, name, n) for name, _ in
                     self._result_plan],
                    np.zeros(n, dtype=bool),
                )
            ).tolist()
        )
        return out

    def score_env_prediction(self, env: dict, n: int):
        """Columnar bulk fast path: run the fused steps over a
        pre-built decode env and hand back the single-Prediction
        result as raw arrays ``(name, keys, stacked [n, k] float64)``
        instead of per-row dicts, so the bulk job can line-encode the
        output without ever materialising n python dicts.  None when
        the plan has any other result shape (or n == 0) - the caller
        falls back to :meth:`score_env`.  ``last_nonfinite_rows`` is
        set exactly as score_env would."""
        if self._single_prediction is None or n == 0:
            return None
        env = reduce(_apply_step, self._step_fns, env)
        name = self._single_prediction
        keys, stacked = _prediction_stack_arrays(env, name)
        self.last_nonfinite_rows = tuple(
            np.flatnonzero(_nonfinite_mask(env, name, n)).tolist())
        return name, keys, stacked

    def __call__(self, record: Mapping[str, Any]) -> dict[str, Any]:
        return self.score_batch([record])[0]


class PipelineCompiler:
    """Trace a fitted (stage, inputs, output) plan and fuse every
    lowered stage into one FusedPipeline, or raise FusionError naming
    the first stage/feature that cannot be compiled."""

    def __init__(self, steps: Sequence, raw_features: Sequence,
                 result_features: Sequence) -> None:
        self.steps = tuple(steps)
        self.raw_features = tuple(raw_features)
        self.result_features = tuple(result_features)

    def compile(self) -> FusedPipeline:
        raw_by_name = {f.name: f for f in self.raw_features}
        lowered = [
            (stage, out_name, self._lower_or_raise(stage))
            for stage, _, out_name in self.steps
        ]
        produced = {out_name for _, out_name, _ in lowered}
        # env-key granularity: a consumer's declared input (including
        # @mask companions) must be an env key some producer DECLARES,
        # not merely a feature name it is associated with - a producer
        # omitting a mask key must fail here, at compile time, not as a
        # KeyError on every serve-time batch
        produced_keys = {
            key for _, _, lw in lowered for key in lw.outputs
        }
        # _input_base_or_raise always returns a non-empty name (or
        # raises FusionError), so the walrus only binds - it never filters
        needed = {
            base
            for stage, _, lw in lowered
            for key in lw.inputs
            if key not in produced_keys
            and (base := self._input_base_or_raise(
                stage, key, produced, raw_by_name
            ))
        }
        # raw features served straight through as results must decode too
        needed |= {
            f.name for f in self.result_features if f.name not in produced
        }
        # numeric results assemble from value + @mask pairs: a stage-
        # produced numeric result must declare its mask key as well
        missing_masks = [
            f.name
            for f in self.result_features
            if f.ftype.kind == "numeric" and f.name in produced
            and f.name + MASK_SUFFIX not in produced_keys
        ]
        if missing_masks:
            raise FusionError(
                f"numeric result features {missing_masks} are produced "
                "without their @mask companion keys"
            )
        needed_raws = [self._raw_or_raise(raw_by_name, b) for b in
                       sorted(needed)]
        result_plan = [
            (f.name, self._assembler_or_raise(f, produced, raw_by_name))
            for f in self.result_features
        ]
        describe = [
            (stage.uid, stage.operation_name, lw.inputs, lw.outputs,
             dict(lw.signature))
            for stage, _, lw in lowered
        ]
        return FusedPipeline(
            decoder=RecordDecoder(needed_raws),
            step_fns=[lw.fn for _, _, lw in lowered],
            result_plan=result_plan,
            describe=describe,
        )

    @staticmethod
    def _input_base_or_raise(stage, key: str, produced: set,
                             raw_by_name: dict):
        """Resolve an undeclared-producer env input key to the raw
        feature it must decode from, or raise FusionError when the key
        can never exist at serve time."""
        base = (key[: -len(MASK_SUFFIX)]
                if key.endswith(MASK_SUFFIX) else key)
        if base in produced:
            raise FusionError(
                f"stage {stage.uid} consumes env key {key!r}, which "
                "its producing stage does not declare"
            )
        if base is not key and (
            base in raw_by_name
            and raw_by_name[base].ftype.kind != "numeric"
        ):
            raise FusionError(
                f"env mask key {key!r} requested for non-numeric raw "
                f"feature {base!r}"
            )
        return base

    @staticmethod
    def _lower_or_raise(stage):
        lw = stage.lower()
        if lw is None:
            raise FusionError(
                f"stage {stage.uid} ({type(stage).__name__}) does not "
                "lower to an array kernel"
            )
        return lw

    @staticmethod
    def _raw_or_raise(raw_by_name: dict, base: str):
        f = raw_by_name.get(base)
        if f is None:
            raise FusionError(
                f"fused program input {base!r} is neither a stage output "
                "nor a servable raw feature"
            )
        if f.ftype.kind not in DECODABLE_KINDS:
            raise FusionError(
                f"raw feature {f.name!r} has kind {f.ftype.kind!r}, which "
                "the fused decoder does not handle"
            )
        return f

    @staticmethod
    def _assembler_or_raise(f, produced: set, raw_by_name: dict):
        if f.name not in produced and f.name not in raw_by_name:
            raise FusionError(
                f"result feature {f.name!r} is not produced by any "
                "lowered stage"
            )
        fn = _ASSEMBLERS.get(f.ftype.kind)
        if fn is None:
            raise FusionError(
                f"result feature {f.name!r} has kind {f.ftype.kind!r}, "
                "which the fused path cannot assemble"
            )
        return fn


def compile_pipeline(steps, raw_features, result_features) -> FusedPipeline:
    """Fuse a fitted plan into one array program (raises FusionError
    when any stage, raw input, or result feature cannot be compiled)."""
    return PipelineCompiler(steps, raw_features, result_features).compile()
