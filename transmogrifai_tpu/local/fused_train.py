"""Fused training programs: one donate-buffers jit per fold x grid
dispatch, with AOT-cached training executables (ISSUE 15; ROADMAP item 3,
training half).

The serving half of the Flare-style fusion story (PRs 6/12) compiled the
FITTED pipeline; this module compiles the SELECTION hot path.  The
kernel-at-a-time dispatch in ``selector/validator.py`` runs, per family:
a ``jnp.asarray`` upload, one ``fit_arrays_batched``/grid-core dispatch
whose betas (or heaps) return to host, then k x g per-candidate predict
dispatches each shipping an [n_val, d] host slice to the device and the
scores back for host-side metrics - every drift-triggered refit pays
those round trips again.  Here each family's dispatch becomes a fused,
x64-windowed pipeline that keeps EVERY intermediate on device:

* the FIT PROGRAM - the tentpole jit: the family's whole fold x grid fit
  (batched Newton via the bitwise-fixed-point early-exit loop, or the
  grid x fold tree cores) traced as ONE program with ``donate_argnums``
  on the per-call fold-weight / stat / bootstrap buffers, so the Newton
  and tree-scan iterations reuse that device memory instead of doubling
  the working set.  This is the executable the AOT cache persists.
* per-candidate SCORE dispatches - each family's predict math over the
  eagerly-gathered per-fold validation rows (device buffer to device
  buffer); betas/heaps arrive as device buffers straight from the fit
  program.
* the METRIC PROGRAM - one jit computing the whole [k, g] metric matrix:
  exact rank metrics (one uint64 bit-pattern sort per candidate, tie-
  grouped trapezoid AuROC / step-area AuPR accumulated in f64 where
  every term is a half-integer < 2^53, so the sums are EXACT and match
  the host evaluator to final-division rounding ~1e-15) or the f64
  regression metrics.  Scores are donated into it.

Only the metric matrix and the family's betas return to host.

Why three executables and not literally one: on XLA:CPU the dot emitter
is sensitive to operand provenance - the SAME f32 matvec lowers
differently when its operand is an in-program value instead of a program
parameter, and unrolled per-candidate dots sharing one design matrix get
merged into a single matmul with a different accumulation order (both
measured here: up to ~8e-6 score drift, enough to move AUROC past the
1e-9 parity bar through rank flips).  Splitting at the betas/scores
boundaries keeps every dot's operands parameters, which is bit-equal to
the kernel-at-a-time dispatch - while the buffers still never leave the
device.  The metric program is provenance-proof (sort + exact integer
f64 sums), so it fuses freely.

Approx mode (the validator's 1024-bin TPU path) reuses the SAME
``_margins_kernel`` + ``masked_rank_metrics`` kernels the existing arm
dispatches, fed the fit program's device betas - bit-equal by
construction.

AOT executable cache (``train_xla_cache/`` next to ``autotune.json``):
warm refits - the successive-halving rungs of PR 13, item 2's future
drift-triggered refits, restarted trainers - must not pay retrace +
recompile per shape bucket.  Two tiers serve them:

* the in-process program registry: a long-lived refit loop re-dispatching
  the same (family, shape bucket, grid signature) skips trace AND
  compile entirely (``cache: memory``);
* the on-disk cache: jax's persistent compilation cache scoped to the
  ``train_xla_cache/`` directory (enabled only for the fused-program
  compile window, under the PR-12 process-wide config lock) - a fresh
  process re-traces but its ``compile()`` REHYDRATES the cached
  executable (``cache: hit``, the compile wall recorded as ``load_ms``)
  instead of re-optimizing.  A sidecar meta file per program -
  fingerprint = sha256(jax/jaxlib/backend + family + shape bucket +
  grid signature) - keeps the PR-12-style stale accounting: a runtime
  upgrade is a counted STALE retrace-and-recache, never a foreign
  executable (jax's own cache key enforces the never-foreign half).

Why not the literal PR-12 ``serialize_executable`` seam: measured on
jaxlib 0.4.36 CPU, a serialized executable containing LAPACK custom
calls (the Newton kernels' Cholesky solves) deserializes into a fresh
process and then SEGFAULTS at execution - from a clean producer process,
under both CPU runtimes - and the legacy runtime that PR 12 needed for
sound serving serialization both compiles ~20x slower and computes f32
matmuls with a different tiling (~2e-3 abs drift on a [20k, 39] Gram),
which would break the 1e-9 parity bar.  The persistent compilation
cache is the rehydration path jax actually supports for these programs:
one (default) runtime everywhere, so fused == existing stays bit-exact
in every configuration, warm included.

Shape buckets are EXACT shapes: zero-padding rows would change the
fit's f32 reductions and break the bit-parity contract, and refit loops
re-see the same shapes anyway.

Like the rest of local/, this module defers every jax import: importing
it (the validator does so lazily) must never initialize a backend.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import numpy as np

from .fused_xla import runtime_fingerprint

log = logging.getLogger("transmogrifai_tpu.local.fused_train")

TRAIN_CACHE_FORMAT_VERSION = 1

#: directory name of the on-disk executable cache, created next to
#: ``autotune.json`` (workflow/runner.py wires it)
TRAIN_CACHE_DIRNAME = "train_xla_cache"


class FusedTrainError(Exception):
    """A family's fold x grid dispatch cannot ride the fused programs;
    ``reason`` is the short machine-readable fallback reason the
    validator records (mirroring PR-6's ``fused_reason`` discipline)."""

    def __init__(self, reason: str, detail: str = "") -> None:
        super().__init__(detail or reason)
        self.reason = reason


def _jax():
    import jax

    return jax


def _x64():
    return _jax().experimental.enable_x64()


# ---------------------------------------------------------------------------
# Exact device rank / regression metrics
# ---------------------------------------------------------------------------
_ORD32_FLIP = 0x80000000
_ORD64_FLIP = 0x8000000000000000


def _ord62(scores):
    """Order-preserving 62-bit integer keys for a [B, m] score block.

    f32 scores map losslessly (32 ordered bits << 30).  f64 scores keep
    their top 62 ordered pattern bits: only values within 4 consecutive
    f64 patterns collide, which exact ties (the case that matters -
    saturated sigmoids, binary predictions) never are."""
    jnp = _jax().numpy
    lax = _jax().lax
    if scores.dtype == jnp.float64:
        bits = lax.bitcast_convert_type(scores, jnp.uint64)
        ordered = jnp.where(
            (bits >> 63) == 0, bits | jnp.uint64(_ORD64_FLIP), ~bits
        )
        return ordered >> 2
    bits = lax.bitcast_convert_type(scores.astype(jnp.float32), jnp.uint32)
    ordered = jnp.where(
        (bits >> 31) == 0, bits | jnp.uint32(_ORD32_FLIP), ~bits
    )
    return ordered.astype(jnp.uint64) << 30


def exact_rank_metrics(scores, yb, okb):
    """Exact AuROC + AuPR per candidate row, entirely on device.

    scores [B, m] (f32 or f64, higher = more positive), yb [B, m] f64
    labels in {0, 1}, okb [B, m] bool validity (False = gather padding).
    One uint64 sort per row: key = (valid << 63) | (ordered score bits
    << 1) | label, so invalid rows sink below every valid row and a
    single pass of cumulative sums over the descending order yields the
    tie-grouped trapezoid AuROC and the step-area AuPR - the same
    group-end formulas the host evaluator's ``_roc_pr_areas`` computes,
    term-for-term in f64 (each term is a half-integer < 2^53: the sums
    are exact)."""
    jnp = _jax().numpy
    lax = _jax().lax
    B, m = scores.shape
    key = (
        (okb.astype(jnp.uint64) << 63)
        | (_ord62(scores) << 1)
        | yb.astype(jnp.uint64)
    )
    skey = jnp.flip(lax.sort(key, dimension=1), axis=1)  # descending
    valid = ((skey >> 63) & jnp.uint64(1)).astype(jnp.float64)
    yy = (skey & jnp.uint64(1)).astype(jnp.float64)
    gkey = skey >> 1  # score bits + validity: tie groups
    tp = jnp.cumsum(yy * valid, axis=1)
    fp = jnp.cumsum((1.0 - yy) * valid, axis=1)
    iota = jnp.arange(m, dtype=jnp.int32)
    neq_prev = gkey[:, 1:] != gkey[:, :-1]
    is_start = jnp.concatenate(
        [jnp.ones((B, 1), bool), neq_prev], axis=1)
    is_end = jnp.concatenate(
        [neq_prev, jnp.ones((B, 1), bool)], axis=1)
    start_idx = lax.cummax(
        jnp.where(is_start, iota[None, :], 0), axis=1)
    prev = jnp.maximum(start_idx - 1, 0)
    tp_prev = jnp.where(
        start_idx > 0, jnp.take_along_axis(tp, prev, axis=1), 0.0)
    hp = tp - tp_prev
    fp_prev = jnp.where(
        start_idx > 0, jnp.take_along_axis(fp, prev, axis=1), 0.0)
    hn = fp - fp_prev
    P = tp[:, -1:]
    N = fp[:, -1:]
    endw = (is_end & (valid > 0)).astype(jnp.float64)
    auroc = (endw * hn * (tp_prev + 0.5 * hp)).sum(axis=1) / jnp.maximum(
        P * N, 1e-12
    )[:, 0]
    prec = tp / jnp.maximum(tp + fp, 1e-12)
    aupr = (endw * hp * prec).sum(axis=1) / jnp.maximum(P, 1e-12)[:, 0]
    has_both = ((P > 0) & (N > 0))[:, 0]
    return (
        jnp.where(has_both, auroc, 0.0),
        jnp.where(has_both, aupr, 0.0),
    )


def regression_metrics(pred, yb, okb, metric_name: str):
    """Per-candidate regression metric over gathered validation rows:
    the f64 mirror of evaluators/regression.OpRegressionEvaluator on
    (pred [B, m], yb [B, m]), padding masked by ``okb``."""
    jnp = _jax().numpy
    okd = okb.astype(jnp.float64)
    cnt = jnp.maximum(okd.sum(axis=1), 1.0)
    err = (yb - pred.astype(jnp.float64)) * okd
    sse = (err * err).sum(axis=1)
    if metric_name == "MeanSquaredError":
        return sse / cnt
    if metric_name == "RootMeanSquaredError":
        return jnp.sqrt(sse / cnt)
    if metric_name == "MeanAbsoluteError":
        return jnp.abs(err).sum(axis=1) / cnt
    if metric_name == "R2":
        ymean = (yb * okd).sum(axis=1, keepdims=True) / cnt[:, None]
        ss_tot = (((yb - ymean) ** 2) * okd).sum(axis=1)
        return jnp.where(ss_tot > 0, 1.0 - sse / ss_tot, 0.0)
    raise FusedTrainError("metric_unsupported", metric_name)


SUPPORTED_RANK_METRICS = ("AuROC", "AuPR")
SUPPORTED_REGRESSION_METRICS = (
    "RootMeanSquaredError", "MeanSquaredError", "MeanAbsoluteError", "R2",
)


def metric_kind(evaluator) -> Optional[tuple]:
    """(kind, metric_name) when the evaluator's default metric has an
    exact in-program implementation, else None.  Exact TYPE match: a
    subclass may override evaluate_arrays, and the fused metrics must
    mirror the implementation they claim parity with."""
    from ..evaluators.binary import OpBinaryClassificationEvaluator
    from ..evaluators.regression import OpRegressionEvaluator

    name = getattr(evaluator, "metric_name", None)
    if (type(evaluator) is OpBinaryClassificationEvaluator
            and name in SUPPORTED_RANK_METRICS):
        return ("rank", name)
    if (type(evaluator) is OpRegressionEvaluator
            and name in SUPPORTED_REGRESSION_METRICS):
        return ("regression", name)
    return None


def val_gather_plan(masks: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-fold validation-row index arrays from [k, n] train masks,
    padded to the widest fold: (val_idx [k, m] int32, val_ok [k, m]
    bool).  Padding indexes row 0 with ok=False - gathered but masked."""
    k = masks.shape[0]
    idxs = [np.nonzero(~masks[f])[0] for f in range(k)]
    m = max((len(i) for i in idxs), default=0)
    if m == 0:
        raise FusedTrainError("no_validation_rows")
    val_idx = np.zeros((k, m), np.int32)
    val_ok = np.zeros((k, m), bool)
    for f, i in enumerate(idxs):
        val_idx[f, : len(i)] = i
        val_ok[f, : len(i)] = True
    return val_idx, val_ok


# ---------------------------------------------------------------------------
# On-disk cache: jax persistent compilation cache + sidecar meta
# ---------------------------------------------------------------------------
#: sidecar meta filename suffix (distinguishes our records from jax's
#: own cache entries in the shared train_xla_cache/ directory)
_META_SUFFIX = ".txmeta.json"


class TrainExecutableCache:
    """The sidecar bookkeeping over a ``train_xla_cache/`` directory
    shared with jax's persistent compilation cache: one
    ``<fingerprint>.txmeta.json`` per fused program, written via the
    crash-consistent atomic byte writer in serialization/model_io.py.
    ``logical_key`` (the fingerprint minus runtime) lets a
    jax/jaxlib/backend upgrade be counted as STALE - the retrace
    replaces the record - while a never-seen program is a plain MISS.
    The executables themselves live in jax's cache entries (its key
    covers jax version/backend/flags, so a foreign executable can never
    rehydrate)."""

    def __init__(self, root: str) -> None:
        self.root = str(root)

    def _meta_path(self, fp: str) -> str:
        return os.path.join(self.root, fp + _META_SUFFIX)

    def has(self, fingerprint: str) -> bool:
        try:
            with open(self._meta_path(fingerprint)) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return False
        return meta.get("format_version") == TRAIN_CACHE_FORMAT_VERSION

    def has_stale_sibling(self, fingerprint: str, logical_key: str) -> bool:
        """A record exists for this program under a DIFFERENT
        fingerprint (new jax/jaxlib/backend): the retrace that follows
        is a counted 'stale', not a cold 'miss'."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return False
        for name in names:
            if (not name.endswith(_META_SUFFIX)
                    or name == fingerprint + _META_SUFFIX):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                continue
            if meta.get("logical_key") == logical_key:
                return True
        return False

    def store(self, fingerprint: str, logical_key: str,
              extra: dict) -> None:
        """Best-effort atomic record; superseded same-logical-key
        records are reaped so a long-lived cache dir holds one record
        per live program."""
        from ..serialization.model_io import write_bytes_atomic

        try:
            names = [n for n in os.listdir(self.root)
                     if n.endswith(_META_SUFFIX)]
        except OSError:
            names = []
        meta = {
            "format_version": TRAIN_CACHE_FORMAT_VERSION,
            "fingerprint": fingerprint,
            "logical_key": logical_key,
            "runtime": runtime_fingerprint(),
        }
        meta.update(extra)
        try:
            write_bytes_atomic(
                self._meta_path(fingerprint),
                json.dumps(meta, sort_keys=True).encode("utf-8"),
            )
        except OSError as e:
            log.warning("could not store train cache record %s: %s",
                        fingerprint, e)
            return
        for name in names:
            if name == fingerprint + _META_SUFFIX:
                continue
            p = os.path.join(self.root, name)
            try:
                with open(p) as f:
                    if json.load(f).get("logical_key") != logical_key:
                        continue
                os.remove(p)
            except (OSError, ValueError):
                continue


def _compile_program(lowered, cache_dir: Optional[str]):
    """Compile a lowered fused program, through jax's persistent
    compilation cache when a cache dir is configured: the config toggle
    window is process-wide state, so it runs under the SAME lock the
    PR-12 serving compiles use (fused_xla._COMPILE_CACHE_LOCK) - the
    serving AOT path needs the cache OFF for its window, this path
    needs it ON, and interleaving would corrupt both.  Returns
    (executable, compile_ms, disk_hit: Optional[bool]) where disk_hit
    is None without a cache dir, else whether the compile rehydrated an
    existing entry (no cache files appeared or changed).  The hit
    heuristic is directory-level: a CONCURRENT writer landing its own
    cold entry in a shared cache dir during this window under-counts a
    genuine rehydration as a miss - the hit/miss counters are
    observability, never a correctness input, so an under-count costs
    one report line, not an executable."""
    jax = _jax()
    import time as _time

    if cache_dir is None:
        t0 = _time.perf_counter()
        exe = lowered.compile()
        return exe, (_time.perf_counter() - t0) * 1e3, None
    from jax.experimental.compilation_cache import (
        compilation_cache as _jax_cc,
    )

    from .fused_xla import _COMPILE_CACHE_LOCK

    os.makedirs(cache_dir, exist_ok=True)
    with _COMPILE_CACHE_LOCK:
        cfg = jax.config
        old = (
            cfg.jax_enable_compilation_cache,
            cfg.jax_compilation_cache_dir,
            cfg.jax_persistent_cache_min_compile_time_secs,
            cfg.jax_persistent_cache_min_entry_size_bytes,
        )
        def _entries():
            # (name, size, mtime_ns) so a corrupt entry jax silently
            # rewrites in place reads as a MISS, not a hit; the -atime
            # marker files are touched on every cache READ, so they
            # must not count as writes
            out = set()
            for n in os.listdir(cache_dir):
                if n.endswith(_META_SUFFIX) or n.endswith("-atime"):
                    continue
                try:
                    st = os.stat(os.path.join(cache_dir, n))
                except OSError:
                    continue
                out.add((n, st.st_size, st.st_mtime_ns))
            return out

        before = _entries()
        try:
            jax.config.update("jax_enable_compilation_cache", True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # the fused-program compiles are sub-second: jax's default
            # 1s floor would silently skip caching exactly the
            # executables this cache exists for
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0)
            # the cache backend memoizes the directory it was first
            # initialized with (usually None): drop it so this window's
            # dir takes effect, and again on exit so later compiles
            # don't keep writing here
            _jax_cc.reset_cache()
            t0 = _time.perf_counter()
            try:
                exe = lowered.compile()
            except Exception as e:  # noqa: BLE001 - a damaged cache
                # entry must degrade to a plain compile, never kill
                # the dispatch
                log.warning(
                    "cached-compile failed (%s: %s); recompiling "
                    "without the cache", type(e).__name__, e,
                )
                jax.config.update("jax_enable_compilation_cache", False)
                t0 = _time.perf_counter()
                exe = lowered.compile()
            compile_ms = (_time.perf_counter() - t0) * 1e3
        finally:
            jax.config.update("jax_enable_compilation_cache", old[0])
            jax.config.update("jax_compilation_cache_dir", old[1])
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", old[2])
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", old[3])
            _jax_cc.reset_cache()
        after = _entries()
    return exe, compile_ms, after == before and bool(before)


# ---------------------------------------------------------------------------
# Program registry: trace/compile once per (family, shape bucket)
# ---------------------------------------------------------------------------
@dataclass
class _Program:
    exe: Any
    n_outputs: int
    stats: dict = field(default_factory=dict)


_PROGRAMS: dict[str, _Program] = {}
_PROGRAMS_LOCK = threading.Lock()
_MAX_PROGRAMS = 32


@dataclass
class FusedDispatchResult:
    """What one fused family dispatch hands back to the validator."""

    metrics: np.ndarray  # [k, g] float64, metric per (fold, candidate)
    betas: Optional[np.ndarray]
    b0s: Optional[np.ndarray]
    report: dict


def fingerprint_for(sig: Sequence) -> tuple[str, str]:
    """(fingerprint, logical_key): sha256 over runtime + program
    signature, and the runtime-free logical identity used for stale
    accounting."""
    logical = json.dumps(
        {"format": TRAIN_CACHE_FORMAT_VERSION, "sig": list(sig)},
        sort_keys=True, default=str,
    )
    doc = json.dumps(
        {"logical": logical, "runtime": runtime_fingerprint()},
        sort_keys=True,
    )
    return (
        hashlib.sha256(doc.encode("utf-8")).hexdigest(),
        hashlib.sha256(logical.encode("utf-8")).hexdigest(),
    )


def _counters():
    from ..obs.metrics import metrics_registry

    return metrics_registry()


def _get_program(sig: Sequence, build_fn: Callable[[], Any],
                 arg_specs: Sequence, donate: Sequence[int],
                 n_outputs: int,
                 cache_dir: Optional[str]) -> tuple[_Program, dict]:
    """The compiled executable for ``sig``, via (in order): the
    in-process registry (``memory`` - trace and compile both skipped),
    or trace + compile, where a configured cache dir routes the compile
    through jax's persistent compilation cache: a rehydrated entry is a
    counted HIT (compile wall recorded as load_ms), a never-seen
    program a MISS, and a known program whose runtime fingerprint
    changed a counted STALE retrace-and-recache."""
    jax = _jax()
    fp, logical = fingerprint_for(sig)
    # the in-process registry is keyed per cache dir: a program first
    # compiled WITHOUT a cache dir must not be served as a memory hit
    # once the operator configures train_xla_cache/ - the recompile is
    # what persists the executable for the next process
    reg_key = f"{fp}|{cache_dir or ''}"
    with _PROGRAMS_LOCK:
        prog = _PROGRAMS.get(reg_key)
    if prog is not None:
        return prog, {"cache": "memory", "fingerprint": fp}
    reg = _counters()
    event = {"fingerprint": fp}
    cache = TrainExecutableCache(cache_dir) if cache_dir else None
    stats = {"trace_ms": 0.0, "compile_ms": 0.0, "load_ms": 0.0,
             "cache_hit": 0}
    known = cache is not None and cache.has(fp)
    program = build_fn()
    with _x64():
        t0 = time.perf_counter()
        with warnings.catch_warnings():
            # CPU XLA has no output buffer shaped like the donated
            # fold-weight block to alias, so it warns the donation is
            # unusable there; the donation is deliberate (it pays on
            # backends with aliasable layouts) and the warning would
            # otherwise fire once per compile
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not "
                "usable",
            )
            lowered = jax.jit(
                program, donate_argnums=tuple(donate)
            ).lower(*arg_specs)
        t1 = time.perf_counter()
        exe, compile_ms, disk_hit = _compile_program(lowered, cache_dir)
    stats["trace_ms"] = round((t1 - t0) * 1e3, 3)
    if known and disk_hit:
        # the compile call rehydrated the cached executable: that wall
        # IS the load
        stats["load_ms"] = round(compile_ms, 3)
        stats["cache_hit"] = 1
        event["cache"] = "hit"
        reg.counter(
            "train_fused.cache_hits",
            help="fused training executables rehydrated from the AOT "
                 "compile cache instead of re-optimized",
        ).inc()
    else:
        stats["compile_ms"] = round(compile_ms, 3)
        stale = (cache is not None
                 and cache.has_stale_sibling(fp, logical))
        event["cache"] = "stale" if stale else "miss"
        reg.counter(
            "train_fused.cache_stale" if stale
            else "train_fused.cache_misses",
            help="fused training programs re-optimized because the "
                 "cached record's fingerprint no longer matches"
            if stale else
            "fused training programs compiled cold (no cache entry)",
        ).inc()
        if cache is not None:
            cache.store(fp, logical, {"sig": list(sig)})
    prog = _Program(exe=exe, n_outputs=n_outputs, stats=stats)
    with _PROGRAMS_LOCK:
        if len(_PROGRAMS) >= _MAX_PROGRAMS:
            _PROGRAMS.pop(next(iter(_PROGRAMS)))
        _PROGRAMS[reg_key] = prog
    event.update(stats)
    return prog, event


def reset_program_registry() -> None:
    """Drop every in-process compiled program (tests / cache drills):
    the next dispatch goes back through the on-disk AOT cache."""
    with _PROGRAMS_LOCK:
        _PROGRAMS.clear()


def _merge_events(*events: dict) -> dict:
    """One report entry from the fit/metric program events: cache state
    keyed by the FIT program (the expensive executable), timing summed."""
    out = dict(events[0])
    for e in events[1:]:
        for key in ("trace_ms", "compile_ms", "load_ms", "exec_ms"):
            if key in e:
                out[key] = round(out.get(key, 0.0) + e[key], 3)
    return out


def _run_metric_program(scores, y_folds, val_ok, g: int, mkind: str,
                        mname: str,
                        cache_dir: Optional[str]) -> tuple:
    """The [k, g] metric matrix from fold-major stacked scores
    [k*g, m]: builds/loads the shared metric program (family-agnostic -
    one per (metric, shapes, dtype) bucket) and donates the score block
    into it."""
    jax = _jax()
    jnp = jax.numpy
    B, m = int(scores.shape[0]), int(scores.shape[1])
    k = B // g
    sig = ("metric", mkind, mname, str(scores.dtype), B, m, g)

    def build():
        def program(sc, yf, ok):
            yb = jnp.repeat(yf, g, axis=0)       # [k*g, m]
            okb = jnp.repeat(ok, g, axis=0)
            if mkind == "rank":
                auroc, aupr = exact_rank_metrics(sc, yb, okb)
                vals = auroc if mname == "AuROC" else aupr
            else:
                vals = regression_metrics(sc, yb, okb, mname)
            return (vals.reshape(k, g).astype(jnp.float64),)

        return program

    args = (scores, y_folds, val_ok)
    specs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args)
    prog, event = _get_program(
        sig, build, specs, donate=(0,), n_outputs=1,
        cache_dir=cache_dir)
    with _x64():
        (metrics,) = prog.exe(*args)
        metrics = np.asarray(metrics)
    return metrics, event


# ---------------------------------------------------------------------------
# Linear families
# ---------------------------------------------------------------------------
def run_linear(
    est,
    X,
    y: np.ndarray,
    masks: np.ndarray,
    w: np.ndarray,
    weights_given: bool,
    regs: np.ndarray,
    ens: np.ndarray,
    g: int,
    evaluator,
    mode: str,
    cache_dir: Optional[str] = None,
) -> FusedDispatchResult:
    """One fused dispatch for a batched linear family (LR / linear SVC /
    linear regression): returns the [k, g] metric matrix + betas, or
    raises :class:`FusedTrainError` with the fallback reason.

    ``X`` may be the validator's hoisted device buffer (shared across
    families - it is NOT donated); the [B, n] fold-weight block this
    call builds IS donated into the fit program and never touched
    again."""
    jax = _jax()
    jnp = jax.numpy
    kind = metric_kind(evaluator)
    if kind is None:
        raise FusedTrainError(
            "evaluator_unsupported", type(evaluator).__name__)
    mkind, mname = kind
    if mode == "approx" and mkind != "rank":
        raise FusedTrainError("approx_needs_rank_metric")
    if not hasattr(est, "fused_train_core"):
        raise FusedTrainError("family_unsupported", est.model_type)
    from ..models.packed_newton import use_packed

    k, n = masks.shape
    packed = bool(use_packed(X))
    core = est.fused_train_core(packed)
    d = int(X.shape[1])
    sig = (
        "linear-fit", est.model_type, tuple(core.get("sig", ())),
        int(n), int(d), int(k), int(g), bool(weights_given),
    )

    def build():
        fit_fn = core["fit"]

        def program(Xd, y32, W, regs_d, ens_d):
            return fit_fn(Xd, y32, W, regs_d, ens_d)

        return program

    # per-call device buffers; W is DONATED (arg index 2) and must never
    # be read after the dispatch - the donation-safety test pins this
    Xd = jnp.asarray(X, jnp.float32)
    y32 = jnp.asarray(np.asarray(y), jnp.float32)
    trainj = jnp.asarray(masks).astype(jnp.float32)
    if not weights_given:
        W = jnp.repeat(trainj, g, axis=0)
    else:
        wj = jnp.asarray(w, jnp.float32)
        W = jnp.repeat(trainj * wj[None, :], g, axis=0)
    regs_d = jnp.asarray(np.asarray(regs, np.float32))
    ens_d = jnp.asarray(np.asarray(ens, np.float32))
    args = (Xd, y32, W, regs_d, ens_d)
    specs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args)
    prog, fit_event = _get_program(
        sig, build, specs, donate=(2,), n_outputs=2,
        cache_dir=cache_dir)
    t0 = time.perf_counter()
    with _x64():
        betas_d, b0s_d = prog.exe(*args)
    if mode == "approx":
        # the existing approx arm's own kernels, fed the fit program's
        # device betas: bit-equal to that arm by construction
        from ..evaluators.binary import masked_rank_metrics
        from ..selector.validator import _margins_kernel

        scores = _margins_kernel(
            Xd, jnp.asarray(betas_d, jnp.float32),
            jnp.asarray(b0s_d, jnp.float32),
        ).T
        vmask = jnp.repeat(1.0 - trainj, g, axis=0)
        auroc_b, aupr_b = masked_rank_metrics(scores, y32, vmask)
        vals = auroc_b if mname == "AuROC" else aupr_b
        metrics = np.asarray(vals, np.float64).reshape(k, g)
        met_event: dict = {}
    else:
        val_idx, val_ok = val_gather_plan(masks)
        score_fn = core["score"]
        with _x64():
            # one jitted score kernel per family, reused across the
            # k x g candidates.  The fold's validation rows are gathered
            # EAGERLY (device buffer -> device buffer, a pure copy), so
            # the kernel sees exactly the [m, d] operand shape and
            # buffer contents the per-candidate dispatch jits - the same
            # jaxpr on the same buffers is bitwise-deterministic, where
            # a fused in-program gather or a full-matrix matvec picks a
            # different dot emitter (module docstring); betas stay
            # device-resident slices
            score_jit = jax.jit(score_fn)
            vidx_d = jnp.asarray(val_idx)
            rows = []
            for f in range(k):
                Xv = Xd[vidx_d[f]]
                for j in range(g):
                    b = f * g + j
                    rows.append(score_jit(Xv, betas_d[b], b0s_d[b]))
            scores = jnp.stack(rows)  # [k*g, m] fold-major
            y_folds = jnp.asarray(np.asarray(y, np.float64))[vidx_d]
        metrics, met_event = _run_metric_program(
            scores, y_folds, jnp.asarray(val_ok), g, mkind, mname,
            cache_dir)
    out_betas = np.asarray(betas_d)
    out_b0s = np.asarray(b0s_d)
    event = _merge_events(fit_event, met_event) if met_event else fit_event
    event["exec_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
    _counters().counter(
        "train_fused.dispatches",
        help="family fold x grid dispatches that ran as fused "
             "programs",
    ).inc()
    return FusedDispatchResult(
        metrics=metrics, betas=out_betas, b0s=out_b0s,
        report=dict(event, backend="fused", mode=mode,
                    bucket=f"n={n},d={d},k={k},g={g}"),
    )


# ---------------------------------------------------------------------------
# Tree families
# ---------------------------------------------------------------------------
def run_tree(
    est,
    X: np.ndarray,
    y: np.ndarray,
    masks: np.ndarray,
    W: np.ndarray,
    grid: Sequence[dict],
    evaluator,
    cache_dir: Optional[str] = None,
) -> FusedDispatchResult:
    """One fused dispatch for a tree family (random forest / GBT): the
    whole grid x fold fit as ONE donated-buffers program (heaps stay on
    device), per-candidate traversal scoring over the once-gathered
    validation bins, and the shared metric program.  Raises
    :class:`FusedTrainError` with the fallback reason (native backend,
    chunked dispatch, multiple shape groups...)."""
    jax = _jax()
    jnp = jax.numpy
    kind = metric_kind(evaluator)
    if kind is None:
        raise FusedTrainError(
            "evaluator_unsupported", type(evaluator).__name__)
    mkind, mname = kind
    if not hasattr(est, "fused_tree_plan"):
        raise FusedTrainError("family_unsupported", est.model_type)
    try:
        plan = est.fused_tree_plan(X, y, W, list(grid))
    except ValueError as e:
        raise FusedTrainError(str(e) or "tree_plan_rejected") from e
    k, n = masks.shape
    G = len(grid)
    val_idx, val_ok = val_gather_plan(masks)
    names = list(plan["arrays"])
    donate_idx = tuple(
        names.index(nm) for nm in plan.get("donate", ()) if nm in names
    )
    sig = (
        "tree-fit", est.model_type, tuple(plan["sig"]),
        int(n), int(X.shape[1]), int(k), int(G),
    )
    n_state = int(plan["n_state"])

    def build():
        fit_fn = plan["fit"]

        def program(*flat):
            return tuple(fit_fn(dict(zip(names, flat))))

        return program

    args = tuple(jnp.asarray(plan["arrays"][nm]) for nm in names)
    specs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args)
    prog, fit_event = _get_program(
        sig, build, specs, donate=donate_idx, n_outputs=n_state,
        cache_dir=cache_dir)
    bins_all = args[names.index(plan["bins_key"])]
    t0 = time.perf_counter()
    with _x64():
        state = prog.exe(*args)
        vidx_d = jnp.asarray(val_idx)
        rows = []
        for f in range(k):
            # ONE validation-bins gather per fold (pure integer
            # movement - exactly the bin values the per-candidate
            # re-binning of the existing path produces), then the
            # family's predict mirror per candidate with every operand
            # a device buffer
            bins_v = bins_all[vidx_d[f]]
            for gi in range(G):
                rows.append(plan["score"](state, bins_v, f, gi))
        scores = jnp.stack([jnp.asarray(r) for r in rows])
        y_folds = jnp.asarray(np.asarray(y, np.float64))[vidx_d]
    metrics, met_event = _run_metric_program(
        scores, y_folds, jnp.asarray(val_ok), G, mkind, mname,
        cache_dir)
    event = _merge_events(fit_event, met_event)
    event["exec_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
    _counters().counter(
        "train_fused.dispatches",
        help="family fold x grid dispatches that ran as fused "
             "programs",
    ).inc()
    return FusedDispatchResult(
        metrics=metrics, betas=None, b0s=None,
        report=dict(event, backend="fused", mode="exact",
                    bucket=f"n={n},d={int(X.shape[1])},k={k},g={G}"),
    )
