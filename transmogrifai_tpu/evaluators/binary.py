"""Binary classification evaluation.

Counterpart of OpBinaryClassificationEvaluator / OpBinScoreEvaluator
(reference: core/.../evaluators/OpBinaryClassificationEvaluator.scala:56-113,
OpBinScoreEvaluator.scala): AuROC/AuPR by rank statistics over sorted
scores (the mllib BinaryClassificationMetrics analog), confusion counts at
the 0.5 prediction, and bin-calibration (Brier score per score bin).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..types.columns import PredictionColumn
from .base import EvaluationMetrics, OpEvaluatorBase


_N_BINS = 1024  # threshold groups (mllib BinaryClassificationMetrics bins
_HI = 32        # at ~1000 thresholds for big data the same way); 1024 =
_LO = 32        # 32x32 so the histogram is one outer-product matmul


@jax.jit
def _masked_rank_metrics_kernel(scores, y, w):
    """Batched AuROC + AuPR entirely on device: scores [B, n] (higher =
    more positive), y [n] in {0,1}, w [B, n] 0/1 validation-row masks.

    Sort-free and scatter-free (both are pathologically slow TPU
    primitives at [B, n] scale): scores quantize to 1024 threshold bins
    whose index splits into hi/lo digits, so each candidate's score
    histogram is ONE [n, 32]^T @ [n, 32] outer-product matmul on the MXU.
    AuROC is the trapezoid over the binned ROC (identical to the host
    evaluator's tie-grouped _roc_pr_areas when binning is lossless) and
    AuPR the step-wise area the same way.  Built so CV fan-outs never ship
    per-fold matrix slices back to the host."""
    smin = scores.min(axis=1, keepdims=True)
    smax = scores.max(axis=1, keepdims=True)
    span = jnp.maximum(smax - smin, 1e-12)
    idx = jnp.clip(
        jnp.floor((scores - smin) / span * (_N_BINS - 1) + 0.5).astype(
            jnp.int32
        ),
        0, _N_BINS - 1,
    )
    hi = idx // _LO
    lo = idx % _LO
    hi_iota = jnp.arange(_HI, dtype=jnp.int32)
    lo_iota = jnp.arange(_LO, dtype=jnp.int32)
    wpos = w * y[None, :]
    wneg = w * (1.0 - y[None, :])

    def hists_of(args):
        hi_r, lo_r, wp, wn = args
        oh_hi = (hi_r[:, None] == hi_iota[None, :]).astype(jnp.float32)
        oh_lo = (lo_r[:, None] == lo_iota[None, :]).astype(jnp.float32)
        hp = (oh_hi * wp[:, None]).T @ oh_lo   # [32, 32] -> 1024 bins
        hn = (oh_hi * wn[:, None]).T @ oh_lo
        return hp.reshape(-1), hn.reshape(-1)

    hp, hn = jax.lax.map(hists_of, (hi, lo, wpos, wneg))  # [B, 1024] asc
    hp = hp[:, ::-1]  # descending score order
    hn = hn[:, ::-1]
    P = hp.sum(axis=1)
    N = hn.sum(axis=1)
    cum_p = jnp.cumsum(hp, axis=1)          # inclusive
    cum_n = jnp.cumsum(hn, axis=1)
    cum_p_excl = cum_p - hp
    denom = jnp.maximum(P * N, 1e-12)[:, None]
    auroc = ((hn * (cum_p_excl + 0.5 * hp)) / denom).sum(axis=1)
    prec = cum_p / jnp.maximum(cum_p + cum_n, 1e-12)
    aupr = (hp * prec).sum(axis=1) / jnp.maximum(P, 1e-12)
    return auroc, aupr


def masked_rank_metrics(scores, y, val_masks):
    """Device wrapper: returns (auroc [B], aupr [B]) numpy arrays for B
    candidates evaluated on their masked validation rows.  Metrics are
    1024-threshold-binned (error O(1/1024) vs the exact host evaluator)."""
    a, p = _masked_rank_metrics_kernel(
        jnp.asarray(scores, jnp.float32),
        jnp.asarray(y, jnp.float32),
        jnp.asarray(val_masks, jnp.float32),
    )
    return np.asarray(a, np.float64), np.asarray(p, np.float64)


def _roc_pr_areas(y: np.ndarray, score: np.ndarray) -> tuple[float, float]:
    """AuROC + AuPR from score ranking, ties handled by threshold grouping
    (trapezoidal ROC, step-wise PR like mllib)."""
    order = np.argsort(-score, kind="stable")
    y_sorted = y[order]
    s_sorted = score[order]
    # group ties: cum counts at each distinct threshold
    distinct = np.nonzero(np.diff(s_sorted))[0]
    idx = np.concatenate([distinct, [len(s_sorted) - 1]])
    tp = np.cumsum(y_sorted)[idx]
    fp = (idx + 1) - tp
    P = y.sum()
    N = len(y) - P
    if P == 0 or N == 0:
        return 0.0, 0.0
    tpr = np.concatenate([[0.0], tp / P])
    fpr = np.concatenate([[0.0], fp / N])
    auroc = float(np.trapezoid(tpr, fpr))
    precision = np.concatenate([[1.0], tp / (tp + fp)])
    recall = np.concatenate([[0.0], tp / P])
    aupr = float(np.sum(np.diff(recall) * precision[1:]))
    return auroc, aupr


@dataclass
class BinaryClassificationMetrics(EvaluationMetrics):
    AuROC: float = 0.0
    AuPR: float = 0.0
    Precision: float = 0.0
    Recall: float = 0.0
    F1: float = 0.0
    Error: float = 0.0
    TP: float = 0.0
    TN: float = 0.0
    FP: float = 0.0
    FN: float = 0.0
    thresholds: list = field(default_factory=list)
    precision_by_threshold: list = field(default_factory=list)
    recall_by_threshold: list = field(default_factory=list)


class OpBinaryClassificationEvaluator(OpEvaluatorBase):
    metric_name = "AuROC"
    larger_better = True

    def __init__(self, num_thresholds: int = 100) -> None:
        self.num_thresholds = num_thresholds

    def evaluate_arrays(self, y, pred: PredictionColumn):
        score = (
            pred.probability[:, 1]
            if pred.probability is not None and pred.probability.shape[1] > 1
            else pred.prediction
        )
        yhat = pred.prediction
        auroc, aupr = _roc_pr_areas(y, score)
        tp = float(((yhat == 1) & (y == 1)).sum())
        tn = float(((yhat == 0) & (y == 0)).sum())
        fp = float(((yhat == 1) & (y == 0)).sum())
        fn = float(((yhat == 0) & (y == 1)).sum())
        precision = tp / (tp + fp) if tp + fp > 0 else 0.0
        recall = tp / (tp + fn) if tp + fn > 0 else 0.0
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall > 0
            else 0.0
        )
        error = (fp + fn) / max(len(y), 1)
        ths = np.linspace(0.0, 1.0, self.num_thresholds + 1)
        p_by, r_by = [], []
        P = y.sum()
        for t in ths:
            yh = (score >= t).astype(np.float64)
            tpt = float(((yh == 1) & (y == 1)).sum())
            fpt = float(((yh == 1) & (y == 0)).sum())
            p_by.append(tpt / (tpt + fpt) if tpt + fpt > 0 else 1.0)
            r_by.append(tpt / P if P > 0 else 0.0)
        return BinaryClassificationMetrics(
            AuROC=auroc, AuPR=aupr, Precision=precision, Recall=recall,
            F1=f1, Error=error, TP=tp, TN=tn, FP=fp, FN=fn,
            thresholds=ths.tolist(),
            precision_by_threshold=p_by, recall_by_threshold=r_by,
        )


@dataclass
class BinScoreMetrics(EvaluationMetrics):
    bin_centers: list = field(default_factory=list)
    n_per_bin: list = field(default_factory=list)
    avg_score_per_bin: list = field(default_factory=list)
    avg_label_per_bin: list = field(default_factory=list)
    brier_score: float = 0.0


class OpBinScoreEvaluator(OpEvaluatorBase):
    """Score-bin calibration (reference: OpBinScoreEvaluator.scala)."""

    metric_name = "brier_score"
    larger_better = False

    def __init__(self, num_bins: int = 100) -> None:
        self.num_bins = num_bins

    def evaluate_arrays(self, y, pred: PredictionColumn):
        score = (
            pred.probability[:, 1]
            if pred.probability is not None and pred.probability.shape[1] > 1
            else pred.prediction
        )
        edges = np.linspace(0.0, 1.0, self.num_bins + 1)
        which = np.clip(np.digitize(score, edges) - 1, 0, self.num_bins - 1)
        centers, counts, avg_s, avg_y = [], [], [], []
        for b in range(self.num_bins):
            m = which == b
            centers.append(float((edges[b] + edges[b + 1]) / 2))
            counts.append(int(m.sum()))
            avg_s.append(float(score[m].mean()) if m.any() else 0.0)
            avg_y.append(float(y[m].mean()) if m.any() else 0.0)
        brier = float(np.mean((score - y) ** 2))
        return BinScoreMetrics(
            bin_centers=centers, n_per_bin=counts,
            avg_score_per_bin=avg_s, avg_label_per_bin=avg_y,
            brier_score=brier,
        )
