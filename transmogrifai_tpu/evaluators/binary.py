"""Binary classification evaluation.

Counterpart of OpBinaryClassificationEvaluator / OpBinScoreEvaluator
(reference: core/.../evaluators/OpBinaryClassificationEvaluator.scala:56-113,
OpBinScoreEvaluator.scala): AuROC/AuPR by rank statistics over sorted
scores (the mllib BinaryClassificationMetrics analog), confusion counts at
the 0.5 prediction, and bin-calibration (Brier score per score bin).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..types.columns import PredictionColumn
from .base import EvaluationMetrics, OpEvaluatorBase


def _roc_pr_areas(y: np.ndarray, score: np.ndarray) -> tuple[float, float]:
    """AuROC + AuPR from score ranking, ties handled by threshold grouping
    (trapezoidal ROC, step-wise PR like mllib)."""
    order = np.argsort(-score, kind="stable")
    y_sorted = y[order]
    s_sorted = score[order]
    # group ties: cum counts at each distinct threshold
    distinct = np.nonzero(np.diff(s_sorted))[0]
    idx = np.concatenate([distinct, [len(s_sorted) - 1]])
    tp = np.cumsum(y_sorted)[idx]
    fp = (idx + 1) - tp
    P = y.sum()
    N = len(y) - P
    if P == 0 or N == 0:
        return 0.0, 0.0
    tpr = np.concatenate([[0.0], tp / P])
    fpr = np.concatenate([[0.0], fp / N])
    auroc = float(np.trapezoid(tpr, fpr))
    precision = np.concatenate([[1.0], tp / (tp + fp)])
    recall = np.concatenate([[0.0], tp / P])
    aupr = float(np.sum(np.diff(recall) * precision[1:]))
    return auroc, aupr


@dataclass
class BinaryClassificationMetrics(EvaluationMetrics):
    AuROC: float = 0.0
    AuPR: float = 0.0
    Precision: float = 0.0
    Recall: float = 0.0
    F1: float = 0.0
    Error: float = 0.0
    TP: float = 0.0
    TN: float = 0.0
    FP: float = 0.0
    FN: float = 0.0
    thresholds: list = field(default_factory=list)
    precision_by_threshold: list = field(default_factory=list)
    recall_by_threshold: list = field(default_factory=list)


class OpBinaryClassificationEvaluator(OpEvaluatorBase):
    metric_name = "AuROC"
    larger_better = True

    def __init__(self, num_thresholds: int = 100) -> None:
        self.num_thresholds = num_thresholds

    def evaluate_arrays(self, y, pred: PredictionColumn):
        score = (
            pred.probability[:, 1]
            if pred.probability is not None and pred.probability.shape[1] > 1
            else pred.prediction
        )
        yhat = pred.prediction
        auroc, aupr = _roc_pr_areas(y, score)
        tp = float(((yhat == 1) & (y == 1)).sum())
        tn = float(((yhat == 0) & (y == 0)).sum())
        fp = float(((yhat == 1) & (y == 0)).sum())
        fn = float(((yhat == 0) & (y == 1)).sum())
        precision = tp / (tp + fp) if tp + fp > 0 else 0.0
        recall = tp / (tp + fn) if tp + fn > 0 else 0.0
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall > 0
            else 0.0
        )
        error = (fp + fn) / max(len(y), 1)
        ths = np.linspace(0.0, 1.0, self.num_thresholds + 1)
        p_by, r_by = [], []
        P = y.sum()
        for t in ths:
            yh = (score >= t).astype(np.float64)
            tpt = float(((yh == 1) & (y == 1)).sum())
            fpt = float(((yh == 1) & (y == 0)).sum())
            p_by.append(tpt / (tpt + fpt) if tpt + fpt > 0 else 1.0)
            r_by.append(tpt / P if P > 0 else 0.0)
        return BinaryClassificationMetrics(
            AuROC=auroc, AuPR=aupr, Precision=precision, Recall=recall,
            F1=f1, Error=error, TP=tp, TN=tn, FP=fp, FN=fn,
            thresholds=ths.tolist(),
            precision_by_threshold=p_by, recall_by_threshold=r_by,
        )


@dataclass
class BinScoreMetrics(EvaluationMetrics):
    bin_centers: list = field(default_factory=list)
    n_per_bin: list = field(default_factory=list)
    avg_score_per_bin: list = field(default_factory=list)
    avg_label_per_bin: list = field(default_factory=list)
    brier_score: float = 0.0


class OpBinScoreEvaluator(OpEvaluatorBase):
    """Score-bin calibration (reference: OpBinScoreEvaluator.scala)."""

    metric_name = "brier_score"
    larger_better = False

    def __init__(self, num_bins: int = 100) -> None:
        self.num_bins = num_bins

    def evaluate_arrays(self, y, pred: PredictionColumn):
        score = (
            pred.probability[:, 1]
            if pred.probability is not None and pred.probability.shape[1] > 1
            else pred.prediction
        )
        edges = np.linspace(0.0, 1.0, self.num_bins + 1)
        which = np.clip(np.digitize(score, edges) - 1, 0, self.num_bins - 1)
        centers, counts, avg_s, avg_y = [], [], [], []
        for b in range(self.num_bins):
            m = which == b
            centers.append(float((edges[b] + edges[b + 1]) / 2))
            counts.append(int(m.sum()))
            avg_s.append(float(score[m].mean()) if m.any() else 0.0)
            avg_y.append(float(y[m].mean()) if m.any() else 0.0)
        brier = float(np.mean((score - y) ** 2))
        return BinScoreMetrics(
            bin_centers=centers, n_per_bin=counts,
            avg_score_per_bin=avg_s, avg_label_per_bin=avg_y,
            brier_score=brier,
        )
