"""Evaluator base + factory.

Counterpart of OpEvaluatorBase / Evaluators factory (reference: core/.../
evaluators/Evaluators.scala:40-260, OpEvaluatorBase hierarchy): evaluators
consume a scored Dataset (label column + Prediction column) and return a
typed metrics object serializable to JSON.
"""
from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Optional

import numpy as np

from ..types.columns import NumericColumn, PredictionColumn
from ..types.dataset import Dataset


@dataclass
class EvaluationMetrics:
    def to_json(self) -> dict:
        return asdict(self)


class OpEvaluatorBase:
    """metric_name: the default metric; larger_better drives model selection
    direction (reference: OpEvaluatorBase.isLargerBetter)."""

    metric_name: str = "metric"
    larger_better: bool = True

    def evaluate(self, ds: Dataset, label_col: str, pred_col: str) -> EvaluationMetrics:
        label = ds[label_col]
        pred = ds[pred_col]
        assert isinstance(label, NumericColumn)
        assert isinstance(pred, PredictionColumn)
        return self.evaluate_arrays(
            np.asarray(label.values, dtype=np.float64), pred
        )

    def evaluate_arrays(
        self, y: np.ndarray, pred: PredictionColumn
    ) -> EvaluationMetrics:
        raise NotImplementedError

    def default_metric(self, metrics: EvaluationMetrics) -> float:
        return float(getattr(metrics, self.metric_name))
