"""Multiclass classification evaluation.

Counterpart of OpMultiClassificationEvaluator (reference: core/.../
evaluators/OpMultiClassificationEvaluator.scala:79-151): weighted
precision/recall/F1/error plus ThresholdMetrics - correct/incorrect/
no-prediction counts per topN in {1, 3} across a confidence-threshold grid
0..1 step 0.01.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..types.columns import PredictionColumn
from .base import EvaluationMetrics, OpEvaluatorBase


@dataclass
class ThresholdMetrics(EvaluationMetrics):
    topns: list = field(default_factory=list)
    thresholds: list = field(default_factory=list)
    correct_counts: dict = field(default_factory=dict)
    incorrect_counts: dict = field(default_factory=dict)
    no_prediction_counts: dict = field(default_factory=dict)


@dataclass
class MultiClassificationMetrics(EvaluationMetrics):
    Precision: float = 0.0
    Recall: float = 0.0
    F1: float = 0.0
    Error: float = 0.0
    threshold_metrics: dict = field(default_factory=dict)


class OpMultiClassificationEvaluator(OpEvaluatorBase):
    metric_name = "F1"
    larger_better = True

    def __init__(self, topns=(1, 3), threshold_step: float = 0.01) -> None:
        self.topns = tuple(topns)
        self.threshold_step = threshold_step

    def evaluate_arrays(self, y, pred: PredictionColumn):
        yhat = pred.prediction
        n = len(y)
        classes = np.unique(np.concatenate([y, yhat]))
        # weighted precision/recall (Spark MulticlassMetrics semantics)
        precisions, recalls, weights = [], [], []
        for c in classes:
            tp = float(((yhat == c) & (y == c)).sum())
            fp = float(((yhat == c) & (y != c)).sum())
            fn = float(((yhat != c) & (y == c)).sum())
            p = tp / (tp + fp) if tp + fp > 0 else 0.0
            r = tp / (tp + fn) if tp + fn > 0 else 0.0
            precisions.append(p)
            recalls.append(r)
            weights.append(float((y == c).sum()) / n)
        precision = float(np.dot(precisions, weights))
        recall = float(np.dot(recalls, weights))
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall > 0
            else 0.0
        )
        error = float((yhat != y).sum()) / max(n, 1)

        tm: dict = {}
        if pred.probability is not None and pred.probability.shape[1] >= 2:
            prob = pred.probability
            ths = np.arange(0.0, 1.0 + 1e-9, self.threshold_step)
            order = np.argsort(-prob, axis=1)
            sorted_prob = np.take_along_axis(prob, order, axis=1)
            correct: dict = {}
            incorrect: dict = {}
            nopred: dict = {}
            for topn in self.topns:
                k = min(topn, prob.shape[1])
                topk_classes = order[:, :k].astype(np.float64)
                top_conf = sorted_prob[:, 0]
                hit = (topk_classes == y[:, None]).any(axis=1)
                ccounts, icounts, ncounts = [], [], []
                for t in ths:
                    confident = top_conf >= t
                    ccounts.append(int((confident & hit).sum()))
                    icounts.append(int((confident & ~hit).sum()))
                    ncounts.append(int((~confident).sum()))
                correct[str(topn)] = ccounts
                incorrect[str(topn)] = icounts
                nopred[str(topn)] = ncounts
            tm = ThresholdMetrics(
                topns=list(self.topns), thresholds=ths.tolist(),
                correct_counts=correct, incorrect_counts=incorrect,
                no_prediction_counts=nopred,
            ).to_json()
        return MultiClassificationMetrics(
            Precision=precision, Recall=recall, F1=f1, Error=error,
            threshold_metrics=tm,
        )
