"""Regression evaluation.

Counterpart of OpRegressionEvaluator + OPLogLoss (reference: core/.../
evaluators/OpRegressionEvaluator.scala, core/.../impl/evaluator/
OPLogLoss.scala): RMSE/MSE/R2/MAE.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..types.columns import PredictionColumn
from .base import EvaluationMetrics, OpEvaluatorBase


@dataclass
class RegressionMetrics(EvaluationMetrics):
    RootMeanSquaredError: float = 0.0
    MeanSquaredError: float = 0.0
    R2: float = 0.0
    MeanAbsoluteError: float = 0.0


class OpRegressionEvaluator(OpEvaluatorBase):
    metric_name = "RootMeanSquaredError"
    larger_better = False

    def evaluate_arrays(self, y, pred: PredictionColumn):
        yhat = pred.prediction
        err = y - yhat
        mse = float(np.mean(err**2))
        mae = float(np.mean(np.abs(err)))
        ss_tot = float(np.sum((y - y.mean()) ** 2))
        r2 = 1.0 - float(np.sum(err**2)) / ss_tot if ss_tot > 0 else 0.0
        return RegressionMetrics(
            RootMeanSquaredError=float(np.sqrt(mse)),
            MeanSquaredError=mse, R2=r2, MeanAbsoluteError=mae,
        )


@dataclass
class LogLossMetrics(EvaluationMetrics):
    LogLoss: float = 0.0


class OpLogLossEvaluator(OpEvaluatorBase):
    """Multiclass log loss (reference: OPLogLoss.scala)."""

    metric_name = "LogLoss"
    larger_better = False

    def evaluate_arrays(self, y, pred: PredictionColumn):
        if pred.probability is None:
            raise ValueError("log loss needs probabilities")
        p = np.clip(pred.probability, 1e-15, 1.0)
        idx = y.astype(int)
        ll = -float(np.mean(np.log(p[np.arange(len(y)), idx])))
        return LogLossMetrics(LogLoss=ll)
