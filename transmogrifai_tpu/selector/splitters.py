"""Data splitters: holdout reservation + class rebalancing as sample weights.

Counterparts of Splitter / DataSplitter / DataBalancer / DataCutter
(reference: core/.../impl/tuning/Splitter.scala:57, DataSplitter.scala,
DataBalancer.scala:45-90, DataCutter.scala:48-141).  TPU-first difference:
instead of materializing up/down-sampled copies of the data (Spark RDD
resampling), rebalancing is expressed as per-row SAMPLE WEIGHTS so the
design matrix stays fixed in HBM and every candidate/fold sees the same
arrays - the rebalance rides the weight vector that the CV fan-out already
vmaps over.  Each splitter emits a SplitterSummary into metadata.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np


@dataclass
class PreparedData:
    """Outcome of splitter preparation: kept row indices (None = all rows),
    per-row weights, and the summary."""

    weights: np.ndarray
    keep_mask: Optional[np.ndarray]
    summary: dict


class Splitter:
    """(reference: tuning/Splitter.scala - reserveTestFraction default 0.1)"""

    def __init__(self, reserve_test_fraction: float = 0.1, seed: int = 42) -> None:
        self.reserve_test_fraction = reserve_test_fraction
        self.seed = seed

    def prepare(self, y: np.ndarray) -> PreparedData:
        return PreparedData(
            weights=np.ones(len(y)),
            keep_mask=None,
            summary={"splitter": type(self).__name__},
        )


class DataSplitter(Splitter):
    """Regression: plain holdout reservation, pass-through prep (reference:
    DataSplitter.scala)."""


class DataBalancer(Splitter):
    """Binary-classification rebalancing (reference: DataBalancer.scala:45-90):
    if the positive fraction is below ``sample_fraction``, up-weight the
    minority class / down-weight the majority so the effective positive
    fraction equals sample_fraction, capping effective size at
    ``max_training_sample``."""

    def __init__(
        self,
        sample_fraction: float = 0.1,
        max_training_sample: int = 1_000_000,
        reserve_test_fraction: float = 0.1,
        seed: int = 42,
    ) -> None:
        super().__init__(reserve_test_fraction, seed)
        self.sample_fraction = sample_fraction
        self.max_training_sample = max_training_sample

    def prepare(self, y: np.ndarray) -> PreparedData:
        n = len(y)
        pos = float((y == 1).sum())
        neg = float(n - pos)
        small, big = (pos, neg) if pos <= neg else (neg, pos)
        small_label = 1.0 if pos <= neg else 0.0
        weights = np.ones(n)
        summary = {
            "splitter": "DataBalancer",
            "positiveCount": pos,
            "negativeCount": neg,
            "desiredFraction": self.sample_fraction,
            "upSampled": False,
            "downSampled": False,
        }
        frac = small / max(n, 1)
        if small > 0 and frac < self.sample_fraction:
            # target: small_w*small / (small_w*small + big) = sample_fraction
            small_w = self.sample_fraction * big / (
                (1.0 - self.sample_fraction) * small
            )
            weights = np.where(y == small_label, small_w, 1.0)
            summary["upSampled"] = True
            summary["minorityWeight"] = float(small_w)
        # cap effective training size by uniform down-weighting
        eff = float(weights.sum())
        if eff > self.max_training_sample:
            weights *= self.max_training_sample / eff
            summary["downSampled"] = True
        return PreparedData(weights=weights, keep_mask=None, summary=summary)


class DataCutter(Splitter):
    """Multiclass label curation (reference: DataCutter.scala:48-141): drop
    rows whose label falls below ``min_label_fraction`` or beyond
    ``max_label_categories`` most-frequent labels."""

    def __init__(
        self,
        min_label_fraction: float = 0.0,
        max_label_categories: int = 100,
        reserve_test_fraction: float = 0.1,
        seed: int = 42,
    ) -> None:
        super().__init__(reserve_test_fraction, seed)
        self.min_label_fraction = min_label_fraction
        self.max_label_categories = max_label_categories

    def prepare(self, y: np.ndarray) -> PreparedData:
        n = len(y)
        labels, counts = np.unique(y, return_counts=True)
        order = np.argsort(-counts, kind="stable")
        labels, counts = labels[order], counts[order]
        kept = [
            l
            for i, (l, c) in enumerate(zip(labels, counts))
            if c / n >= self.min_label_fraction and i < self.max_label_categories
        ]
        kept_set = set(float(l) for l in kept)
        keep_mask = np.array([float(v) in kept_set for v in y], dtype=bool)
        summary = {
            "splitter": "DataCutter",
            "labelsKept": sorted(kept_set),
            "labelsDropped": sorted(set(float(l) for l in labels) - kept_set),
            "rowsDropped": int(n - keep_mask.sum()),
        }
        return PreparedData(
            weights=np.ones(n), keep_mask=keep_mask, summary=summary
        )
