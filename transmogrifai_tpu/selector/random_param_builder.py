"""Random hyperparameter grids.

Counterpart of RandomParamBuilder (reference: core/.../impl/selector/
RandomParamBuilder.scala): sample N param maps from per-param
distributions - uniform/log-uniform ranges for floats, choice lists for
discrete values.

Determinism contract (ISSUE 13 satellite, pinned in tests): the same
seed + the same specs yield the same candidate LIST, independent of how
many candidates any earlier ``build`` call drew - each ``build`` seeds
a fresh per-call stream from ``(seed, call index)`` instead of
continuing one shared stream.  Candidate ORDER is the winner tie-break
(``validate`` keeps the first of equal metrics, and successive-halving
preserves original grid order among survivors), so grids must
reproduce identically whether or not pruning reordered evaluation.
"""
from __future__ import annotations

from typing import Any, Sequence

import numpy as np


class RandomParamBuilder:
    def __init__(self, seed: int = 42) -> None:
        self._specs: list[tuple[str, str, Any]] = []
        self._seed = int(seed)
        self._calls = 0

    def uniform(self, name: str, low: float, high: float) -> "RandomParamBuilder":
        self._specs.append((name, "uniform", (low, high)))
        return self

    def log_uniform(self, name: str, low: float, high: float) -> "RandomParamBuilder":
        assert low > 0 and high > 0
        self._specs.append((name, "log", (low, high)))
        return self

    def choice(self, name: str, values: Sequence) -> "RandomParamBuilder":
        self._specs.append((name, "choice", list(values)))
        return self

    def int_uniform(self, name: str, low: int, high: int) -> "RandomParamBuilder":
        self._specs.append((name, "int", (low, high)))
        return self

    def build(self, n: int) -> list[dict]:
        """Sample ``n`` param maps.  Per-call child stream: the i-th
        ``build`` on a builder always consumes RandomState(seed + i *
        7919), so ``build(3)`` returns the same 3 candidates in the
        same order whether the previous call drew 3 or 300 - grid
        identity (and therefore winner tie-breaks) can never depend on
        unrelated sampling history."""
        rng = np.random.RandomState(
            (self._seed + self._calls * 7919) % (2 ** 32)
        )
        self._calls += 1
        grids = []
        for _ in range(n):
            p = {}
            for name, kind, spec in self._specs:
                if kind == "uniform":
                    p[name] = float(rng.uniform(*spec))
                elif kind == "log":
                    lo, hi = np.log(spec[0]), np.log(spec[1])
                    p[name] = float(np.exp(rng.uniform(lo, hi)))
                elif kind == "int":
                    p[name] = int(rng.randint(spec[0], spec[1] + 1))
                else:
                    p[name] = spec[int(rng.randint(len(spec)))]
            grids.append(p)
        return grids
