"""Random hyperparameter grids.

Counterpart of RandomParamBuilder (reference: core/.../impl/selector/
RandomParamBuilder.scala): sample N param maps from per-param
distributions - uniform/log-uniform ranges for floats, choice lists for
discrete values.
"""
from __future__ import annotations

from typing import Any, Sequence

import numpy as np


class RandomParamBuilder:
    def __init__(self, seed: int = 42) -> None:
        self._specs: list[tuple[str, str, Any]] = []
        self._rng = np.random.RandomState(seed)

    def uniform(self, name: str, low: float, high: float) -> "RandomParamBuilder":
        self._specs.append((name, "uniform", (low, high)))
        return self

    def log_uniform(self, name: str, low: float, high: float) -> "RandomParamBuilder":
        assert low > 0 and high > 0
        self._specs.append((name, "log", (low, high)))
        return self

    def choice(self, name: str, values: Sequence) -> "RandomParamBuilder":
        self._specs.append((name, "choice", list(values)))
        return self

    def int_uniform(self, name: str, low: int, high: int) -> "RandomParamBuilder":
        self._specs.append((name, "int", (low, high)))
        return self

    def build(self, n: int) -> list[dict]:
        grids = []
        for _ in range(n):
            p = {}
            for name, kind, spec in self._specs:
                if kind == "uniform":
                    p[name] = float(self._rng.uniform(*spec))
                elif kind == "log":
                    lo, hi = np.log(spec[0]), np.log(spec[1])
                    p[name] = float(np.exp(self._rng.uniform(lo, hi)))
                elif kind == "int":
                    p[name] = int(self._rng.randint(spec[0], spec[1] + 1))
                else:
                    p[name] = spec[int(self._rng.randint(len(spec)))]
            grids.append(p)
        return grids
