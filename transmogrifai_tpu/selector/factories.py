"""Model selector factories with default candidate grids.

Counterparts of BinaryClassificationModelSelector /
MultiClassificationModelSelector / RegressionModelSelector +
DefaultSelectorParams (reference: core/.../impl/classification/
BinaryClassificationModelSelector.scala:46-100,
impl/regression/RegressionModelSelector.scala,
impl/selector/DefaultSelectorParams.scala:36-61 - MaxDepth {3,6,12},
Regularization {0.001,0.01,0.1,0.2}, ElasticNet {0.1,0.5}, MaxTrees {50},
MinInfoGain {0.001,0.01,0.1}, MinInstancesPerNode {10,100}).
"""
from __future__ import annotations

from itertools import product
from typing import Optional, Sequence

from ..evaluators.binary import OpBinaryClassificationEvaluator
from ..evaluators.multiclass import OpMultiClassificationEvaluator
from ..evaluators.regression import OpRegressionEvaluator
from .model_selector import ModelSelector
from .splitters import DataBalancer, DataCutter, DataSplitter, Splitter
from .validator import OpCrossValidation, OpTrainValidationSplit

REGULARIZATION = [0.001, 0.01, 0.1, 0.2]
ELASTIC_NET = [0.1, 0.5]
MAX_DEPTH = [3, 6, 12]
MAX_TREES = [50]
MIN_INFO_GAIN = [0.001, 0.01, 0.1]
MIN_INSTANCES_PER_NODE = [10, 100]


def lr_grid() -> list[dict]:
    return [
        {"reg_param": r, "elastic_net_param": e}
        for r, e in product(REGULARIZATION, ELASTIC_NET)
    ]


def linreg_grid() -> list[dict]:
    return lr_grid()


def rf_grid() -> list[dict]:
    return [
        {
            "max_depth": d,
            "num_trees": t,
            "min_info_gain": g,
            "min_instances_per_node": m,
        }
        for d, t, g, m in product(
            MAX_DEPTH, MAX_TREES, MIN_INFO_GAIN, MIN_INSTANCES_PER_NODE
        )
    ]


def gbt_grid() -> list[dict]:
    return [
        {"max_depth": d, "num_trees": 20, "min_info_gain": g}
        for d, g in product(MAX_DEPTH, MIN_INFO_GAIN)
    ]


def _binary_models(model_types: Optional[Sequence[str]]):
    from ..models.logistic_regression import OpLogisticRegression
    from ..models.naive_bayes import OpNaiveBayes
    from ..models.trees import OpGBTClassifier, OpRandomForestClassifier
    from ..models.linear_svc import OpLinearSVC

    registry = {
        "OpLogisticRegression": lambda: (OpLogisticRegression(), lr_grid()),
        "OpRandomForestClassifier": lambda: (OpRandomForestClassifier(), rf_grid()),
        "OpGBTClassifier": lambda: (OpGBTClassifier(), gbt_grid()),
        "OpLinearSVC": lambda: (OpLinearSVC(), lr_grid()),
        "OpNaiveBayes": lambda: (OpNaiveBayes(), [{}]),
    }
    # reference defaults: LR, RF, GBT, LinearSVC
    # (BinaryClassificationModelSelector.scala:46-100)
    wanted = model_types or [
        "OpLogisticRegression",
        "OpRandomForestClassifier",
        "OpGBTClassifier",
        "OpLinearSVC",
    ]
    return [registry[m]() for m in wanted]


class BinaryClassificationModelSelector:
    """Factory (reference: BinaryClassificationModelSelector cv/ts
    constructors)."""

    @staticmethod
    def with_cross_validation(
        num_folds: int = 3,
        validation_metric=None,
        model_types_to_use: Optional[Sequence[str]] = None,
        splitter: Optional[Splitter] = None,
        seed: int = 42,
        models_and_parameters=None,
        autotune=None,
    ) -> ModelSelector:
        ev = validation_metric or OpBinaryClassificationEvaluator()
        return ModelSelector(
            validator=OpCrossValidation(
                num_folds=num_folds, evaluator=ev, seed=seed, stratify=True,
                autotune=autotune,
            ),
            models=models_and_parameters or _binary_models(model_types_to_use),
            splitter=splitter
            if splitter is not None
            else DataBalancer(sample_fraction=0.1, reserve_test_fraction=0.1, seed=seed),
            evaluators=[OpBinaryClassificationEvaluator()],
        )

    @staticmethod
    def with_train_validation_split(
        train_ratio: float = 0.75,
        validation_metric=None,
        model_types_to_use: Optional[Sequence[str]] = None,
        splitter: Optional[Splitter] = None,
        seed: int = 42,
        models_and_parameters=None,
        autotune=None,
    ) -> ModelSelector:
        ev = validation_metric or OpBinaryClassificationEvaluator()
        return ModelSelector(
            validator=OpTrainValidationSplit(
                train_ratio=train_ratio, evaluator=ev, seed=seed,
                stratify=True, autotune=autotune,
            ),
            models=models_and_parameters or _binary_models(model_types_to_use),
            splitter=splitter
            if splitter is not None
            else DataBalancer(sample_fraction=0.1, reserve_test_fraction=0.1, seed=seed),
            evaluators=[OpBinaryClassificationEvaluator()],
        )

    # parameterless call mirrors the reference's `BinaryClassificationModelSelector()`
    def __new__(cls, *args, **kw) -> ModelSelector:  # type: ignore[misc]
        return cls.with_cross_validation(*args, **kw)


def _multiclass_models(model_types: Optional[Sequence[str]]):
    from ..models.logistic_regression import OpLogisticRegression
    from ..models.naive_bayes import OpNaiveBayes
    from ..models.trees import OpDecisionTreeClassifier, OpRandomForestClassifier

    registry = {
        "OpLogisticRegression": lambda: (OpLogisticRegression(), lr_grid()),
        "OpRandomForestClassifier": lambda: (OpRandomForestClassifier(), rf_grid()),
        "OpDecisionTreeClassifier": lambda: (
            OpDecisionTreeClassifier(),
            [{"max_depth": d, "min_info_gain": g}
             for d, g in product(MAX_DEPTH, MIN_INFO_GAIN)],
        ),
        "OpNaiveBayes": lambda: (OpNaiveBayes(), [{}]),
    }
    # reference defaults: LR, RF, DT, NB
    wanted = model_types or [
        "OpLogisticRegression",
        "OpRandomForestClassifier",
        "OpDecisionTreeClassifier",
        "OpNaiveBayes",
    ]
    return [registry[m]() for m in wanted]


class MultiClassificationModelSelector:
    @staticmethod
    def with_cross_validation(
        num_folds: int = 3,
        validation_metric=None,
        model_types_to_use: Optional[Sequence[str]] = None,
        splitter: Optional[Splitter] = None,
        seed: int = 42,
        models_and_parameters=None,
        autotune=None,
    ) -> ModelSelector:
        ev = validation_metric or OpMultiClassificationEvaluator()
        return ModelSelector(
            validator=OpCrossValidation(
                num_folds=num_folds, evaluator=ev, seed=seed, stratify=True,
                autotune=autotune,
            ),
            models=models_and_parameters or _multiclass_models(model_types_to_use),
            splitter=splitter
            if splitter is not None
            else DataCutter(reserve_test_fraction=0.1, seed=seed),
            evaluators=[OpMultiClassificationEvaluator()],
        )

    def __new__(cls, *args, **kw) -> ModelSelector:  # type: ignore[misc]
        return cls.with_cross_validation(*args, **kw)


def _regression_models(model_types: Optional[Sequence[str]]):
    from ..models.linear_regression import OpLinearRegression
    from ..models.trees import OpGBTRegressor, OpRandomForestRegressor

    registry = {
        "OpLinearRegression": lambda: (OpLinearRegression(), linreg_grid()),
        "OpRandomForestRegressor": lambda: (OpRandomForestRegressor(), rf_grid()),
        "OpGBTRegressor": lambda: (OpGBTRegressor(), gbt_grid()),
    }
    # reference defaults: LinReg, RF, GBT, DT, GLM
    wanted = model_types or [
        "OpLinearRegression",
        "OpRandomForestRegressor",
        "OpGBTRegressor",
    ]
    return [registry[m]() for m in wanted]


class RegressionModelSelector:
    @staticmethod
    def with_cross_validation(
        num_folds: int = 3,
        validation_metric=None,
        model_types_to_use: Optional[Sequence[str]] = None,
        splitter: Optional[Splitter] = None,
        seed: int = 42,
        models_and_parameters=None,
        autotune=None,
    ) -> ModelSelector:
        ev = validation_metric or OpRegressionEvaluator()
        return ModelSelector(
            validator=OpCrossValidation(num_folds=num_folds, evaluator=ev,
                                        seed=seed, autotune=autotune),
            models=models_and_parameters or _regression_models(model_types_to_use),
            splitter=splitter
            if splitter is not None
            else DataSplitter(reserve_test_fraction=0.1, seed=seed),
            evaluators=[OpRegressionEvaluator()],
        )

    def __new__(cls, *args, **kw) -> ModelSelector:  # type: ignore[misc]
        return cls.with_cross_validation(*args, **kw)
