"""Cross-validation / train-validation split over array-level candidates.

Counterpart of OpValidator / OpCrossValidation / OpTrainValidationSplit
(reference: core/.../impl/tuning/OpValidator.scala:275-322,
OpCrossValidation.scala:71-167, OpTrainValidationSplit.scala).  Where the
reference fans fold x model-type training out on a JVM thread pool (Scala
Futures, parallelism 8) with Spark jobs inside, here the fan-out is
ARRAY-BATCHED: folds and grid points become a leading axis of weight
vectors, and estimators that implement ``fit_arrays_batched`` train the
whole (fold x grid) batch as ONE vmapped jitted computation - on a sharded
mesh this is replicas across devices, the direct analog (and replacement)
of the reference's Future pool.  Estimators without a batched path fall
back to a per-candidate loop of jitted fits.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..autotune.cost_model import params_hash as _params_hash
from ..evaluators.base import OpEvaluatorBase
from ..models.base import PredictorEstimator
from ..obs import trace as _obs_trace
from ..parallel.mesh import cv_mesh_or_none
from ..types.columns import PredictionColumn


@jax.jit
def _margins_kernel(X, betas, b0s):
    """[n, d] @ [B, d]^T + [B] -> [n, B] decision margins for all
    candidates in one matmul (stays in HBM)."""
    return X @ betas.T + b0s[None, :]


@dataclass
class ValidationResult:
    best_estimator: PredictorEstimator
    best_params: dict
    best_metric: float
    metric_name: str
    larger_better: bool
    all_results: list = field(default_factory=list)  # per (model, grid) dicts
    #: successive-halving decision trail (ISSUE 13): rungs, prunes,
    #: predicted-vs-actual times; None when autotune was off
    autotune: Optional[dict] = None
    #: fused-training dispatch trail (ISSUE 15): per-family backend
    #: (fused / existing + reason), AOT-cache hits/misses/stale - the
    #: warm-refit observability the continuous-training loop asserts on
    train_fused: Optional[dict] = None


def _numeric_params(pmap: dict) -> dict:
    """The numeric hyperparameters of one grid point, flattened for
    span attrs: exactly the features the cost model trains on."""
    return {
        k: float(v) for k, v in pmap.items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    }


def _rung_train_mask(ys: np.ndarray, train_fraction: float,
                     seed: int) -> np.ndarray:
    """Deterministic train mask over the rung subsample: stratified per
    label class when the label is discrete (<=32 classes), else a plain
    shuffled split - regression rungs must not np.unique-explode."""
    n = len(ys)
    rng = np.random.RandomState(seed + 1)
    mask = np.zeros(n, dtype=bool)
    classes = np.unique(ys)
    if len(classes) <= 32:
        for c in classes:
            idx = np.nonzero(ys == c)[0]
            perm = rng.permutation(idx)
            mask[perm[: int(np.ceil(len(idx) * train_fraction))]] = True
    else:
        perm = rng.permutation(n)
        mask[perm[: int(np.ceil(n * train_fraction))]] = True
    return mask


def stratified_kfold_masks(
    y: np.ndarray, k: int, seed: int, stratify: bool
) -> np.ndarray:
    """[k, n] bool masks, True = row in the fold's TRAIN split.  Stratified
    per label class when requested (reference: OpCrossValidation.scala:161-167
    label-stratified kFold)."""
    n = len(y)
    if stratify:
        classes = np.unique(y)
        class_indices = {c: np.nonzero(y == c)[0] for c in classes}
        return _kfold_masks_from_indices(class_indices, n, k, seed)
    rng = np.random.RandomState(seed)
    fold_of = np.empty(n, dtype=np.int64)
    fold_of[rng.permutation(n)] = np.arange(n) % k
    return np.stack([fold_of != f for f in range(k)], axis=0)


def _kfold_masks_from_indices(
    class_indices: dict, n: int, k: int, seed: int
) -> np.ndarray:
    """Stratified fold masks from precomputed per-class row indices:
    THE shared implementation behind the batch path and the streamed
    fold builder — identical RNG consumption order (classes ascending),
    so streamed and batch masks are bit-equal (pinned in tier-1)."""
    rng = np.random.RandomState(seed)
    fold_of = np.empty(n, dtype=np.int64)
    for c in sorted(class_indices):
        idx = np.asarray(class_indices[c])
        perm = rng.permutation(len(idx))
        fold_of[idx[perm]] = np.arange(len(idx)) % k
    return np.stack([fold_of != f for f in range(k)], axis=0)


class StreamingFoldBuilder:
    """CV fold construction that consumes design-matrix chunks AS THEY
    LAND from the sharded input pipeline (readers/pipeline.py), instead
    of waiting for the full matrix.

    ``observe`` runs the per-chunk work — per-class row scans for the
    stratified split plus block retention — while worker threads are
    still parsing later shards; ``finalize`` orders chunks by their
    (shard_id, chunk_id) key, assembles X/y with one copy pass, and
    computes fold masks bit-identical to the batch
    :func:`stratified_kfold_masks` on the assembled y (same RNG
    consumption), regardless of chunk ARRIVAL order.
    """

    def __init__(self, k: int, seed: int = 42,
                 stratify: bool = False) -> None:
        self.k = int(k)
        self.seed = int(seed)
        self.stratify = bool(stratify)
        self._chunks: list[tuple] = []  # (order_key, X, y, local_idx)
        self.rows = 0

    def observe(self, order_key, X_block, y_block) -> None:
        Xb = np.asarray(X_block)
        yb = np.asarray(y_block)
        local: dict = {}
        if self.stratify:
            for c in np.unique(yb):
                local[float(c)] = np.nonzero(yb == c)[0]
        self._chunks.append((tuple(order_key), Xb, yb, local))
        self.rows += len(yb)

    def finalize(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns (X [n, d] float32, y [n], train_masks [k, n])."""
        if not self._chunks:
            raise ValueError("no chunks observed")
        self._chunks.sort(key=lambda t: t[0])
        n = self.rows
        d = self._chunks[0][1].shape[1]
        X = np.empty((n, d), np.float32)
        y = np.empty(n, self._chunks[0][2].dtype)
        class_indices: dict = {}
        at = 0
        for _, Xb, yb, local in self._chunks:
            m = len(yb)
            X[at:at + m] = Xb
            y[at:at + m] = yb
            for c, idx in local.items():
                class_indices.setdefault(c, []).append(idx + at)
            at += m
        if self.stratify:
            merged = {
                c: np.concatenate(parts)
                for c, parts in class_indices.items()
            }
            masks = _kfold_masks_from_indices(merged, n, self.k,
                                              self.seed)
        else:
            masks = stratified_kfold_masks(y, self.k, self.seed, False)
        return X, y, masks


class OpValidator:
    """``checkpoint_path`` enables CV-state checkpointing: each completed
    (model, grid-point) row of fold metrics is persisted and skipped on
    restart - the preemption-recovery story the reference delegated to
    Spark task retry (SURVEY §5.3: on TPU pods this gap is owned here)."""

    def __init__(
        self,
        evaluator: OpEvaluatorBase,
        seed: int = 42,
        stratify: bool = False,
        checkpoint_path: Optional[str] = None,
        autotune=None,
    ) -> None:
        self.evaluator = evaluator
        self.seed = seed
        self.stratify = stratify
        self.checkpoint_path = checkpoint_path
        #: successive-halving config (autotune.AutotuneConfig) - None
        #: runs the exhaustive sweep (ISSUE 13)
        self.autotune = autotune
        #: decision trail of the LAST validate() call (also carried on
        #: ValidationResult.autotune); None when autotune was off
        self.last_autotune_report: Optional[dict] = None
        #: fused-training knobs (ISSUE 15): None = auto (TX_TRAIN_FUSED
        #: env + row floor), True/False force; cache dir holds the AOT
        #: train executables (train_xla_cache/ next to autotune.json)
        self.train_fused: Optional[bool] = None
        self.train_cache_dir: Optional[str] = None
        #: per-family dispatch trail of the LAST validate() call (also
        #: carried on ValidationResult.train_fused)
        self.last_train_fused: Optional[dict] = None

    # -- CV checkpoint ------------------------------------------------------
    def _ckpt_load(self) -> dict:
        if not self.checkpoint_path:
            return {}
        import json
        import os

        if not os.path.exists(self.checkpoint_path):
            return {}
        try:
            with open(self.checkpoint_path) as f:
                done = json.load(f)
        except (OSError, ValueError):
            return {}
        # migrate pre-mode-suffix checkpoints: un-suffixed keys were
        # produced by the exact host metrics path, so restarting after an
        # upgrade must not silently retrain every candidate
        return {
            (k if k.endswith((":exact", ":approx")) else k + ":exact"): v
            for k, v in done.items()
        }

    def _ckpt_save(self, done: dict) -> None:
        if not self.checkpoint_path:
            return
        import json
        import os

        tmp = self.checkpoint_path + ".tmp"
        os.makedirs(os.path.dirname(self.checkpoint_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(done, f)
        os.replace(tmp, self.checkpoint_path)
        self._beat()

    def _beat(self) -> None:
        """Progress heartbeat for the preemption supervisor (workflow/
        supervisor.py): liveness == CV progress, so a wedged dispatch or a
        killed host stops the beat and triggers re-dispatch."""
        if not self.checkpoint_path:
            return
        from ..workflow.supervisor import beat

        beat(self.checkpoint_path + ".heartbeat")

    def train_masks(self, y: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    #: one-shot mask override installed by validate_stream (fold masks
    #: already built chunk-by-chunk during ingest)
    _streamed_masks: Optional[np.ndarray] = None

    def validate_stream(
        self,
        models: Sequence[tuple[PredictorEstimator, Sequence[dict]]],
        chunks,
        weights: Optional[np.ndarray] = None,
    ) -> ValidationResult:
        """:meth:`validate` fed by a chunk stream: ``chunks`` yields
        (order_key, X_block, y_block) as the input pipeline lands them
        (readers/pipeline.py).  Fold construction — the stratified
        per-class row scans and the design-matrix assembly — runs
        per chunk DURING parsing; the candidate fits start the moment
        the last chunk lands.  Selection is identical to the batch path
        on the same data (same masks, same RNG), pinned in tier-1."""
        is_cv = isinstance(self, OpCrossValidation)
        k = getattr(self, "num_folds", 1)
        # per-chunk stratified scans only pay off when the masks will
        # actually be used: non-CV validators (train/validation split)
        # compute their own masks in validate(), so the builder just
        # assembles X/y for them
        builder = StreamingFoldBuilder(
            k, self.seed, self.stratify and is_cv)
        for order_key, Xb, yb in chunks:
            builder.observe(order_key, Xb, yb)
        X, y, masks = builder.finalize()
        if is_cv:
            self._streamed_masks = masks
        try:
            return self.validate(models, X, y, weights=weights)
        finally:
            self._streamed_masks = None

    def _metric_of(self, y: np.ndarray, pred, raw, prob) -> float:
        m = self.evaluator.evaluate_arrays(
            y, PredictionColumn(pred, raw, prob)
        )
        return self.evaluator.default_metric(m)

    # -- fused training programs (ISSUE 15) ---------------------------------
    def _train_fused_gate(self, n: int, mesh_present: bool) -> Optional[str]:
        """None when the fused fold x grid program may engage for this
        family, else the recorded fallback reason.  Auto mode engages
        only at scale (TX_TRAIN_FUSED_MIN_ROWS, default 200k): below it
        a one-shot validate pays more in trace+compile than the fused
        dispatch saves, and the proven kernel-at-a-time path stays
        bit-for-bit what it always was.  ``train_fused=True`` (or
        TX_TRAIN_FUSED=1) forces the path at any size - warm-refit
        loops and tests; a CV mesh always falls back (the PR-3 guarded
        mesh route owns multi-device degradation unchanged)."""
        import os

        if self.train_fused is False:
            return "disabled"
        env = os.environ.get("TX_TRAIN_FUSED", "").strip().lower()
        if env in ("0", "false", "off"):
            return "disabled_env"
        forced = self.train_fused is True or env in ("1", "true", "on")
        if not forced:
            min_rows = int(os.environ.get(
                "TX_TRAIN_FUSED_MIN_ROWS", 200_000))
            if n < min_rows:
                return "below_min_rows"
        if mesh_present:
            return "mesh"
        return None

    def _record_train_fused(self, family: str, entry: dict) -> None:
        rep = self.last_train_fused
        if rep is None:
            rep = self.last_train_fused = {
                "backend": "existing",
                "families": {},
                "cache": {"hits": 0, "misses": 0, "stale": 0},
            }
        rep["families"][family] = entry
        backends = {e.get("backend") for e in rep["families"].values()}
        rep["backend"] = (
            "fused" if backends == {"fused"}
            else "existing" if backends == {"existing"} else "mixed"
        )
        c = entry.get("cache")
        if c in ("hit", "memory"):
            rep["cache"]["hits"] += 1
        elif c == "miss":
            rep["cache"]["misses"] += 1
        elif c == "stale":
            rep["cache"]["stale"] += 1

    def _try_train_fused(self, kind: str, est, mode: str, **kw):
        """Attempt the one-program dispatch for this family; None (with
        the reason recorded in the trail) routes the caller to the
        existing kernel-at-a-time path.  Any failure here must degrade,
        never abort a selection."""
        from ..local import fused_train as _ft

        reason = self._train_fused_gate(
            kw.pop("n"), kw.pop("mesh_present"))
        if reason is not None:
            self._record_train_fused(
                est.model_type,
                {"backend": "existing", "reason": reason})
            return None
        try:
            with _obs_trace.span(
                "cv.fit_batch", family=est.model_type,
                candidates=int(kw["candidates"]), folds=int(kw["folds"]),
                n_rows=int(kw["n_rows"]), n_features=int(kw["n_features"]),
                fused=1,
            ):
                if kind == "linear":
                    res = _ft.run_linear(
                        est, kw["xdev"](), kw["y"], kw["masks"], kw["w"],
                        kw["weights_given"], kw["regs"], kw["ens"],
                        kw["g"], self.evaluator, mode,
                        cache_dir=self.train_cache_dir,
                    )
                else:
                    res = _ft.run_tree(
                        est, kw["X"], kw["y"], kw["masks"], kw["W"],
                        kw["grid"], self.evaluator,
                        cache_dir=self.train_cache_dir,
                    )
        except _ft.FusedTrainError as e:
            self._record_train_fused(
                est.model_type,
                {"backend": "existing", "reason": e.reason})
            return None
        except Exception as e:  # noqa: BLE001 - a fused-path bug must
            # degrade to the proven dispatch, loudly, never kill the
            # whole selection
            import logging

            logging.getLogger("transmogrifai_tpu.selector").warning(
                "fused training dispatch for %s failed (%s: %s); "
                "falling back to the kernel-at-a-time path",
                est.model_type, type(e).__name__, e,
            )
            self._record_train_fused(
                est.model_type,
                {"backend": "existing",
                 "reason": f"error:{type(e).__name__}"})
            return None
        self._record_train_fused(est.model_type, res.report)
        return res

    # -- successive-halving pre-pass (ISSUE 13) -----------------------------
    def _autotune_prune(self, models, X, y, w, masks, larger, xdev=None):
        """Budget-ladder rung 0: every candidate fits ONCE on a
        deterministic row subsample, the cost model plus interim eval
        scores pick survivors, and only survivors proceed to the full
        fold x grid spend.  Decision logic lives in autotune/pruning.py
        (go/no-go BEFORE any rung fit, so a degraded run costs exactly
        the exhaustive budget); this method owns execution.  Returns
        (models-to-run, decision report, all_results entries for the
        pruned candidates)."""
        import time as _time

        from ..autotune import pruning as _at
        from ..autotune.cost_model import (
            candidate_features,
            key_for_fit,
            params_hash,
        )

        cfg = self.autotune
        k = masks.shape[0]
        n = len(y)
        d = int(X.shape[1])
        models = [(est, list(grid) or [{}]) for est, grid in models]
        infos = []
        gi = 0
        for ei, (est, grid) in enumerate(models):
            for j, pmap in enumerate(grid):
                infos.append(_at.CandidateInfo(
                    index=gi, est_index=ei, grid_index=j,
                    family=est.model_type, params=dict(pmap),
                    params_hash=params_hash(pmap),
                ))
                gi += 1
        classes, counts = np.unique(y, return_counts=True)
        balance = float(counts.min() / counts.sum()) \
            if len(classes) > 1 else 1.0
        plan = _at.plan_pruning(cfg, infos, n, d, k,
                                class_balance=balance)
        if not plan.pruning:
            report = plan.report()
            self._record_autotune(report)
            return models, report, []
        # rung subsample + split: seeded by the validator seed, so the
        # ladder is reproducible run to run
        rng = np.random.RandomState(self.seed)
        sub = np.sort(rng.permutation(n)[: plan.rung_rows])
        if xdev is not None:
            # rung 0 shares the validate-wide device buffer (ISSUE 15
            # satellite): one [rung_rows, d] gather off the already-
            # converted f32 matrix instead of a second host fancy-index
            # whose rows every rung fit re-converts f64->f32
            Xs = np.asarray(xdev()[jnp.asarray(sub)])
        else:
            Xs = X[sub]
        ys, ws = y[sub], w[sub]
        rtr = _rung_train_mask(ys, cfg.rung_train_fraction, self.seed)
        n_rtr = int(rtr.sum())
        cm = cfg.cost_model
        with _obs_trace.span("autotune.rung", rows=int(plan.rung_rows),
                             candidates=len(infos)):
            for c in infos:
                est, _grid = models[c.est_index]
                cand = est.with_params(**c.params)
                t0 = _time.perf_counter()
                try:
                    with _obs_trace.span(
                        "autotune.rung_fit", family=c.family,
                        params_hash=c.params_hash, n_rows=n_rtr,
                        n_features=d, **_numeric_params(c.params),
                    ):
                        params = cand.fit_arrays(
                            Xs[rtr], ys[rtr], ws[rtr])
                    pred, raw, prob = cand.predict_arrays(
                        params, Xs[~rtr])
                    c.interim_metric = self._metric_of(
                        ys[~rtr], pred, raw, prob)
                except Exception as e:  # noqa: BLE001 - a failed rung
                    # fit ranks the candidate last (recorded in the
                    # trail) but must never kill the whole selection
                    c.rung_error = f"{type(e).__name__}: {e}"
                c.rung_wall_ms = (_time.perf_counter() - t0) * 1e3
                if c.rung_error is None:
                    # a failed fit's time-to-exception is NOT a cost
                    # observation - a ~0ms sample would drag the ridge
                    # toward "this family fits for free"
                    cm.observe(
                        key_for_fit(c.family),
                        candidate_features(n_rtr, d, c.params, balance),
                        c.rung_wall_ms,
                    )
        _at.select_survivors(plan, larger)
        pruned_models = []
        for ei, (est, grid) in enumerate(models):
            keep = sorted(
                c.grid_index for c in infos
                if c.est_index == ei and c.kept
            )
            if keep:
                # survivors keep their ORIGINAL grid order, so the main
                # loop's evaluation order - and therefore winner
                # tie-breaks - match the exhaustive sweep's
                pruned_models.append((est, [grid[j] for j in keep]))
        pruned_results = [
            {
                "model_type": c.family,
                "model_uid": models[c.est_index][0].uid,
                "params": dict(c.params),
                "metric": float("nan") if c.interim_metric is None
                else float(c.interim_metric),
                "fold_metrics": [],
                "pruned": True,
                "metric_kind": "rung",
                "rung_rows": int(plan.rung_rows),
            }
            for c in infos if not c.kept
        ]
        report = plan.report()
        self._record_autotune(report)
        return pruned_models, report, pruned_results

    def _record_autotune(self, report: dict) -> None:
        """Every pruning decision is visible in the obs plane: counters
        and gauges in the metrics registry (scraped as tx_autotune_*)
        plus a decision event on the ambient trace."""
        from ..obs.metrics import metrics_registry

        reg = metrics_registry()
        reg.counter(
            "autotune.selections",
            help="validate() calls that consulted the autotune ladder",
        ).inc()
        if report["mode"] == "pruned":
            reg.counter(
                "autotune.candidates_pruned",
                help="grid candidates pruned at the rung",
            ).inc(report["candidates_pruned"])
            if report.get("predicted_speedup"):
                reg.gauge(
                    "autotune.predicted_speedup",
                    help="cost-model predicted exhaustive/pruned "
                         "selection speedup",
                ).set(float(report["predicted_speedup"]))
        else:
            reg.counter(
                "autotune.exhaustive_runs",
                help="autotune-enabled selections that degraded to the "
                     "exhaustive sweep (reason in the report)",
            ).inc()
        _obs_trace.tracer().event(
            "autotune.decision", mode=report["mode"],
            reason=report.get("reason") or "",
            pruned=int(report["candidates_pruned"]),
            survivors=int(report["survivors"]),
        )

    def validate(
        self,
        models: Sequence[tuple[PredictorEstimator, Sequence[dict]]],
        X: np.ndarray,
        y: np.ndarray,
        weights: Optional[np.ndarray] = None,
    ) -> ValidationResult:
        """Pick the best (estimator, param-map) by mean validation metric
        across folds (reference: OpValidator.validate:129 +
        OpCrossValidation fold aggregation :60,118-124)."""
        n = len(y)
        w = np.ones(n) if weights is None else np.asarray(weights, dtype=np.float64)
        if self._streamed_masks is not None:
            # fold masks already built chunk-by-chunk during ingest
            # (validate_stream); bit-identical to train_masks(y)
            masks = self._streamed_masks
        else:
            masks = self.train_masks(y)  # [k, n] True=train
        k = masks.shape[0]
        larger = self.evaluator.larger_better

        # ONE f32 device upload of the design matrix per validate call
        # (ISSUE 15 satellite): shared by every batched family dispatch,
        # the fused training programs, and the successive-halving rung -
        # lazy, so validators whose families all take host paths never
        # pay the [n, d] conversion at all
        _xdev_box: list = []

        def _xdev():
            if not _xdev_box:
                _xdev_box.append(jnp.asarray(X, jnp.float32))
            return _xdev_box[0]

        self.last_train_fused = None
        at_report = None
        pruned_results: list = []
        if self.autotune is not None:
            models, at_report, pruned_results = self._autotune_prune(
                models, X, y, w, masks, larger, xdev=_xdev
            )
        self.last_autotune_report = at_report
        all_results = []
        best = None  # (metric, estimator, params)
        import json as _json
        import time as _time

        # The 1024-bin device approximation of AuROC/AuPR (~5e-3 error)
        # only pays for itself where it saves host-device transfers of the
        # per-fold validation slices: on an accelerator with enough rows.
        # On CPU hosts - or small data, where near-tied candidates could
        # flip on quantization - use the exact host metrics.
        # TX_CV_RANK_METRICS=approx|exact overrides the auto rule (the
        # fused-training parity drills exercise the approx arm on CPU).
        import os as _os

        approx_rank = (
            jax.default_backend() == "tpu" and n >= 100_000
        )
        _rank_env = _os.environ.get("TX_CV_RANK_METRICS", "").strip().lower()
        if _rank_env == "approx":
            approx_rank = True
        elif _rank_env == "exact":
            approx_rank = False

        ckpt = self._ckpt_load()
        self._beat()  # validation started: open the liveness window
        metric_name = getattr(self.evaluator, "metric_name", "")

        # One np.unique scan per validate() at most, and only if some
        # classifier actually asks (regression estimators set
        # batched_needs_binary_y=False and never trigger it).
        _ybin: list = []

        def _labels_ok(est) -> bool:
            if not getattr(est, "batched_needs_binary_y", True):
                return True
            if not _ybin:
                _ybin.append(_binary_labels(y))
            return _ybin[0]

        def _est_mode(est, grid) -> str:
            """Whether THIS estimator's metrics will come from the 1024-bin
            device approximation; only the batched-LR rank-metric branch
            uses it - tree/generic paths are exact on every backend."""
            uses_approx = (
                approx_rank
                and metric_name in ("AuROC", "AuPR")
                and hasattr(est, "fit_arrays_batched")
                and _lr_style_grid(grid)
                and _labels_ok(est)
            )
            return "approx" if uses_approx else "exact"

        def _key(est, pmap, mode) -> str:
            # metric mode is part of the key so checkpoints produced by the
            # approximate device path never mix with exact host metrics
            return (
                f"{est.model_type}:{_json.dumps(pmap, sort_keys=True)}:{mode}"
            )

        for est, grid in models:
            grid = list(grid) or [{}]
            g = len(grid)
            t_est0 = _time.perf_counter()
            mode = _est_mode(est, grid)
            metrics = np.zeros((g, k))
            done_mask = [
                _key(est, pmap, mode) in ckpt for pmap in grid
            ]
            for j, pmap in enumerate(grid):
                if done_mask[j]:
                    metrics[j] = ckpt[_key(est, pmap, mode)]
            if all(done_mask):
                pass  # everything restored from checkpoint
            elif (
                hasattr(est, "fit_arrays_batched")
                and _lr_style_grid(grid)
                and _labels_ok(est)
            ):
                # ONE vmapped fit for the whole fold x grid batch.  Host
                # ships only X (or nothing, if X is already a device
                # array), the [k, n] fold masks and [n] weights - the
                # [B, n] per-candidate weight matrix is tiled ON DEVICE
                # (at 10M rows x 24 candidates that tiling is ~1 GB the
                # tunnel never has to carry).
                regs_g, ens_g = lr_grid_scalars(est, grid)
                regs = np.tile(regs_g, k)  # fold-major [k*g] replicas
                ens = np.tile(ens_g, k)
                mesh = cv_mesh_or_none(k * g)
                # fused training program (ISSUE 15): fit -> score ->
                # rank metrics as ONE donate-buffers jit; falls back to
                # the dispatch below with the reason recorded
                fused_res = None
                if not any(done_mask):
                    fused_res = self._try_train_fused(
                        "linear", est, mode,
                        n=n, mesh_present=mesh is not None,
                        xdev=_xdev, y=y, masks=masks, w=w,
                        weights_given=weights is not None,
                        regs=regs, ens=ens, g=g,
                        candidates=k * g, folds=k, n_rows=n,
                        n_features=int(X.shape[1]),
                    )
                if fused_res is not None:
                    # metrics filled by the one-program dispatch;
                    # the shared tail below checkpoints rows and
                    # builds the per-candidate results exactly as
                    # for the kernel-at-a-time dispatch
                    metrics[:, :] = fused_res.metrics.T
                else:
                    Xj = _xdev()
                    trainj = jnp.asarray(masks).astype(jnp.float32)  # [k, n]
                    if weights is None:
                        Wj = jnp.repeat(trainj, g, axis=0)  # [B, n]
                    else:
                        wj = jnp.asarray(w, jnp.float32)
                        Wj = jnp.repeat(trainj * wj[None, :], g, axis=0)
                    # >1 device: the fold x grid batch shards over 'replica'
                    # and rows over 'data' - XLA inserts the psum collectives
                    # where each replica's Newton reductions cross row shards
                    # (the treeAggregate / Future-pool analog on the mesh).
                    # Rows pad to the data-shard multiple with zero weight in
                    # BOTH the train masks (W=0) and the validation masks
                    # (trainj=1 -> vmask=0), so pads touch no statistic.
                    y_fit = jnp.asarray(y, jnp.float32)
                    host_fit_args = None
                    if mesh is not None:
                        from jax.sharding import NamedSharding, PartitionSpec as P

                        # host-route copies BEFORE padding/placement: the
                        # shrink-to-survivors recompute (parallel/resilience)
                        # reruns the SAME fit from these host-local inputs on
                        # the single-host route - zero-weight padding touches
                        # no statistic, so parity holds to f32 tolerance
                        host_fit_args = (Xj, y_fit, Wj, regs, ens)
                        nd_data = mesh.shape["data"]
                        pad = (-Xj.shape[0]) % nd_data
                        if pad:
                            Xj = jnp.concatenate(
                                [Xj, jnp.zeros((pad, Xj.shape[1]), Xj.dtype)]
                            )
                            Wj = jnp.concatenate(
                                [Wj, jnp.zeros((Wj.shape[0], pad), Wj.dtype)],
                                axis=1,
                            )
                            trainj = jnp.concatenate(
                                [trainj, jnp.ones((k, pad), trainj.dtype)], axis=1
                            )
                            y_fit = jnp.concatenate(
                                [y_fit, jnp.zeros((pad,), y_fit.dtype)]
                            )
                        Xj = jax.device_put(Xj, NamedSharding(mesh, P("data", None)))
                        y_fit = jax.device_put(
                            y_fit, NamedSharding(mesh, P("data"))
                        )
                        Wj = jax.device_put(
                            Wj, NamedSharding(mesh, P("replica", "data"))
                        )
                        regs = jax.device_put(
                            jnp.asarray(regs, jnp.float32),
                            NamedSharding(mesh, P("replica")),
                        )
                        ens = jax.device_put(
                            jnp.asarray(ens, jnp.float32),
                            NamedSharding(mesh, P("replica")),
                        )
                    # ONE span for the whole one-dispatch batch: per-
                    # candidate walls do not exist here, so the cost model
                    # amortizes the batch wall across `candidates`
                    # (satellite: fit spans identify the candidate set)
                    with _obs_trace.span(
                        "cv.fit_batch", family=est.model_type,
                        candidates=int(k * g), folds=int(k),
                        n_rows=int(n), n_features=int(X.shape[1]),
                    ):
                        if mesh is not None:
                            # the fold x grid fit is THE mesh collective of
                            # this path: run it under the collective
                            # watchdog so a hung or dead peer degrades
                            # (straggler retry, then a survivor/single-host
                            # recompute) instead of wedging the whole
                            # selection forever
                            from ..parallel import resilience as _resilience

                            betas, b0s = _resilience.guarded_collective(
                                "validator.fit_arrays_batched",
                                lambda: est.fit_arrays_batched(
                                    Xj, y_fit, Wj, regs, ens),
                                shrink_fn=lambda: est.fit_arrays_batched(
                                    *(np.asarray(a) for a in host_fit_args)),
                            )
                        else:
                            betas, b0s = est.fit_arrays_batched(
                                Xj, y_fit, Wj, regs, ens)
                    if mode == "approx":
                        # rank-based binary metrics computed ON DEVICE against
                        # the already-resident X: no per-fold slices ever leave
                        # HBM (the host loop below ships [n_val, d] k*g times)
                        from ..evaluators.binary import masked_rank_metrics

                        scores = _margins_kernel(
                            Xj, jnp.asarray(betas, jnp.float32),
                            jnp.asarray(b0s, jnp.float32),
                        ).T  # [B, n(+pad)]
                        vmask = jnp.repeat(1.0 - trainj, g, axis=0)
                        auroc_b, aupr_b = masked_rank_metrics(scores, y_fit, vmask)
                        vals = auroc_b if metric_name == "AuROC" else aupr_b
                        for f in range(k):
                            for j in range(g):
                                metrics[j, f] = vals[f * g + j]
                    else:
                        Xh = np.asarray(X)
                        for f in range(k):
                            val = ~masks[f]
                            yv = y[val]
                            for j in range(g):
                                b = f * g + j
                                pred, raw, prob = est.predict_arrays(
                                    {"beta": betas[b], "intercept": b0s[b]},
                                    Xh[val],
                                )
                                metrics[j, f] = self._metric_of(yv, pred, raw, prob)
            elif hasattr(est, "fit_arrays_folds"):
                # fold-batched path (trees): grid x folds in one-or-few
                # device dispatches when the estimator supports whole-grid
                # batching, else one vmapped fit per grid point
                Xh = np.asarray(X)
                W = masks.astype(np.float64) * w[None, :]
                todo = [j for j in range(g) if not done_mask[j]]
                # fused training program (ISSUE 15): the whole grid x
                # fold fit PLUS per-fold traversal scoring and metrics
                # as one donated-buffers jit - heaps never come to host
                fused_res = None
                if len(todo) == g and hasattr(est, "fused_tree_plan"):
                    from ..parallel.mesh import data_mesh_or_none

                    fused_res = self._try_train_fused(
                        "tree", est, mode,
                        n=n, mesh_present=data_mesh_or_none() is not None,
                        X=Xh, y=y, masks=masks, W=W, grid=grid,
                        candidates=int(g * k), folds=k, n_rows=n,
                        n_features=int(X.shape[1]),
                    )
                if fused_res is not None:
                    metrics[:, :] = fused_res.metrics.T
                    todo = []
                grid_fold_params = None
                if todo and hasattr(est, "fit_arrays_folds_grid"):
                    with _obs_trace.span(
                        "cv.fit_batch", family=est.model_type,
                        candidates=int(len(todo) * k), folds=int(k),
                        n_rows=int(n), n_features=int(X.shape[1]),
                    ):
                        grid_fold_params = est.fit_arrays_folds_grid(
                            Xh, y, W, [grid[j] for j in todo]
                        )
                for pos, j in enumerate(todo):
                    pmap = grid[j]
                    cand = est.with_params(**pmap)
                    if grid_fold_params is not None:
                        fold_params = grid_fold_params[pos]
                    else:
                        with _obs_trace.span(
                            "cv.fit_folds", family=est.model_type,
                            params_hash=_params_hash(pmap),
                            folds=int(k), n_rows=int(n),
                            n_features=int(X.shape[1]),
                            **_numeric_params(pmap),
                        ):
                            fold_params = cand.fit_arrays_folds(Xh, y, W)
                    for f in range(k):
                        val = ~masks[f]
                        pred, raw, prob = cand.predict_arrays(
                            fold_params[f], Xh[val]
                        )
                        metrics[j, f] = self._metric_of(y[val], pred, raw, prob)
                    ckpt[_key(est, pmap, mode)] = metrics[j].tolist()
                    self._ckpt_save(ckpt)
            else:
                Xh = np.asarray(X)
                for j, pmap in enumerate(grid):
                    if done_mask[j]:
                        continue
                    cand = est.with_params(**pmap)
                    for f in range(k):
                        tr, val = masks[f], ~masks[f]
                        with _obs_trace.span(
                            "cv.fit", family=est.model_type,
                            params_hash=_params_hash(pmap), fold=int(f),
                            n_rows=int(tr.sum()),
                            n_features=int(X.shape[1]),
                            **_numeric_params(pmap),
                        ):
                            params = cand.fit_arrays(
                                Xh[tr], y[tr], w[tr])
                        pred, raw, prob = cand.predict_arrays(params, Xh[val])
                        metrics[j, f] = self._metric_of(y[val], pred, raw, prob)
                    ckpt[_key(est, pmap, mode)] = metrics[j].tolist()
                    self._ckpt_save(ckpt)
            if not all(done_mask):
                for j, pmap in enumerate(grid):
                    ckpt[_key(est, pmap, mode)] = metrics[j].tolist()
                self._ckpt_save(ckpt)
            mean_metrics = metrics.mean(axis=1)
            for j, pmap in enumerate(grid):
                all_results.append(
                    {
                        "model_type": est.model_type,
                        "model_uid": est.uid,
                        "params": dict(pmap),
                        "metric": float(mean_metrics[j]),
                        "fold_metrics": metrics[j].tolist(),
                        # which evaluator produced these numbers: "approx" =
                        # the 1024-bin device rank metrics, "exact" = host
                        # (consumers like bench FLOPs accounting read this
                        # instead of re-deriving the gate)
                        "rank_metric_mode": mode,
                    }
                )
            j_best = int(np.argmax(mean_metrics) if larger else np.argmin(mean_metrics))
            cand_metric = float(mean_metrics[j_best])
            if best is None or (
                cand_metric > best[0] if larger else cand_metric < best[0]
            ):
                best = (cand_metric, est, dict(grid[j_best]))
            if at_report is not None:
                # predicted-vs-actual trail: measured full-spend wall
                # per family next to the cost model's predictions
                walls = at_report.setdefault(
                    "actual_full_ms_by_family", {})
                walls[est.model_type] = round(
                    walls.get(est.model_type, 0.0)
                    + (_time.perf_counter() - t_est0) * 1e3, 3)

        assert best is not None, "no models to validate"
        if pruned_results:
            # pruned candidates stay visible in the selection metadata
            # (flagged, rung-scored) but can never win - the best scan
            # above saw only survivors' full-CV means
            all_results.extend(pruned_results)
        return ValidationResult(
            best_estimator=best[1].with_params(**best[2]),
            best_params=best[2],
            best_metric=best[0],
            metric_name=self.evaluator.metric_name,
            larger_better=larger,
            all_results=all_results,
            autotune=at_report,
            train_fused=self.last_train_fused,
        )


def _lr_style_grid(grid: Sequence[dict]) -> bool:
    """Batched path applies when every grid key is a batched-fit scalar."""
    ok = {"reg_param", "elastic_net_param"}
    return all(set(p) <= ok for p in grid)


def _binary_labels(y) -> bool:
    """The batched LR/SVC kernels assume y in {0,1}; multiclass labels
    must take the generic per-candidate path, where fit_arrays routes to
    the one-vs-rest fit (a 3-class label through the binary batched
    kernel would silently fit sigmoid-on-{0,1,2} garbage)."""
    return len(np.unique(np.asarray(y))) <= 2


def lr_grid_scalars(est, grid: Sequence[dict]) -> tuple[np.ndarray, np.ndarray]:
    """Per-grid-point (regs, ens) for fit_arrays_batched, defaulting from
    the estimator's params - the single source of the batched-LR grid
    contract (shared by validate() and workflow-CV's per-fold path)."""
    regs = np.array(
        [p.get("reg_param", est.params.get("reg_param", 0.0)) for p in grid]
    )
    ens = np.array(
        [p.get("elastic_net_param", est.params.get("elastic_net_param", 0.0))
         for p in grid]
    )
    return regs, ens


class OpCrossValidation(OpValidator):
    """(reference: OpCrossValidation.scala - numFolds default 3)"""

    def __init__(
        self,
        num_folds: int = 3,
        evaluator: Optional[OpEvaluatorBase] = None,
        seed: int = 42,
        stratify: bool = False,
        checkpoint_path: Optional[str] = None,
        autotune=None,
    ) -> None:
        super().__init__(evaluator, seed, stratify, checkpoint_path,
                         autotune=autotune)
        self.num_folds = num_folds

    def train_masks(self, y: np.ndarray) -> np.ndarray:
        return stratified_kfold_masks(y, self.num_folds, self.seed, self.stratify)


class OpTrainValidationSplit(OpValidator):
    """(reference: OpTrainValidationSplit.scala - trainRatio default 0.75)"""

    def __init__(
        self,
        train_ratio: float = 0.75,
        evaluator: Optional[OpEvaluatorBase] = None,
        seed: int = 42,
        stratify: bool = False,
        checkpoint_path: Optional[str] = None,
        autotune=None,
    ) -> None:
        super().__init__(evaluator, seed, stratify, checkpoint_path,
                         autotune=autotune)
        self.train_ratio = train_ratio

    def train_masks(self, y: np.ndarray) -> np.ndarray:
        n = len(y)
        rng = np.random.RandomState(self.seed)
        if self.stratify:
            mask = np.zeros(n, dtype=bool)
            for c in np.unique(y):
                idx = np.nonzero(y == c)[0]
                perm = rng.permutation(idx)
                mask[perm[: int(np.ceil(len(idx) * self.train_ratio))]] = True
        else:
            perm = rng.permutation(n)
            mask = np.zeros(n, dtype=bool)
            mask[perm[: int(np.ceil(n * self.train_ratio))]] = True
        return mask[None, :]
