"""ModelSelector: automated model selection.

Counterpart of the reference ModelSelector (reference: core/.../impl/
selector/ModelSelector.scala:74-197): an estimator over (label RealNN,
features OPVector) -> Prediction that

1. runs splitter preparation (rebalancing as sample weights, §splitters),
2. hands candidate estimators x hyperparameter grids to the validator,
   which fans folds x grid points out as one vmapped batch on device,
3. refits the winning candidate on the full prepared training data,
4. evaluates training (and, via has_test_eval, holdout) metrics with every
   registered evaluator,
5. writes a ModelSelectorSummary into stage metadata.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..evaluators.base import OpEvaluatorBase
from ..models.base import PredictorEstimator, PredictorModel
from ..types.columns import Column, NumericColumn, VectorColumn
from ..types.dataset import Dataset
from ..types.feature_types import OPVector, Prediction, RealNN
from ..stages.base import Estimator
from .splitters import Splitter
from .validator import OpValidator, ValidationResult


class SelectedModel(PredictorModel):
    """Fitted best model (reference: SelectedModel in ModelSelector.scala).
    Adds holdout evaluation used by the workflow's test-eval hook."""

    def __init__(self, estimator, params, selector: "ModelSelector", **kw) -> None:
        super().__init__(estimator, params, **kw)
        self.selector = selector

    def evaluate_model(self, holdout: Dataset) -> dict:
        """(reference: FitStagesUtil.scala:266-268 HasTestEval path)"""
        label_f, vec_f = self.input_features
        y = np.asarray(holdout[label_f.name].values, dtype=np.float64)
        X = np.asarray(holdout[vec_f.name].values, dtype=np.float64)
        pred, raw, prob = self.estimator_ref.predict_arrays(self.model_params, X)
        from ..types.columns import PredictionColumn

        pc = PredictionColumn(pred, raw, prob)
        out = {}
        for ev in self.selector.evaluators:
            m = ev.evaluate_arrays(y, pc)
            out[type(ev).__name__] = m.to_json()
        self.holdout_metrics = out
        md = self.metadata.get("model_selector_summary", {})
        md["holdout_metrics"] = _strip_curves(out)
        self.metadata["model_selector_summary"] = md
        return out


def _strip_curves(metrics: dict) -> dict:
    """Keep scalar metrics only in the summary blob."""
    clean = {}
    for ev_name, m in metrics.items():
        clean[ev_name] = {
            k: v for k, v in m.items() if isinstance(v, (int, float, str, bool))
        }
    return clean


class ModelSelector(Estimator):
    input_types = [RealNN, OPVector]
    output_type = Prediction
    is_model_selector = True
    has_test_eval = True

    def __init__(
        self,
        validator: OpValidator,
        models: Sequence[tuple[PredictorEstimator, Sequence[dict]]],
        splitter: Optional[Splitter] = None,
        evaluators: Sequence[OpEvaluatorBase] = (),
        **kw,
    ) -> None:
        super().__init__(**kw)
        self.validator = validator
        self.models = list(models)
        self.splitter = splitter
        self.evaluators = list(evaluators)
        self.validation_result: Optional[ValidationResult] = None

    def fit_model(self, cols: Sequence[Column], ds: Dataset):
        label, vec = cols
        assert isinstance(label, NumericColumn)
        assert isinstance(vec, VectorColumn)
        y = np.asarray(label.values, dtype=np.float64)
        X = np.asarray(vec.values, dtype=np.float64)
        if len(y) == 0:
            raise ValueError(
                "empty dataset (reference guard: ModelSelector.scala:148)"
            )

        weights = np.ones(len(y))
        splitter_summary = {}
        if self.splitter is not None:
            prepared = self.splitter.prepare(y)
            splitter_summary = prepared.summary
            weights = prepared.weights
            if prepared.keep_mask is not None:
                keep = prepared.keep_mask
                X, y, weights = X[keep], y[keep], weights[keep]

        result = self.validator.validate(self.models, X, y, weights)
        self.validation_result = result

        # refit best on full prepared train (reference:
        # ModelSelector.scala:159-160)
        best = result.best_estimator
        best_params = best.fit_arrays(X, y, weights)
        model = SelectedModel(best, best_params, self)

        # training-set evaluation with all evaluators
        pred, raw, prob = best.predict_arrays(best_params, X)
        from ..types.columns import PredictionColumn

        pc = PredictionColumn(pred, raw, prob)
        train_metrics = {
            type(ev).__name__: ev.evaluate_arrays(y, pc).to_json()
            for ev in self.evaluators
        }

        model.metadata = {
            "model_selector_summary": {
                "best_model_type": best.model_type,
                "best_model_uid": best.uid,
                "best_params": result.best_params,
                "validation_metric": {
                    "name": result.metric_name,
                    "value": result.best_metric,
                    "larger_better": result.larger_better,
                },
                "validation_results": result.all_results,
                "splitter_summary": splitter_summary,
                "train_metrics": _strip_curves(train_metrics),
                "n_rows": int(len(y)),
                "n_features": int(X.shape[1]),
            }
        }
        self.metadata = model.metadata
        return model
