"""ModelSelector: automated model selection.

Counterpart of the reference ModelSelector (reference: core/.../impl/
selector/ModelSelector.scala:74-197): an estimator over (label RealNN,
features OPVector) -> Prediction that

1. runs splitter preparation (rebalancing as sample weights, §splitters),
2. hands candidate estimators x hyperparameter grids to the validator,
   which fans folds x grid points out as one vmapped batch on device,
3. refits the winning candidate on the full prepared training data,
4. evaluates training (and, via has_test_eval, holdout) metrics with every
   registered evaluator,
5. writes a ModelSelectorSummary into stage metadata.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..evaluators.base import OpEvaluatorBase
from ..models.base import PredictorEstimator, PredictorModel
from ..types.columns import Column, NumericColumn, VectorColumn
from ..types.dataset import Dataset
from ..types.feature_types import OPVector, Prediction, RealNN
from ..stages.base import Estimator
from .splitters import Splitter
from .validator import OpValidator, ValidationResult


class SelectedModel(PredictorModel):
    """Fitted best model (reference: SelectedModel in ModelSelector.scala).
    Adds holdout evaluation used by the workflow's test-eval hook."""

    def __init__(self, estimator, params, selector: "ModelSelector", **kw) -> None:
        super().__init__(estimator, params, **kw)
        self.selector = selector

    def evaluate_model(self, holdout: Dataset) -> dict:
        """(reference: FitStagesUtil.scala:266-268 HasTestEval path)"""
        label_f, vec_f = self.input_features
        y = np.asarray(holdout[label_f.name].values, dtype=np.float64)
        X = np.asarray(holdout[vec_f.name].values, dtype=np.float64)
        pred, raw, prob = self.estimator_ref.predict_arrays(self.model_params, X)
        from ..types.columns import PredictionColumn

        pc = PredictionColumn(pred, raw, prob)
        out = {}
        for ev in self.selector.evaluators:
            m = ev.evaluate_arrays(y, pc)
            out[type(ev).__name__] = m.to_json()
        self.holdout_metrics = out
        md = self.metadata.get("model_selector_summary", {})
        md["holdout_metrics"] = _strip_curves(out)
        self.metadata["model_selector_summary"] = md
        return out


def _strip_curves(metrics: dict) -> dict:
    """Keep scalar metrics only in the summary blob."""
    clean = {}
    for ev_name, m in metrics.items():
        clean[ev_name] = {
            k: v for k, v in m.items() if isinstance(v, (int, float, str, bool))
        }
    return clean


class ModelSelector(Estimator):
    input_types = [RealNN, OPVector]
    output_type = Prediction
    is_model_selector = True
    has_test_eval = True

    def __init__(
        self,
        validator: OpValidator,
        models: Sequence[tuple[PredictorEstimator, Sequence[dict]]],
        splitter: Optional[Splitter] = None,
        evaluators: Sequence[OpEvaluatorBase] = (),
        **kw,
    ) -> None:
        super().__init__(**kw)
        self.validator = validator
        self.models = list(models)
        self.splitter = splitter
        self.evaluators = list(evaluators)
        self.validation_result: Optional[ValidationResult] = None
        # workflow-level CV: when set, fit_model skips its own validation and
        # uses this result (reference: findBestEstimator,
        # ModelSelector.scala:113-123)
        self.best_override: Optional[ValidationResult] = None

    def find_best_estimator(
        self, ds: Dataset, during_stages: Sequence, seed_data_prepare=None
    ) -> ValidationResult:
        """Workflow-level CV (reference: ModelSelector.findBestEstimator:
        113-123 -> OpValidator in-fold DAG refit :230-256): for each fold,
        refit every 'during' estimator (e.g. the SanityChecker) on the
        fold's train rows only, transform both splits with the fold-fitted
        stages, then score every candidate x grid on the fold's validation
        rows.  Eliminates leakage from label-aware upstream estimators."""
        import numpy as np

        from ..workflow.workflow import fit_and_transform_dag

        label_f, vec_f = self.input_features
        y_full = np.asarray(ds[label_f.name].values, dtype=np.float64)
        weights = np.ones(len(y_full))
        if self.splitter is not None:
            prepared = self.splitter.prepare(y_full)
            weights = prepared.weights
            if prepared.keep_mask is not None:
                ds = ds.take(np.nonzero(prepared.keep_mask)[0])
                y_full = y_full[prepared.keep_mask]
                weights = weights[prepared.keep_mask]

        masks = self.validator.train_masks(y_full)
        larger = self.validator.evaluator.larger_better
        non_selector = [s for s in during_stages if s is not self]
        results: dict[int, list[dict]] = {}
        self.validator._beat()  # liveness for the preemption supervisor
        for f in range(masks.shape[0]):
            tr_idx = np.nonzero(masks[f])[0]
            val_idx = np.nonzero(~masks[f])[0]
            fold_train, fold_val = ds.take(tr_idx), ds.take(val_idx)
            if non_selector:
                # deep-ish copy stages so full-data refit stays clean
                stages = [s.copy() for s in non_selector]
                for orig, cp in zip(non_selector, stages):
                    cp.input_features = orig.input_features
                    cp._output = orig._output
                _, fold_train, fold_val = fit_and_transform_dag(
                    [[s] for s in stages], fold_train, fold_val
                )
            Xt = np.asarray(fold_train[vec_f.name].values, dtype=np.float64)
            yt = np.asarray(fold_train[label_f.name].values, dtype=np.float64)
            Xv = np.asarray(fold_val[vec_f.name].values, dtype=np.float64)
            yv = np.asarray(fold_val[label_f.name].values, dtype=np.float64)
            wt = weights[tr_idx]
            gi = 0
            for est, grid in self.models:
                grid = list(grid) or [{}]
                fold_params = self._fit_fold_candidates(
                    est, grid, Xt, yt, wt
                )
                for pmap, params in zip(grid, fold_params):
                    cand = est.with_params(**pmap)
                    pred, raw, prob = cand.predict_arrays(params, Xv)
                    m = self.validator._metric_of(yv, pred, raw, prob)
                    results.setdefault(gi, []).append(
                        {"model_type": est.model_type, "est": est,
                         "params": dict(pmap), "metric": m}
                    )
                    gi += 1
            self.validator._beat()  # one beat per completed fold
        all_results = []
        best = None
        for gi, fold_results in results.items():
            mean_m = float(np.mean([r["metric"] for r in fold_results]))
            r0 = fold_results[0]
            all_results.append(
                {
                    "model_type": r0["model_type"],
                    "model_uid": r0["est"].uid,
                    "params": r0["params"],
                    "metric": mean_m,
                    "fold_metrics": [r["metric"] for r in fold_results],
                }
            )
            if best is None or (mean_m > best[0] if larger else mean_m < best[0]):
                best = (mean_m, r0["est"], r0["params"])
        result = ValidationResult(
            best_estimator=best[1].with_params(**best[2]),
            best_params=best[2],
            best_metric=best[0],
            metric_name=self.validator.evaluator.metric_name,
            larger_better=larger,
            all_results=all_results,
        )
        self.best_override = result
        return result

    @staticmethod
    def _fit_fold_candidates(est, grid, Xt, yt, wt) -> list:
        """Train one estimator's whole grid on one fold's train split with
        the SAME batched dispatches the plain validator uses (folds differ
        in data under workflow CV, so only the grid axis batches here):
        LR-style grids ride fit_arrays_batched, tree grids ride
        fit_arrays_folds_grid with a single fold row.  Falls back to
        per-candidate fits for estimators with no batched path."""
        from .validator import _binary_labels, _lr_style_grid, lr_grid_scalars

        g = len(grid)
        if (
            g > 1
            and hasattr(est, "fit_arrays_batched")
            and _lr_style_grid(grid)
            and (
                not getattr(est, "batched_needs_binary_y", True)
                or _binary_labels(yt)
            )
        ):
            import jax.numpy as jnp

            # tile the [n] weight vector ON DEVICE: one transfer, not g
            # identical host copies (same move as validator.py's batched
            # branch)
            W = jnp.repeat(
                jnp.asarray(wt, jnp.float32)[None, :], g, axis=0
            )
            regs, ens = lr_grid_scalars(est, grid)
            betas, b0s = est.fit_arrays_batched(Xt, yt, W, regs, ens)
            return [
                {"beta": betas[j], "intercept": float(b0s[j])}
                for j in range(g)
            ]
        if g > 1 and hasattr(est, "fit_arrays_folds_grid"):
            by_grid = est.fit_arrays_folds_grid(
                Xt, yt, np.asarray(wt, np.float64)[None, :], grid
            )
            if by_grid is not None:
                return [by_grid[j][0] for j in range(g)]
        return [
            est.with_params(**pmap).fit_arrays(Xt, yt, wt) for pmap in grid
        ]

    def fit_model(self, cols: Sequence[Column], ds: Dataset):
        from ..models.base import _check_label_mask

        label, vec = cols
        assert isinstance(label, NumericColumn)
        assert isinstance(vec, VectorColumn)
        _check_label_mask(label, self)
        y = np.asarray(label.values, dtype=np.float64)
        X = np.asarray(vec.values, dtype=np.float64)
        if len(y) == 0:
            raise ValueError(
                "empty dataset (reference guard: ModelSelector.scala:148)"
            )

        weights = np.ones(len(y))
        splitter_summary = {}
        if self.splitter is not None:
            prepared = self.splitter.prepare(y)
            splitter_summary = prepared.summary
            weights = prepared.weights
            if prepared.keep_mask is not None:
                keep = prepared.keep_mask
                X, y, weights = X[keep], y[keep], weights[keep]

        if self.best_override is not None:
            result = self.best_override
        else:
            result = self.validator.validate(self.models, X, y, weights)
        self.validation_result = result

        # refit best on full prepared train (reference:
        # ModelSelector.scala:159-160)
        best = result.best_estimator
        best_params = best.fit_arrays(X, y, weights)
        model = SelectedModel(best, best_params, self)

        # training-set evaluation with all evaluators
        pred, raw, prob = best.predict_arrays(best_params, X)
        from ..types.columns import PredictionColumn

        pc = PredictionColumn(pred, raw, prob)
        train_metrics = {
            type(ev).__name__: ev.evaluate_arrays(y, pc).to_json()
            for ev in self.evaluators
        }

        model.metadata = {
            "model_selector_summary": {
                "best_model_type": best.model_type,
                "best_model_uid": best.uid,
                "best_params": result.best_params,
                "validation_metric": {
                    "name": result.metric_name,
                    "value": result.best_metric,
                    "larger_better": result.larger_better,
                },
                "validation_results": result.all_results,
                "splitter_summary": splitter_summary,
                "train_metrics": _strip_curves(train_metrics),
                "n_rows": int(len(y)),
                "n_features": int(X.shape[1]),
            }
        }
        if result.autotune is not None:
            # the successive-halving decision trail (ISSUE 13): rungs,
            # prunes, predicted-vs-actual times - rides the stage
            # metadata into summary_json() and the saved summary.json
            model.metadata["model_selector_summary"]["autotune"] = (
                result.autotune
            )
        if result.train_fused is not None:
            # the fused-training dispatch trail (ISSUE 15): which family
            # dispatches ran fused / AOT-loaded / retraced, mirroring
            # the PR-12 serving fused.cache telemetry shape - the
            # continuous-refit loop asserts warm refits skip retrace on
            # exactly this record
            model.metadata["model_selector_summary"]["train_fused"] = (
                result.train_fused
            )
        self.metadata = model.metadata
        return model
