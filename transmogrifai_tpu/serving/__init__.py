"""Batched, compiled model serving with admission control + telemetry.

The production-serving tier the reference delegates to MLeap local
scoring (reference: local/.../OpWorkflowModelLocal.scala) rebuilt
batch-first for this engine: a micro-batching scheduler packs concurrent
requests into fixed shape buckets so every predict rides the vectorized
flat-heap / jitted batch paths, admission control sheds load gracefully,
a circuit breaker turns persistent batch-path failure into fast loud
shedding (with a NaN/Inf output guard) instead of a silent slow-path
meltdown, schema/distribution drift guards validate every batch against
the contract the model trained under (schema/: ``SchemaDriftError``,
``drift_policy="raise"|"warn"|"shed"``, per-feature JS drift scores),
and built-in telemetry reports p50/p95/p99 latency, batch fill, queue
depth, rows/s, breaker transitions, and drift as a JSON artifact.

    endpoint = compile_endpoint(model)           # warmed, bucketed
    with MicroBatchScheduler(endpoint) as srv:
        result = srv.score(record, timeout_s=1.0)
    endpoint.telemetry.export("serving_metrics.json")
"""
from ..schema.contract import SchemaDriftError
from .admission import (
    AdmissionController,
    BreakerOpenError,
    CircuitBreaker,
    DeadlineExceededError,
    QueueFullError,
    RequestTimeoutError,
    TenantQuotaError,
)
from .endpoint import (
    CompiledEndpoint,
    RowScoringError,
    compile_endpoint,
    records_from_dataset,
)
from .scheduler import MicroBatchScheduler
from .telemetry import ServingTelemetry

__all__ = [
    "AdmissionController",
    "BreakerOpenError",
    "CircuitBreaker",
    "CompiledEndpoint",
    "DeadlineExceededError",
    "MicroBatchScheduler",
    "QueueFullError",
    "RequestTimeoutError",
    "RowScoringError",
    "SchemaDriftError",
    "ServingTelemetry",
    "TenantQuotaError",
    "compile_endpoint",
    "records_from_dataset",
]
