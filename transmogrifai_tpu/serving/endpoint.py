"""Compiled, batch-first scoring endpoint over a fitted OpWorkflowModel.

The serving analog of the reference's MLeap-compiled local model
(reference: local/.../OpWorkflowModelLocal.scala:30-120 compiles a fitted
pipeline once into a reusable score function) built batch-FIRST: the
single-row contract the reference exposes is the degenerate case here,
not the design center.

* the scoring DAG resolves ONCE at construction (the LocalScorer's
  precompiled (stage, inputs, output) plan, numpy predict paths);
* requests score through fixed shape BUCKETS (pad to the next bucket, so
  repeated batch shapes reuse every shape-keyed cache: one-hot code
  memos, fitted-metadata memos, and - for any stage that does dispatch
  to jax - its jit cache);
* tree predicts hit ONE flat-heap C++/vectorized-numpy call per batch
  (models/trees.predict_arrays_np), never a per-row or per-tree loop;
* construction warm-up primes each bucket ahead of traffic, so the
  first real request never pays cold-path latency;
* a batch that fails the compiled path degrades gracefully: rows re-score
  individually through the row fallback, bad rows surface as
  ``RowScoringError`` results instead of poisoning their batch peers;
* a circuit breaker (admission.CircuitBreaker) watches batch-path
  health: K consecutive compiled-path failures open it, after which
  requests shed FAST (``BreakerOpenError``) instead of silently running
  every row through the slow fallback loop, until a half-open probe
  batch proves the path healthy again;
* a NaN/Inf output guard refuses non-finite scores (a poisoned model
  or kernel must fail loudly, not serve garbage) - guarded rows count
  as batch-path failures toward the breaker.

Fault-injection points (faults/injection.py): ``serving.batch`` (raise
inside the compiled path), ``serving.nan_scores`` (poison outputs),
``serving.slow_batch`` (sleep) - the drills in tests/test_faults.py
prove the breaker, the guard, and the fallback end to end.
"""
from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass
from typing import Any, Mapping, Optional, Sequence

from ..faults import injection as _faults
from ..local.scorer import LocalScorer
from ..obs import trace as _obs_trace
from ..schema.contract import (
    SchemaDriftError,
    apply_drift_policy,
    collect_violations,
)
from ..schema.drift import DriftMonitor
from .admission import CircuitBreaker
from .telemetry import ServingTelemetry

log = logging.getLogger("transmogrifai_tpu.serving")

DEFAULT_BUCKETS = (1, 8, 32, 128)

DRIFT_POLICIES = ("raise", "warn", "shed")


@dataclass
class RowScoringError:
    """Per-row failure marker returned in a batch's result list (the
    scheduler converts it into the request's exception; direct batch
    callers can filter).  ``shed`` marks rows refused unscored, with
    ``shed_reason`` naming why: ``"breaker"`` (circuit open — scheduler
    accounting shed_breaker) or ``"schema"`` (contract violation under
    drift_policy='shed' — accounting shed_schema)."""

    error: str
    shed: bool = False
    shed_reason: str = "breaker"


class CompiledEndpoint:
    """Batch-first compiled scorer with shape buckets + row fallback."""

    def __init__(
        self,
        model,
        batch_buckets: Sequence[int] = DEFAULT_BUCKETS,
        warm: bool = True,
        warm_record: Optional[Mapping[str, Any]] = None,
        telemetry: Optional[ServingTelemetry] = None,
        breaker: Optional[CircuitBreaker] = None,
        breaker_threshold: int = 5,
        breaker_cooldown_s: float = 5.0,
        guard_nonfinite: bool = True,
        contract=None,
        drift_policy: str = "warn",
        drift_scores: bool = True,
        fused: bool = True,
        fused_backend: Optional[str] = None,
        knob_source: str = "hand_set",
    ) -> None:
        if not batch_buckets or any(int(b) < 1 for b in batch_buckets):
            raise ValueError("batch_buckets must be positive sizes")
        if drift_policy not in DRIFT_POLICIES:
            raise ValueError(
                f"drift_policy must be one of {DRIFT_POLICIES}, got "
                f"{drift_policy!r}"
            )
        self.batch_buckets = tuple(sorted({int(b) for b in batch_buckets}))
        #: who owns the shape buckets: 'hand_set' defaults or the
        #: autotune bucket proposer (ISSUE 13)
        self.knob_source = str(knob_source)
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
        )
        self.telemetry = telemetry if telemetry is not None else ServingTelemetry()
        self.guard_nonfinite = bool(guard_nonfinite)
        # schema/distribution drift guards: the contract the model
        # trained under (loaded from the artifact's schema.json) is
        # enforced per batch; the inner scorer's own validation is OFF -
        # the endpoint owns it, validating twice would be pure overhead
        self.contract = (
            contract if contract is not None
            else getattr(model, "schema_contract", None)
        )
        self.drift_policy = drift_policy
        self._warned_violations: set = set()
        self._drift_monitor: Optional[DriftMonitor] = None
        self._drift_pending: list = []
        self._drift_lock = threading.Lock()
        if (drift_scores and self.contract is not None
                and self.contract.distributions):
            self._drift_monitor = DriftMonitor(self.contract)
        self._scorer = LocalScorer(model, drift_policy=None, fused=fused,
                                   fused_backend=fused_backend)
        # the pad row: scored to fill a bucket, sliced off before return.
        # All-None raw features ride the same missing-value handling every
        # stage already implements; a caller-provided warm_record is used
        # instead when the pipeline requires non-null rows.
        self._pad_record: Mapping[str, Any] = dict(
            warm_record
            if warm_record is not None
            else {f.name: None for f in self._scorer.raw_features}
        )
        self.shape_misses = 0
        self.warmed_buckets: tuple[int, ...] = ()
        self.warm_error: Optional[str] = None
        self._push_knob_status()
        if warm:
            self.warm_up()
        self._push_fused_status()

    def _push_knob_status(self) -> None:
        """Record bucket-knob provenance (ISSUE 13) into whatever
        telemetry accumulator is currently attached, so tuned-vs-
        hand-set stays visible across accumulator swaps."""
        bb = getattr(self, "batch_buckets", None)
        if not bb:  # telemetry attached before construction finished
            return
        self._telemetry.set_tuned_knobs(
            {
                "batch_bucket_top": bb[-1],
                "batch_bucket_count": len(bb),
                "batch_buckets": ",".join(str(b) for b in bb),
            },
            source=getattr(self, "knob_source", "hand_set"),
        )

    @property
    def telemetry(self) -> ServingTelemetry:
        return self._telemetry

    @telemetry.setter
    def telemetry(self, value: ServingTelemetry) -> None:
        # breaker transitions must land wherever request telemetry lands,
        # including after a caller swaps the accumulator (bench does)
        self._telemetry = value
        self.breaker.telemetry = value
        self._push_fused_status()
        self._push_knob_status()

    # -- fused-path status --------------------------------------------------
    @property
    def fused(self) -> bool:
        """True when batches score through the whole-pipeline fused
        program (local/fused.py) rather than the interpreted DAG walk."""
        return self._scorer.fused is not None

    @property
    def fused_reason(self) -> Optional[str]:
        return self._scorer.fused_reason

    @property
    def fused_backend(self) -> Optional[str]:
        """'xla' | 'numpy' | None: which fused program serves batches
        (None = interpreted DAG walk)."""
        return self._scorer.fused_backend

    def _push_fused_status(self) -> None:
        """Mirror the scorer's fused status + per-bucket compile times
        (and, on the XLA backend, the trace/compile/load/first-exec
        split + executable-cache events) into whatever telemetry
        accumulator is currently attached (the choice and its cost must
        ride every serving artifact)."""
        scorer = getattr(self, "_scorer", None)
        if scorer is None:  # telemetry attached before construction done
            return
        fp = scorer.fused
        self._fused_buckets_pushed = (
            len(fp.compile_ms) if fp is not None else 0
        )
        self._telemetry.set_fused_status(
            fp is not None,
            scorer.fused_reason,
            dict(fp.compile_ms) if fp is not None else None,
            backend=scorer.fused_backend,
            bucket_timings=(
                {k: dict(v) for k, v in fp.bucket_stats.items()}
                if fp is not None and getattr(fp, "bucket_stats", None)
                else None
            ),
            cache_events=(
                dict(fp.cache_events)
                if fp is not None and getattr(fp, "cache_events", None)
                else None
            ),
        )

    # -- warm-up ------------------------------------------------------------
    def warm_up(self) -> tuple[int, ...]:
        """Score one pad-batch per bucket ahead of traffic: primes the
        one-hot/metadata memos and any jit cache for EXACTLY the shapes
        the bucketed hot path will submit.  Best-effort: a pipeline that
        cannot score the pad record serves cold (warm_error records why)."""
        warmed = []
        try:
            for b in self.batch_buckets:
                self._scorer.score_batch([self._pad_record] * b)
                warmed.append(b)
        except Exception as e:  # noqa: BLE001 - warm-up must never kill serving
            self.warm_error = f"{type(e).__name__}: {e}"
            log.warning("endpoint warm-up failed (serving cold, exact "
                        "batch shapes): %s", self.warm_error)
        self.warmed_buckets = tuple(warmed)
        return self.warmed_buckets

    # -- scoring ------------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (callers chunk at the largest bucket)."""
        for b in self.batch_buckets:
            if n <= b:
                return b
        return self.batch_buckets[-1]

    def score_batch(self, records: Sequence[Mapping[str, Any]]) -> list:
        """Score a batch through the bucketed compiled path; element i of
        the result aligns with records[i] (RowScoringError on failure).
        An empty batch (all rows quarantined upstream) is a counted
        no-op, never an exception - pinned to LocalScorer's behavior."""
        if not records:
            self.telemetry.record_empty_batch()
            return []
        shed = self._enforce_contract(records)
        if shed is not None:
            return shed
        out: list = []
        step = self.batch_buckets[-1]
        for lo in range(0, len(records), step):
            chunk = records[lo:lo + step]
            # one span per bucketed chunk (obs/): bucket + fused status
            # tagged so a slow batch in the trace names its shape and
            # whether it rode the fused program or the interpreted walk
            with _obs_trace.span(
                "serve.batch", n=len(chunk),
                bucket=self.bucket_for(len(chunk)), fused=self.fused,
                fused_reason=self.fused_reason,
            ):
                out.extend(self._score_bucketed(chunk))
        self._observe_drift(records)
        return out

    # -- schema/distribution drift guards -----------------------------------
    def _enforce_contract(
        self, records: Sequence[Mapping[str, Any]]
    ) -> Optional[list]:
        """Validate a batch against the training contract and apply
        ``drift_policy``.  Returns None to proceed with scoring, or the
        full shed-marker result list (policy='shed').

        Enforcement is BATCH-granular by design (missing-column
        detection is a property of the batch's key union, and one
        validation per batch keeps the hot path O(1)-ish): under
        raise/shed, conformant requests micro-batched together with a
        violating one share its outcome for that batch.  Deployments
        mixing untrusted clients behind one scheduler should prefer
        ``drift_policy="warn"`` (violations counted + logged, rows
        still served) or segregate clients per endpoint."""
        extra = ()
        if _faults.fires("serving.schema_drift") is not None:
            extra = ({
                "kind": "injected",
                "feature": "<injected>",
                "detail": "serving.schema_drift fault armed",
            },)
        # the validate + policy dispatch is the ONE shared implementation
        # in schema/contract.py (the local scorer runs the same code, so
        # the two serve surfaces cannot diverge); only the telemetry +
        # shed-marker mechanics are endpoint-specific
        violations = collect_violations(self.contract, records, extra)
        if not violations:
            return None
        self.telemetry.record_schema_violations(
            violations, self.drift_policy
        )
        shed = apply_drift_policy(violations, self.drift_policy,
                                  self._warned_violations, log,
                                  "endpoint serving anyway")
        if not shed:
            return None
        # shed: refuse the batch unscored, loudly and cheaply - the
        # endpoint stays healthy for conformant traffic
        self.telemetry.record_schema_shed_rows(len(records))
        err = SchemaDriftError(violations)
        return [
            RowScoringError(str(err), shed=True, shed_reason="schema")
            for _ in records
        ]

    #: drift observation amortization: scored records buffer until this
    #: many rows, then fold into the running distributions in ONE
    #: vectorized pass - per-histogram python overhead on the batch-of-1
    #: hot path would otherwise cost ~2/3 of single-row throughput
    DRIFT_OBSERVE_MIN_ROWS = 64

    def _observe_drift(
        self, records: Sequence[Mapping[str, Any]]
    ) -> None:
        """Buffer the batch toward the running serve-side distributions;
        per-feature JS divergence lands in telemetry once per observe
        window.  Monitoring must never take scoring down."""
        if self._drift_monitor is None:
            return
        with self._drift_lock:
            self._drift_pending.extend(records)
            if len(self._drift_pending) < self.DRIFT_OBSERVE_MIN_ROWS:
                return
            pending, self._drift_pending = self._drift_pending, []
        try:
            self._drift_monitor.observe(pending)
            self.telemetry.record_drift_scores(
                self._drift_monitor.scores()
            )
        except Exception:  # noqa: BLE001 - monitoring only
            log.warning("drift monitoring failed for a batch",
                        exc_info=True)

    def drift_scores(self) -> dict[str, float]:
        """Current per-feature JS divergence vs the training
        distributions (empty when the model has no contract).  Flushes
        the observation buffer so the scores reflect every row scored
        so far."""
        if self._drift_monitor is None:
            return {}
        with self._drift_lock:
            pending, self._drift_pending = self._drift_pending, []
        if pending:
            try:
                self._drift_monitor.observe(pending)
            except Exception:  # noqa: BLE001 - monitoring only
                log.warning("drift flush failed", exc_info=True)
        scores = self._drift_monitor.scores()
        if scores:
            self.telemetry.record_drift_scores(scores)
        return scores

    def _score_bucketed(self, records: Sequence[Mapping[str, Any]]) -> list:
        n = len(records)
        if n == 0:
            return []
        if not self.breaker.allow():
            # open breaker: shed FAST with an explicit marker instead of
            # burning the slow row loop on every request while the batch
            # path is known-bad (meltdown protection + a loud signal)
            self.telemetry.record_breaker_shed_rows(n)
            return [
                RowScoringError(
                    "serving batch path unhealthy (circuit breaker open); "
                    "request shed",
                    shed=True,
                )
                for _ in range(n)
            ]
        bucket = self.bucket_for(n)
        if self.warm_error is not None:
            # the pad record itself cannot score through this pipeline
            # (warm-up told us): padding every partial batch with it
            # would silently degrade ALL serving to the per-row fallback.
            # Score the exact batch instead - no shape bucketing, but the
            # batch path stays hot.
            padded = list(records)
        else:
            padded = list(records) + [self._pad_record] * (bucket - n)
        t0 = time.perf_counter()
        # inside the timed window: injected slowness must be VISIBLE to
        # batch telemetry, or the drill proves nothing
        _faults.inject_sleep("serving.slow_batch")
        poisoned = False
        try:
            _faults.inject("serving.batch")
            results = self._scorer.score_batch(padded)[:n]
            if _faults.fires("serving.nan_scores"):
                poisoned = True
                _faults.poison_nonfinite(results)
        except Exception:  # noqa: BLE001 - degrade to the row path
            # shape miss / malformed row: the compiled batch path assumes
            # bucket-shaped well-formed batches; anything else re-scores
            # row by row so one bad request cannot fail its batch peers.
            # Deliberately NOT record_batch: these rows never rode the
            # batch path, and counting them would make batch_rows_per_s /
            # batch-fill read nominal while serving is fully degraded -
            # rows_fallback is the truth signal
            self.shape_misses += 1
            results = self._score_rows_fallback(records)
            self.telemetry.record_fallback_rows(n)
            # breaker accounting distinguishes WHY the batch path failed:
            # rows that ALSO fail individually are data-borne (a poison
            # record opens no breaker - it is already surfaced to its
            # caller), while a batch that re-scores 100% clean row-by-row
            # indicts the batch path itself - exactly the persistent
            # degradation the breaker exists to make loud.  Decided
            # BEFORE the output guard runs: guard-refused NaN rows are
            # model/kernel-borne, not caller-data-borne, and must still
            # count toward the breaker.  In half-open the probe must
            # resolve either way, so any failure re-opens.
            data_borne = any(isinstance(r, RowScoringError) for r in results)
            # guard the fallback path too: a NaN row must not slip out
            # just because a batch peer tripped the fallback
            if self.guard_nonfinite:
                bad = self._nonfinite_rows(results)
                if bad:
                    self.telemetry.record_nonfinite_rows(len(bad))
                    for i in bad:
                        results[i] = RowScoringError(
                            "non-finite score (NaN/Inf) refused by the "
                            "serving output guard"
                        )
            if not data_borne or self.breaker.state == "half_open":
                self.breaker.record_failure()
            return results
        bad = self._guard_rows(results, n, poisoned)
        if bad:
            # non-finite scores: a poisoned model/kernel must fail loudly
            # per-row (the fallback would recompute the same NaN), and it
            # counts as a batch-path failure toward the breaker
            self.breaker.record_failure()
            self.telemetry.record_nonfinite_rows(len(bad))
            for i in bad:
                results[i] = RowScoringError(
                    "non-finite score (NaN/Inf) refused by the serving "
                    "output guard"
                )
            return results
        self.breaker.record_success()
        fp = self._scorer.fused
        self.telemetry.record_batch(n, bucket, time.perf_counter() - t0,
                                    fused=fp is not None)
        if fp is not None and len(fp.compile_ms) != getattr(
                self, "_fused_buckets_pushed", 0):
            # a new shape bucket compiled mid-traffic: surface its cost
            self._push_fused_status()
        return results

    def _guard_rows(self, results: Sequence[Any], n: int,
                    poisoned: bool) -> list[int]:
        """NaN/Inf guard dispatch: the fused program already computed a
        columnar non-finite mask over its result arrays, so the guard is
        a slice instead of a python walk over every result dict.  A
        fault-injected poisoning mutates the dicts AFTER scoring, so that
        (test-only) path - and the interpreted path - re-walk the dicts."""
        if not self.guard_nonfinite:
            return []
        fp = self._scorer.fused
        if fp is not None and not poisoned:
            return [i for i in fp.last_nonfinite_rows if i < n]
        return self._nonfinite_rows(results)

    @staticmethod
    def _nonfinite_rows(results: Sequence[Any]) -> list[int]:
        """Indices of rows whose score dicts contain any NaN/Inf float."""

        def bad(v: Any) -> bool:
            if isinstance(v, float):
                return not math.isfinite(v)
            if isinstance(v, dict):
                return any(bad(x) for x in v.values())
            if isinstance(v, (list, tuple)):
                return any(bad(x) for x in v)
            return False

        return [i for i, row in enumerate(results)
                if isinstance(row, dict) and bad(row)]

    def _score_rows_fallback(self, records: Sequence[Mapping[str, Any]]) -> list:
        out: list = []
        for r in records:
            try:
                out.append(self._scorer(r))
            except Exception as e:  # noqa: BLE001 - isolate the bad row
                out.append(RowScoringError(f"{type(e).__name__}: {e}"))
        return out

    def __call__(self, record: Mapping[str, Any]) -> Any:
        return self.score_batch([record])[0]

    @property
    def result_features(self):
        return self._scorer.result_features

    @property
    def raw_features(self):
        return self._scorer.raw_features


def compile_endpoint(model, **kw) -> CompiledEndpoint:
    """Compile a fitted OpWorkflowModel into a warmed batch-first endpoint
    (the serving counterpart of local.score_function)."""
    return CompiledEndpoint(model, **kw)


def records_from_dataset(ds, features) -> list[dict[str, Any]]:
    """Dataset -> per-row request dicts restricted to ``features`` (the
    one conversion the runner's serve run and the serving bench share)."""
    cols = ds.to_pylists()
    names = [f.name for f in features if f.name in cols]
    n = len(cols[names[0]]) if names else 0
    return [{k: cols[k][i] for k in names} for i in range(n)]
