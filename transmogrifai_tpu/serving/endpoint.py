"""Compiled, batch-first scoring endpoint over a fitted OpWorkflowModel.

The serving analog of the reference's MLeap-compiled local model
(reference: local/.../OpWorkflowModelLocal.scala:30-120 compiles a fitted
pipeline once into a reusable score function) built batch-FIRST: the
single-row contract the reference exposes is the degenerate case here,
not the design center.

* the scoring DAG resolves ONCE at construction (the LocalScorer's
  precompiled (stage, inputs, output) plan, numpy predict paths);
* requests score through fixed shape BUCKETS (pad to the next bucket, so
  repeated batch shapes reuse every shape-keyed cache: one-hot code
  memos, fitted-metadata memos, and - for any stage that does dispatch
  to jax - its jit cache);
* tree predicts hit ONE flat-heap C++/vectorized-numpy call per batch
  (models/trees.predict_arrays_np), never a per-row or per-tree loop;
* construction warm-up primes each bucket ahead of traffic, so the
  first real request never pays cold-path latency;
* a batch that fails the compiled path degrades gracefully: rows re-score
  individually through the row fallback, bad rows surface as
  ``RowScoringError`` results instead of poisoning their batch peers.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Mapping, Optional, Sequence

from ..local.scorer import LocalScorer
from .telemetry import ServingTelemetry

DEFAULT_BUCKETS = (1, 8, 32, 128)


@dataclass
class RowScoringError:
    """Per-row failure marker returned in a batch's result list (the
    scheduler converts it into the request's exception; direct batch
    callers can filter)."""

    error: str


class CompiledEndpoint:
    """Batch-first compiled scorer with shape buckets + row fallback."""

    def __init__(
        self,
        model,
        batch_buckets: Sequence[int] = DEFAULT_BUCKETS,
        warm: bool = True,
        warm_record: Optional[Mapping[str, Any]] = None,
        telemetry: Optional[ServingTelemetry] = None,
    ) -> None:
        if not batch_buckets or any(int(b) < 1 for b in batch_buckets):
            raise ValueError("batch_buckets must be positive sizes")
        self.batch_buckets = tuple(sorted({int(b) for b in batch_buckets}))
        self.telemetry = telemetry if telemetry is not None else ServingTelemetry()
        self._scorer = LocalScorer(model)
        # the pad row: scored to fill a bucket, sliced off before return.
        # All-None raw features ride the same missing-value handling every
        # stage already implements; a caller-provided warm_record is used
        # instead when the pipeline requires non-null rows.
        self._pad_record: Mapping[str, Any] = dict(
            warm_record
            if warm_record is not None
            else {f.name: None for f in self._scorer.raw_features}
        )
        self.shape_misses = 0
        self.warmed_buckets: tuple[int, ...] = ()
        self.warm_error: Optional[str] = None
        if warm:
            self.warm_up()

    # -- warm-up ------------------------------------------------------------
    def warm_up(self) -> tuple[int, ...]:
        """Score one pad-batch per bucket ahead of traffic: primes the
        one-hot/metadata memos and any jit cache for EXACTLY the shapes
        the bucketed hot path will submit.  Best-effort: a pipeline that
        cannot score the pad record serves cold (warm_error records why)."""
        warmed = []
        try:
            for b in self.batch_buckets:
                self._scorer.score_batch([self._pad_record] * b)
                warmed.append(b)
        except Exception as e:  # noqa: BLE001 - warm-up must never kill serving
            self.warm_error = f"{type(e).__name__}: {e}"
        self.warmed_buckets = tuple(warmed)
        return self.warmed_buckets

    # -- scoring ------------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (callers chunk at the largest bucket)."""
        for b in self.batch_buckets:
            if n <= b:
                return b
        return self.batch_buckets[-1]

    def score_batch(self, records: Sequence[Mapping[str, Any]]) -> list:
        """Score a batch through the bucketed compiled path; element i of
        the result aligns with records[i] (RowScoringError on failure)."""
        out: list = []
        step = self.batch_buckets[-1]
        for lo in range(0, len(records), step):
            out.extend(self._score_bucketed(records[lo:lo + step]))
        return out

    def _score_bucketed(self, records: Sequence[Mapping[str, Any]]) -> list:
        n = len(records)
        if n == 0:
            return []
        bucket = self.bucket_for(n)
        if self.warm_error is not None:
            # the pad record itself cannot score through this pipeline
            # (warm-up told us): padding every partial batch with it
            # would silently degrade ALL serving to the per-row fallback.
            # Score the exact batch instead - no shape bucketing, but the
            # batch path stays hot.
            padded = list(records)
        else:
            padded = list(records) + [self._pad_record] * (bucket - n)
        t0 = time.perf_counter()
        try:
            results = self._scorer.score_batch(padded)[:n]
        except Exception:  # noqa: BLE001 - degrade to the row path
            # shape miss / malformed row: the compiled batch path assumes
            # bucket-shaped well-formed batches; anything else re-scores
            # row by row so one bad request cannot fail its batch peers.
            # Deliberately NOT record_batch: these rows never rode the
            # batch path, and counting them would make batch_rows_per_s /
            # batch-fill read nominal while serving is fully degraded -
            # rows_fallback is the truth signal
            self.shape_misses += 1
            results = self._score_rows_fallback(records)
            self.telemetry.record_fallback_rows(n)
            return results
        self.telemetry.record_batch(n, bucket, time.perf_counter() - t0)
        return results

    def _score_rows_fallback(self, records: Sequence[Mapping[str, Any]]) -> list:
        out: list = []
        for r in records:
            try:
                out.append(self._scorer(r))
            except Exception as e:  # noqa: BLE001 - isolate the bad row
                out.append(RowScoringError(f"{type(e).__name__}: {e}"))
        return out

    def __call__(self, record: Mapping[str, Any]) -> Any:
        return self.score_batch([record])[0]

    @property
    def result_features(self):
        return self._scorer.result_features

    @property
    def raw_features(self):
        return self._scorer.raw_features


def compile_endpoint(model, **kw) -> CompiledEndpoint:
    """Compile a fitted OpWorkflowModel into a warmed batch-first endpoint
    (the serving counterpart of local.score_function)."""
    return CompiledEndpoint(model, **kw)


def records_from_dataset(ds, features) -> list[dict[str, Any]]:
    """Dataset -> per-row request dicts restricted to ``features`` (the
    one conversion the runner's serve run and the serving bench share)."""
    cols = ds.to_pylists()
    names = [f.name for f in features if f.name in cols]
    n = len(cols[names[0]]) if names else 0
    return [{k: cols[k][i] for k in names} for i in range(n)]
