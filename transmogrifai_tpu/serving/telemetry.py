"""Serving-tier telemetry: latency/throughput/queue/batch-fill metrics.

Counterpart of the per-stage AppMetrics accumulation in utils/tracing.py
(reference: OpSparkListener / AppMetrics, utils/.../spark/
OpSparkListener.scala:56-161) specialized to the request/response tier:
per-request latency percentiles (p50/p95/p99), rows/s, admission-control
outcome counters (shed/timeout/fallback), queue-depth samples, and a
batch-fill histogram showing how well the micro-batching scheduler packs
its shape buckets.  Snapshots export as a JSON artifact (the serving
analog of the bench's one-JSON-line evidence contract).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from ..obs.metrics import (
    metrics_registry,
    percentiles,
    write_json_artifact,
)

log = logging.getLogger("transmogrifai_tpu.serving")

LOG_PREFIX = "op_serving_metrics"

#: bounded sample reservoirs - serving loops run unbounded, telemetry
#: memory must not (beyond the cap, samples decimate 2:1, keeping every
#: other sample so the distribution stays representative)
_MAX_SAMPLES = 65536


def _finite(v: float, ndigits: int):
    """Round, mapping the empty-sample NaN to None: bare NaN tokens are
    not valid JSON (RFC 8259) and would break strict consumers of the
    exported artifact."""
    return None if v != v else round(v, ndigits)


class ServingTelemetry:
    """Thread-safe accumulator shared by endpoint + scheduler."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.started_at = time.time()  # epoch stamp (correlation only)
        self._pc_start = time.perf_counter()  # durations NEVER use the
        # epoch clock (the tests/test_style.py timing gate)
        # unified metrics plane (obs/): this accumulator's snapshot is a
        # registered VIEW - same shape, scrapeable via `tx obs` and the
        # Prometheus exposition next to mesh/data/stage metrics
        metrics_registry().register_view("serving", self)
        # model-version attribution (registry/): every snapshot names
        # the model version + deployment generation that produced it, so
        # bench JSON and summary_json() metrics are attributable after a
        # hot-swap (the Mesh/Data telemetry classes carry the same pair)
        self.model_version: Optional[str] = None
        self.generation: Optional[int] = None
        # multi-model attribution (ISSUE 20): which HOSTED MODEL this
        # accumulator serves when a replica multiplexes N models behind
        # one lane - None on single-model surfaces, a model_id label in
        # the Prometheus exposition otherwise
        self.model_id: Optional[str] = None
        self._lifecycle: list[dict] = []
        self._latencies_s: list[float] = []
        self._batch_sizes: list[int] = []
        self._batch_fills: list[float] = []
        self._queue_depths: list[int] = []
        self.rows_ok = 0
        self.rows_fallback = 0
        self.rows_failed = 0
        self.rows_batched = 0
        # whole-pipeline fused compilation status (local/fused.py): set
        # by the endpoint, exported so every serving artifact names
        # whether the hot path was the fused program or the interpreted
        # DAG walk, why, and what each shape bucket's compile cost
        self.fused_enabled: Optional[bool] = None
        self.fused_reason: Optional[str] = None
        self.fused_compile_ms: dict = {}
        self.batches_fused = 0
        self.rows_fused = 0
        # XLA fused backend (local/fused_xla.py): which backend serves,
        # the per-bucket trace/compile/load/first-exec split, and the
        # AOT executable-cache outcome counters (warm-start hits vs
        # retraces vs stale-fingerprint retrace-and-recache events)
        self.fused_backend: Optional[str] = None
        self.fused_bucket_timings: dict = {}
        self.fused_cache_events: dict = {
            "hits": 0, "misses": 0, "stale": 0,
        }
        self.shed_deadline = 0
        self.shed_queue_full = 0
        self.shed_quota = 0
        self.request_timeouts = 0
        self.batches = 0
        self.batch_wall_s = 0.0
        # circuit-breaker health (admission.CircuitBreaker transitions +
        # the rows it sheds + NaN/Inf rows the output guard caught)
        self.shed_breaker = 0
        self.rows_shed_breaker = 0
        self.breaker_opens = 0
        self.breaker_closes = 0
        self.breaker_probes = 0
        self.rows_nonfinite = 0
        # data-contract guards (schema/: contract validation + the
        # serve-vs-train distribution drift monitor)
        self.empty_batches = 0
        self.shed_schema = 0
        self.rows_shed_schema = 0
        self.schema_drift_batches = 0
        self.schema_violations_by_kind: dict = {}
        self.schema_drift_actions: dict = {}
        self._drift_last: dict = {}
        self._drift_max: dict = {}
        # autotune (ISSUE 13): which serving knobs the tuner owns for
        # this endpoint/scheduler and the values it chose - scraped as
        # tx_serving_tuned_knobs_* so tuned-vs-hand-set is visible in
        # the obs plane, not just in run artifacts
        self.tuned_knobs: dict = {}
        self.knob_source: str = "hand_set"

    # -- recording ----------------------------------------------------------
    def _sample(self, bucket: list, value) -> None:
        bucket.append(value)
        if len(bucket) > _MAX_SAMPLES:
            del bucket[::2]

    def record_request(self, latency_s: float, outcome: str = "ok") -> None:
        """Outcomes: ok | failed | shed_deadline | shed_queue_full |
        shed_quota | shed_breaker | shed_schema | timeout."""
        with self._lock:
            if outcome in ("ok", "failed"):
                self._sample(self._latencies_s, float(latency_s))
            if outcome == "ok":
                self.rows_ok += 1
            elif outcome == "failed":
                self.rows_failed += 1
            elif outcome == "shed_deadline":
                self.shed_deadline += 1
            elif outcome == "shed_queue_full":
                self.shed_queue_full += 1
            elif outcome == "shed_quota":
                self.shed_quota += 1
            elif outcome == "shed_breaker":
                self.shed_breaker += 1
            elif outcome == "shed_schema":
                self.shed_schema += 1
            elif outcome == "timeout":
                self.request_timeouts += 1

    def record_batch(self, n_rows: int, bucket_size: int,
                     wall_s: float, fused: bool = False) -> None:
        with self._lock:
            self.batches += 1
            self.batch_wall_s += float(wall_s)
            self.rows_batched += int(n_rows)
            if fused:
                self.batches_fused += 1
                self.rows_fused += int(n_rows)
            self._sample(self._batch_sizes, int(n_rows))
            self._sample(
                self._batch_fills, n_rows / bucket_size if bucket_size else 0.0
            )

    def set_fused_status(self, enabled: bool, reason: Optional[str],
                         compile_ms_by_bucket: Optional[dict] = None,
                         backend: Optional[str] = None,
                         bucket_timings: Optional[dict] = None,
                         cache_events: Optional[dict] = None) -> None:
        """Record whether this endpoint serves through the fused
        program, which backend ('numpy' | 'xla'), why not (when
        degraded), the per-shape-bucket compile/warm wall times (keyed
        by batch length, ms) and - on the XLA backend - the per-bucket
        ``trace_ms / compile_ms / load_ms / first_exec_ms / cache_hit``
        split plus executable-cache hit/miss/stale counters."""
        with self._lock:
            self.fused_enabled = bool(enabled)
            self.fused_reason = reason
            if backend is not None:
                self.fused_backend = backend
            if compile_ms_by_bucket:
                self.fused_compile_ms.update(
                    {int(k): round(float(v), 3)
                     for k, v in compile_ms_by_bucket.items()}
                )
            if bucket_timings:
                self.fused_bucket_timings.update({
                    int(k): {
                        kk: (round(float(vv), 3)
                             if kk != "cache_hit" else int(vv))
                        for kk, vv in v.items()
                    }
                    for k, v in bucket_timings.items()
                })
            if cache_events:
                # absolute counters from the pipeline, not deltas
                self.fused_cache_events.update(
                    {k: int(v) for k, v in cache_events.items()}
                )

    def record_fallback_rows(self, n: int) -> None:
        """Rows that missed the compiled bucketed path and scored through
        the row fallback (request-level ok/failed accounting stays with
        the caller - this only tracks the degradation count)."""
        with self._lock:
            self.rows_fallback += int(n)

    def record_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._sample(self._queue_depths, int(depth))

    def record_breaker_transition(self, event: str) -> None:
        """Circuit-breaker state changes: open | close | probe.  Opens
        log at WARNING - a breaker opening IS the degradation alarm."""
        with self._lock:
            if event == "open":
                self.breaker_opens += 1
            elif event == "close":
                self.breaker_closes += 1
            elif event == "probe":
                self.breaker_probes += 1
        if event == "open":
            log.warning("%s circuit breaker OPEN: batch path unhealthy, "
                        "shedding until a half-open probe succeeds",
                        LOG_PREFIX)
        elif event == "close":
            log.info("%s circuit breaker closed: batch path recovered",
                     LOG_PREFIX)

    def record_breaker_shed_rows(self, n: int) -> None:
        """Rows shed unscored because the breaker was open (request-level
        shed_breaker accounting stays with the scheduler, mirroring the
        rows_fallback split)."""
        with self._lock:
            self.rows_shed_breaker += int(n)

    def record_nonfinite_rows(self, n: int) -> None:
        """Rows whose scores failed the NaN/Inf output guard."""
        with self._lock:
            self.rows_nonfinite += int(n)

    def record_empty_batch(self) -> None:
        """A zero-row batch reached the endpoint (e.g. every row was
        quarantined upstream): a counted no-op, not an error."""
        with self._lock:
            self.empty_batches += 1

    def record_schema_violations(self, violations, action: str) -> None:
        """One batch violated the schema contract; ``action`` is the
        drift_policy applied (raise | warn | shed), counted per policy
        so the snapshot shows HOW violating batches were handled."""
        with self._lock:
            self.schema_drift_batches += 1
            self.schema_drift_actions[action] = (
                self.schema_drift_actions.get(action, 0) + 1
            )
            for v in violations:
                kind = v.get("kind", "unknown")
                self.schema_violations_by_kind[kind] = (
                    self.schema_violations_by_kind.get(kind, 0) + 1
                )

    def record_schema_shed_rows(self, n: int) -> None:
        """Rows refused unscored under drift_policy='shed' (request-
        level shed_schema accounting stays with the scheduler)."""
        with self._lock:
            self.rows_shed_schema += int(n)

    def set_model_version(self, version: Optional[str],
                          generation: Optional[int] = None) -> None:
        """Attribute everything this accumulator records to one model
        version / deployment generation (set by the registry's
        DeploymentController at deploy time)."""
        with self._lock:
            self.model_version = version
            self.generation = generation

    def set_model_id(self, model_id: Optional[str]) -> None:
        """Attribute this accumulator to one hosted model of a
        multi-model replica (ISSUE 20); surfaces as the ``model_id``
        label on every ``tx_serving_*`` sample this view exports."""
        with self._lock:
            self.model_id = None if model_id is None else str(model_id)

    #: lifecycle events kept per accumulator (bounded like samples)
    _MAX_LIFECYCLE = 256

    def record_lifecycle(self, event: dict) -> None:
        """A deployment lifecycle event (swap / canary start / rollback
        decision with evidence) attributed to this generation; surfaced
        in the snapshot so the serving JSON artifact carries the WHY
        behind any metric discontinuity."""
        with self._lock:
            self._lifecycle.append(dict(event))
            if len(self._lifecycle) > self._MAX_LIFECYCLE:
                del self._lifecycle[0]

    def set_tuned_knobs(self, knobs: dict,
                        source: str = "autotune") -> None:
        """Record the knob values the tuner (or a hand-set override)
        chose for this serving surface; numeric values surface as
        scrapeable series, the source ('hand_set' | 'autotune') says
        who owns them now (docs/serving.md knob table)."""
        with self._lock:
            self.tuned_knobs.update({
                str(k): (float(v) if isinstance(v, (int, float))
                         and not isinstance(v, bool) else str(v))
                for k, v in knobs.items()
            })
            self.knob_source = str(source)

    def record_drift_scores(self, scores: dict) -> None:
        """Latest per-feature JS divergence vs the training
        distributions; running max kept per feature."""
        with self._lock:
            for name, s in scores.items():
                self._drift_last[name] = float(s)
                if s > self._drift_max.get(name, 0.0):
                    self._drift_max[name] = float(s)

    # -- reporting ----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            lat_ms = [v * 1e3 for v in self._latencies_s]
            fills = list(self._batch_fills)
            sizes = list(self._batch_sizes)
            depths = list(self._queue_depths)
            wall = max(time.perf_counter() - self._pc_start, 1e-9)
            batch_wall = max(self.batch_wall_s, 1e-9)
            rows = self.rows_ok + self.rows_failed
            fill_hist = {"0-25%": 0, "25-50%": 0, "50-75%": 0, "75-100%": 0}
            for f in fills:
                if f <= 0.25:
                    fill_hist["0-25%"] += 1
                elif f <= 0.5:
                    fill_hist["25-50%"] += 1
                elif f <= 0.75:
                    fill_hist["50-75%"] += 1
                else:
                    fill_hist["75-100%"] += 1
            return {
                "wall_s": round(wall, 3),
                "model_version": self.model_version,
                "generation": self.generation,
                "model_id": self.model_id,
                "lifecycle": [dict(e) for e in self._lifecycle],
                "rows_scored": self.rows_ok,
                "rows_failed": self.rows_failed,
                "rows_fallback": self.rows_fallback,
                "shed_deadline": self.shed_deadline,
                "shed_queue_full": self.shed_queue_full,
                "shed_quota": self.shed_quota,
                "shed_breaker": self.shed_breaker,
                "request_timeouts": self.request_timeouts,
                "breaker": {
                    "opens": self.breaker_opens,
                    "closes": self.breaker_closes,
                    "probes": self.breaker_probes,
                    "rows_shed": self.rows_shed_breaker,
                    "rows_nonfinite": self.rows_nonfinite,
                },
                "data_contract": {
                    "empty_batches": self.empty_batches,
                    "shed_schema": self.shed_schema,
                    "rows_shed_schema": self.rows_shed_schema,
                    "schema_drift_batches": self.schema_drift_batches,
                    "violations_by_kind": dict(
                        self.schema_violations_by_kind),
                    "batches_by_action": dict(self.schema_drift_actions),
                    "drift_js": {
                        name: {
                            "last": round(self._drift_last[name], 6),
                            "max": round(
                                self._drift_max.get(name, 0.0), 6),
                        }
                        for name in sorted(self._drift_last)
                    },
                    "drift_js_max": round(
                        max(self._drift_max.values(), default=0.0), 6),
                },
                "rows_per_s": round(rows / wall, 1),
                "tuned_knobs": dict(self.tuned_knobs),
                "knob_source": self.knob_source,
                "rows_batched": self.rows_batched,
                "batch_rows_per_s": round(self.rows_batched / batch_wall, 1),
                "fused": {
                    "enabled": self.fused_enabled,
                    "backend": self.fused_backend,
                    "reason": self.fused_reason,
                    "compile_ms_by_bucket": {
                        str(k): v
                        for k, v in sorted(self.fused_compile_ms.items())
                    },
                    "bucket_timings": {
                        str(k): dict(v)
                        for k, v in sorted(
                            self.fused_bucket_timings.items())
                    },
                    "cache": dict(self.fused_cache_events),
                    "batches_fused": self.batches_fused,
                    "rows_fused": self.rows_fused,
                },
                "latency_ms": {
                    k: _finite(v, 3)
                    for k, v in percentiles(lat_ms, (50.0, 95.0, 99.0)).items()
                },
                "batches": self.batches,
                "mean_batch_size": round(
                    sum(sizes) / len(sizes), 2) if sizes else 0.0,
                # observed batch-size spread (ISSUE 13): what the
                # autotune bucket proposer reads to shape bucket edges
                "batch_size_p50": _finite(
                    percentiles(sizes, (50.0,))["p50"], 1),
                "batch_size_p95": _finite(
                    percentiles(sizes, (95.0,))["p95"], 1),
                "batch_size_max": max(sizes) if sizes else 0,
                "batch_fill_histogram": fill_hist,
                "queue_depth": {
                    "max": max(depths) if depths else 0,
                    **{k: _finite(v, 1)
                       for k, v in percentiles(depths, (50.0, 99.0)).items()},
                },
            }

    def log_line(self) -> str:
        snap = self.snapshot()
        lat = snap["latency_ms"]
        kv = {
            "rows": snap["rows_scored"],
            "rows_per_s": snap["rows_per_s"],
            "p50_ms": lat["p50"],
            "p95_ms": lat["p95"],
            "p99_ms": lat["p99"],
            "shed": (snap["shed_deadline"] + snap["shed_queue_full"]
                     + snap["shed_quota"] + snap["shed_breaker"]),
            "fallback": snap["rows_fallback"],
            "breaker_opens": snap["breaker"]["opens"],
        }
        return LOG_PREFIX + " " + " ".join(f"{k}={v}" for k, v in kv.items())

    def export(self, path: str, extra: Optional[dict] = None) -> dict:
        """Write the snapshot (plus caller context, e.g. the model config
        served) as the JSON telemetry artifact; returns what was written."""
        snap = self.snapshot()
        if extra:
            snap.update(extra)
        write_json_artifact(path, snap)
        log.info(self.log_line())
        return snap
