"""Admission control for the serving queue: bounded depth + deadlines.

The graceful-degradation half of the serving subsystem (reference frame:
the reference's serving story is MLeap local scoring behind the caller's
own RPC stack - local/.../OpWorkflowModelLocal.scala:30-120 - so
backpressure semantics live here, not in a Spark analog; the policy
follows TensorFlow Serving's batching-queue admission: bounded queue,
deadline-aware shedding at dequeue time).

* ``QueueFullError``      - raised at submit when the bounded queue is at
                            capacity (load shedding at the front door)
* ``DeadlineExceededError`` - delivered to a request whose deadline passed
                            while it sat in the queue (shed at dequeue,
                            never scored: scoring a dead request wastes
                            a batch slot someone live could use)
* ``AdmissionController`` - the bounded FIFO both ends share
* ``CircuitBreaker``      - batch-path health gate: K consecutive
                            compiled-path failures open it (requests
                            then shed fast with ``BreakerOpenError``
                            instead of silently degrading ALL traffic
                            to the slow row loop), a cooldown later a
                            single half-open probe rides the batch path
                            and its outcome closes or re-opens the
                            breaker
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional


class QueueFullError(RuntimeError):
    """Serving queue at capacity - request rejected at submission."""


class TenantQuotaError(QueueFullError):
    """One tenant's share of the bounded queue is exhausted - ITS
    request is rejected while other tenants keep admitting (ISSUE 14:
    a single chatty tenant must not be able to convert the shared
    bounded queue into a private one and starve the rest of the
    fleet's traffic).  Subclasses QueueFullError so existing
    shed-at-the-front-door handling still catches it; callers that
    care about the distinction catch this first."""

    def __init__(self, tenant: str, held: int, limit: int) -> None:
        super().__init__(
            f"tenant {tenant!r} holds {held}/{limit} queue slots "
            f"(per-tenant quota)"
        )
        self.tenant = tenant
        self.held = held
        self.limit = limit


class DeadlineExceededError(TimeoutError):
    """Request deadline elapsed before a batch picked it up."""


class RequestTimeoutError(TimeoutError):
    """Caller-side wait timed out (the request may still complete)."""


class BreakerOpenError(RuntimeError):
    """The batch-path circuit breaker is open - request shed unscored."""


class CircuitBreaker:
    """Consecutive-failure circuit breaker for the compiled batch path.

    States: ``closed`` (healthy) -> ``open`` after ``failure_threshold``
    consecutive batch-path failures -> ``half_open`` once ``cooldown_s``
    elapses (exactly ONE probe batch is admitted) -> ``closed`` on probe
    success, back to ``open`` on probe failure.  Every transition lands
    in ``ServingTelemetry`` (when attached) so a degraded endpoint is an
    alarm, not a silent slow-down.  Thread-safe: the scheduler's batch
    loop and direct ``score_batch`` callers may race on it.
    """

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 5.0,
                 clock=time.monotonic, telemetry=None,
                 probe_timeout_s: Optional[float] = None) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        # a probe is presumed dead (owner crashed mid-score) only after
        # MUCH longer than the cooldown: a probe merely slower than
        # cooldown_s must keep its ownership, or slow-but-recovered
        # paths could never close the breaker (probe churn livelock)
        self.probe_timeout_s = (
            max(30.0, 10.0 * self.cooldown_s)
            if probe_timeout_s is None else float(probe_timeout_s)
        )
        self.clock = clock
        self.telemetry = telemetry
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probe_in_flight = False
        self._probe_owner: Optional[int] = None  # thread ident of the probe
        self._probe_started_at: Optional[float] = None
        self.opens = 0
        self.closes = 0
        self.probes = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _record(self, event: str) -> None:
        if self.telemetry is not None:
            self.telemetry.record_breaker_transition(event)

    def allow(self) -> bool:
        """True when a batch may ride the compiled path now.  In the
        open state this flips to half-open after the cooldown and admits
        one probe; further calls shed until the probe resolves.  The
        admitted caller's thread OWNS the probe: only its outcome can
        close or re-open (see record_success), and a probe whose owner
        never resolves (died mid-score) is re-granted after
        ``probe_timeout_s`` so the breaker cannot wedge half-open."""
        event = None
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if (self._opened_at is not None
                        and self.clock() - self._opened_at >= self.cooldown_s):
                    event = self._grant_probe()
                else:
                    return False
            elif self._state == "half_open":
                stuck = (
                    self._probe_started_at is not None
                    and self.clock() - self._probe_started_at
                    >= self.probe_timeout_s
                )
                if self._probe_in_flight and not stuck:
                    return False
                event = self._grant_probe()
        self._record(event)
        return True

    def _grant_probe(self) -> str:
        """Lock held: move to half_open with the calling thread as the
        probe owner."""
        self._state = "half_open"
        self._probe_in_flight = True
        self._probe_owner = threading.get_ident()
        self._probe_started_at = self.clock()
        self.probes += 1
        return "probe"

    def _is_probe_owner(self) -> bool:
        """Lock held: is the calling thread the one the probe was
        granted to?  Anything else finishing during open/half_open is a
        batch admitted BEFORE the trip - stale evidence that must
        neither close nor re-open the breaker."""
        return (self._probe_in_flight
                and self._probe_owner == threading.get_ident())

    def record_success(self) -> None:
        event = None
        with self._lock:
            if self._state == "closed":
                self._consecutive_failures = 0
            elif self._state == "half_open" and self._is_probe_owner():
                self._state = "closed"
                self._consecutive_failures = 0
                self._probe_in_flight = False
                self._probe_owner = None
                self._probe_started_at = None
                self._opened_at = None
                self.closes += 1
                event = "close"
            # open, or half_open from a non-probe thread: stale success -
            # only the probe's outcome may close, otherwise mixed-latency
            # traffic makes the breaker flap instead of shedding fast
        if event:
            self._record(event)

    def record_failure(self) -> None:
        event = None
        with self._lock:
            if self._state == "half_open":
                if self._is_probe_owner():
                    self._consecutive_failures += 1
                    self._state = "open"
                    self._probe_in_flight = False
                    self._probe_owner = None
                    self._probe_started_at = None
                    self._opened_at = self.clock()
                    self.opens += 1
                    event = "open"
                # non-probe failure in half_open: stale, ignore
            elif self._state == "closed":
                self._consecutive_failures += 1
                if self._consecutive_failures >= self.failure_threshold:
                    self._state = "open"
                    self._opened_at = self.clock()
                    self.opens += 1
                    event = "open"
            else:  # open: count for observability, no transition
                self._consecutive_failures += 1
        if event:
            self._record(event)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "opens": self.opens,
                "closes": self.closes,
                "probes": self.probes,
            }


@dataclass
class _Request:
    """One queued score request; the scheduler resolves it like a future."""

    record: Mapping[str, Any]
    enqueued_at: float
    deadline: Optional[float] = None  # absolute monotonic time, or None
    #: tenant attribution for per-tenant quota accounting (None = the
    #: anonymous shared pool); released back at take()/drain() time
    tenant: Optional[str] = None
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    #: set when the caller stopped waiting (its wait timed out): the row
    #: still scores, but telemetry must not double-count it as delivered.
    #: Guarded by _state_lock so abandon vs resolve is a strict
    #: either/or - without it the batch loop could read abandoned=False
    #: and record 'ok' in the same instant the caller records 'timeout'.
    abandoned: bool = False
    _state_lock: threading.Lock = field(default_factory=threading.Lock)

    def resolve(self, result: Any = None,
                error: Optional[BaseException] = None) -> None:
        self.result = result
        self.error = error
        self.done.set()

    def try_abandon(self) -> bool:
        """Mark abandoned unless already resolved; True when this caller
        owns the abandonment (and so the 'timeout' telemetry record)."""
        with self._state_lock:
            if self.done.is_set():
                return False
            self.abandoned = True
            return True

    def resolve_delivered(self, result: Any = None,
                          error: Optional[BaseException] = None) -> bool:
        """Resolve; True when the request was NOT abandoned (the resolver
        owns the delivered/failed telemetry record)."""
        with self._state_lock:
            self.resolve(result=result, error=error)
            return not self.abandoned

    def wait(self, timeout: Optional[float] = None) -> Any:
        if not self.done.wait(timeout):
            raise RequestTimeoutError(
                f"request not completed within {timeout}s"
            )
        if self.error is not None:
            raise self.error
        return self.result


class AdmissionController:
    """Bounded FIFO with deadline-aware dequeue.

    ``admit`` is the producer side (request threads); ``take`` the consumer
    side (the scheduler's batch loop).  Expired requests are resolved with
    DeadlineExceededError at take() time and never reach the endpoint.

    ``tenant_quota`` (ISSUE 14) bounds any single tenant's share of the
    queue: a tenant may hold at most ``ceil(tenant_quota * max_queue)``
    queued slots, beyond which ITS submissions raise
    :class:`TenantQuotaError` while other tenants keep admitting.
    Requests with no tenant share one anonymous pool under the same
    rule.  ``None`` (the default) disables quota accounting entirely -
    the single-tenant hot path pays nothing.
    """

    def __init__(self, max_queue: int = 1024,
                 clock=time.monotonic,
                 tenant_quota: Optional[float] = None) -> None:
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if tenant_quota is not None and not (0.0 < tenant_quota <= 1.0):
            raise ValueError("tenant_quota must be in (0, 1]")
        self.max_queue = int(max_queue)
        self.tenant_quota = tenant_quota
        self.tenant_limit = (
            None if tenant_quota is None
            else max(1, math.ceil(tenant_quota * self.max_queue))
        )
        self.clock = clock
        self._lock = threading.Lock()
        self.not_empty = threading.Condition(self._lock)
        self._queue: deque[_Request] = deque()
        self._tenant_held: dict[Optional[str], int] = {}
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    def admit(self, record: Mapping[str, Any],
              deadline_s: Optional[float] = None,
              tenant: Optional[str] = None) -> _Request:
        """Enqueue or raise QueueFullError (TenantQuotaError when the
        per-tenant share is the bound that tripped).  ``deadline_s`` is
        relative to now; the request is shed (not scored) if still
        queued past it."""
        now = self.clock()
        req = _Request(
            record=record, enqueued_at=now,
            deadline=None if deadline_s is None else now + deadline_s,
            tenant=tenant,
        )
        with self.not_empty:
            if self._closed:
                # checked under the SAME lock close() drains with, so a
                # request can never slip in after the final drain and
                # strand its caller
                raise RuntimeError("scheduler closed")
            if len(self._queue) >= self.max_queue:
                raise QueueFullError(
                    f"serving queue full ({self.max_queue} pending)"
                )
            if self.tenant_limit is not None:
                held = self._tenant_held.get(tenant, 0)
                if held >= self.tenant_limit:
                    raise TenantQuotaError(
                        str(tenant), held, self.tenant_limit)
                self._tenant_held[tenant] = held + 1
            self._queue.append(req)
            self.not_empty.notify()
        return req

    def _release_tenant(self, req: _Request) -> None:
        """Lock held: give the request's queue slot back to its
        tenant's quota (dequeue time - quotas bound QUEUED work, the
        in-flight share belongs to the consumer's own bounds)."""
        if self.tenant_limit is None:
            return
        held = self._tenant_held.get(req.tenant, 0)
        if held <= 1:
            self._tenant_held.pop(req.tenant, None)
        else:
            self._tenant_held[req.tenant] = held - 1

    def take(self, max_n: int) -> tuple[list[_Request], list[_Request]]:
        """Dequeue up to ``max_n`` live requests -> (live, shed).  Shed
        requests are already resolved with DeadlineExceededError."""
        now = self.clock()
        live: list[_Request] = []
        shed: list[_Request] = []
        with self._lock:
            while self._queue and len(live) < max_n:
                req = self._queue.popleft()
                self._release_tenant(req)
                if req.deadline is not None and now > req.deadline:
                    shed.append(req)
                else:
                    live.append(req)
        for req in shed:
            req.resolve_delivered(error=DeadlineExceededError(
                f"deadline exceeded after "
                f"{(now - req.enqueued_at) * 1e3:.1f} ms in queue"
            ))
        return live, shed

    def wait_for_fill(self, n: int, timeout: Optional[float] = None) -> int:
        """Block until >= n requests are queued or ``timeout`` elapses;
        returns the queue depth seen (the scheduler's linger-for-fill)."""
        with self.not_empty:
            self.not_empty.wait_for(
                lambda: len(self._queue) >= n, timeout
            )
            return len(self._queue)

    def wait_nonempty(self, timeout: Optional[float] = None) -> bool:
        with self.not_empty:
            if self._queue:
                return True
            return bool(self.not_empty.wait_for(
                lambda: bool(self._queue), timeout
            ))

    def close(self) -> None:
        """Refuse all future admissions (shutdown path; see drain)."""
        with self._lock:
            self._closed = True

    def drain(self) -> list[_Request]:
        """Remove and return everything pending (shutdown path)."""
        with self._lock:
            out, self._queue = list(self._queue), deque()
            self._tenant_held.clear()
        return out

    def tenants_held(self) -> dict:
        """Per-tenant queued-slot counts (observability; the quota
        evidence ``tx fleet status`` surfaces)."""
        with self._lock:
            return dict(self._tenant_held)
