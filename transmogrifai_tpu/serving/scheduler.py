"""Micro-batching scheduler: concurrent requests -> shape-bucketed batches.

The serving-side batching pattern (PAPERS.md: tf.data's pipelined batch
path decoupled from per-request dispatch, and TensorFlow Serving's
BatchingSession accumulating small requests into device-efficient
shapes): requests from any number of caller threads accumulate in the
admission queue; ONE batch loop forms batches under two knobs -

* ``max_batch_size``  - never score more rows per dispatch than this
                        (defaults to the endpoint's largest shape bucket);
* ``max_wait_us``     - a batch launches as soon as it is full OR the
                        oldest queued request has waited this long, so
                        tail latency is bounded at low traffic while
                        throughput batches up under load.

Batches score through the CompiledEndpoint's bucketed flat-heap path,
admission control (bounded queue, deadline shedding) lives in
admission.py, and every outcome lands in ServingTelemetry.

``start=False`` runs no worker thread: tests drive ``run_once`` for
deterministic batch-formation/shedding assertions.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Iterable, Mapping, Optional

from .admission import (
    AdmissionController,
    BreakerOpenError,
    QueueFullError,
    RequestTimeoutError,
    TenantQuotaError,
    _Request,
)
from .endpoint import CompiledEndpoint, RowScoringError
from .telemetry import ServingTelemetry


class MicroBatchScheduler:
    """Batch loop + admission control over a CompiledEndpoint."""

    def __init__(
        self,
        endpoint: CompiledEndpoint,
        max_batch_size: Optional[int] = None,
        max_wait_us: int = 2000,
        max_queue: int = 1024,
        default_deadline_ms: Optional[float] = None,
        telemetry: Optional[ServingTelemetry] = None,
        clock=time.monotonic,
        start: bool = True,
        tenant_quota: Optional[float] = None,
    ) -> None:
        self.endpoint = endpoint
        self.max_batch_size = int(
            max_batch_size
            if max_batch_size is not None
            else endpoint.batch_buckets[-1]
        )
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self.max_wait_s = max(int(max_wait_us), 0) / 1e6
        self.default_deadline_ms = default_deadline_ms
        self.telemetry = (
            telemetry if telemetry is not None else endpoint.telemetry
        )
        self.clock = clock
        self.admission = AdmissionController(max_queue=max_queue, clock=clock,
                                             tenant_quota=tenant_quota)
        self._closed = False
        self._worker: Optional[threading.Thread] = None
        if start:
            self._worker = threading.Thread(
                target=self._worker_loop, name="tx-serving-batcher",
                daemon=True,
            )
            self._worker.start()

    # -- knob seam (ISSUE 13) -----------------------------------------------
    def knobs(self) -> dict:
        """The scheduler's live micro-batch knobs (the tuner's A/B
        probe surface and the values the serving artifact reports)."""
        return {
            "max_batch_size": int(self.max_batch_size),
            "max_wait_us": int(round(self.max_wait_s * 1e6)),
        }

    def retune(self, max_batch_size: Optional[int] = None,
               max_wait_us: Optional[int] = None,
               source: str = "autotune") -> dict:
        """Apply tuner-chosen micro-batch knobs to the LIVE scheduler.
        Attribute writes are atomic and the batch loop reads them fresh
        each ``run_once``, so no lock or restart is needed; the new
        values land in telemetry as the tuned-knob record.  Returns the
        applied knob dict."""
        if max_batch_size is not None:
            if int(max_batch_size) < 1:
                raise ValueError("max_batch_size must be >= 1")
            self.max_batch_size = int(max_batch_size)
        if max_wait_us is not None:
            self.max_wait_s = max(int(max_wait_us), 0) / 1e6
        applied = self.knobs()
        self.telemetry.set_tuned_knobs(applied, source=source)
        return applied

    # -- request side -------------------------------------------------------
    def submit(self, record: Mapping[str, Any],
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               _count_shed: bool = True) -> _Request:
        """Enqueue one score request; returns a future-like handle
        (``.wait(timeout)``).  Raises QueueFullError when the bounded
        queue sheds at the front door (TenantQuotaError - counted as
        ``shed_quota`` - when ``tenant``'s own share is what tripped).
        ``_count_shed=False`` lets the backpressuring stream retry
        without inflating the shed counter for rows that are ultimately
        admitted."""
        if self._closed:
            raise RuntimeError("scheduler is closed")
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        try:
            return self.admission.admit(
                record,
                None if deadline_ms is None else deadline_ms / 1e3,
                tenant=tenant,
            )
        except TenantQuotaError:
            # a quota trip is ALWAYS counted (even on the stream's
            # retry path): the whole-queue-full retry is expected to
            # eventually admit, but a tenant at its own cap retrying is
            # exactly the starvation signal the counter exists for
            self.telemetry.record_request(0.0, "shed_quota")
            raise
        except QueueFullError:
            if _count_shed:
                self.telemetry.record_request(0.0, "shed_queue_full")
            raise

    def score(self, record: Mapping[str, Any],
              timeout_s: Optional[float] = 30.0,
              deadline_ms: Optional[float] = None,
              tenant: Optional[str] = None) -> Any:
        """Synchronous request/response call through the batcher."""
        req = self.submit(record, deadline_ms=deadline_ms, tenant=tenant)
        try:
            return req.wait(timeout_s)
        except RequestTimeoutError:
            # claim abandonment atomically: the batch loop may still
            # score the row, but exactly ONE of {timeout, ok/failed}
            # lands in telemetry - if the worker resolved in the same
            # instant, the response IS here, so deliver it instead
            if not req.try_abandon():
                if req.error is not None:
                    raise req.error from None
                return req.result
            self.telemetry.record_request(
                self.clock() - req.enqueued_at, "timeout"
            )
            raise

    def score_stream(self, records: Iterable[Mapping[str, Any]],
                     window: int = 256,
                     timeout_s: float = 60.0) -> Iterable[Any]:
        """Pipeline an iterable through the batcher with bounded
        in-flight requests; yields results in submission order (failed
        or shed rows yield RowScoringError, the stream never dies on one
        row).  A full queue applies BACKPRESSURE - the stream waits for
        its own oldest request instead of erroring - so ``window`` may
        exceed the admission bound safely."""
        window = max(1, min(int(window), self.admission.max_queue))
        pending: deque = deque()

        def _resolve(req) -> Any:
            try:
                return req.wait(timeout_s)
            except Exception as e:  # noqa: BLE001 - per-row isolation
                return RowScoringError(f"{type(e).__name__}: {e}")

        for r in records:
            while True:
                try:
                    pending.append(self.submit(r, _count_shed=False))
                    break
                except QueueFullError as e:
                    if pending:
                        # drain our oldest in-flight request; its batch
                        # completing frees queue space.  Not a shed: the
                        # row is retried and (normally) admitted
                        yield _resolve(pending.popleft())
                    else:
                        # the queue is full of OTHER callers' requests -
                        # shed this row for real, keep the stream alive
                        self.telemetry.record_request(
                            0.0, "shed_queue_full"
                        )
                        yield RowScoringError(f"{type(e).__name__}: {e}")
                        break
            if len(pending) >= window:
                yield _resolve(pending.popleft())
        while pending:
            yield _resolve(pending.popleft())

    # -- batch loop ---------------------------------------------------------
    def run_once(self, wait_timeout_s: float = 0.0) -> int:
        """Form and score ONE batch; returns rows scored (0 when idle).
        The worker loop calls this forever; tests call it directly for
        deterministic scheduling assertions."""
        if not self.admission.wait_nonempty(wait_timeout_s):
            return 0
        # linger for fill: launch as soon as full, else when the oldest
        # waiter has been queued max_wait_s
        if self.max_wait_s > 0:
            self.admission.wait_for_fill(self.max_batch_size, self.max_wait_s)
        self.telemetry.record_queue_depth(len(self.admission))
        live, shed = self.admission.take(self.max_batch_size)
        now = self.clock()
        for req in shed:
            # take() resolved these under the request state lock, so the
            # abandoned flag is final here: an abandoned request already
            # counted as 'timeout'
            if not req.abandoned:
                self.telemetry.record_request(now - req.enqueued_at,
                                              "shed_deadline")
        if not live:
            return 0
        try:
            results = self.endpoint.score_batch([r.record for r in live])
        except Exception as e:  # noqa: BLE001 - endpoint guards, belt+braces
            results = [RowScoringError(f"{type(e).__name__}: {e}")] * len(live)
        done = self.clock()
        for req, res in zip(live, results):
            # resolve_delivered is atomic vs try_abandon: an abandoned
            # request (caller's wait timed out, counted 'timeout') must
            # not ALSO count as delivered 'ok'/'failed'
            if isinstance(res, RowScoringError):
                if res.shed:
                    # shed rows were refused unscored - a distinct
                    # outcome from a scoring failure, so the degradation
                    # is visible in telemetry, not blended into
                    # rows_failed; shed_reason picks the error class
                    # (breaker open vs schema-contract violation)
                    if getattr(res, "shed_reason", "breaker") == "schema":
                        from ..schema.contract import SchemaDriftError

                        err: Exception = SchemaDriftError(res.error)
                        outcome = "shed_schema"
                    else:
                        err = BreakerOpenError(res.error)
                        outcome = "shed_breaker"
                    if req.resolve_delivered(error=err):
                        self.telemetry.record_request(
                            done - req.enqueued_at, outcome)
                elif req.resolve_delivered(error=RuntimeError(res.error)):
                    self.telemetry.record_request(done - req.enqueued_at,
                                                  "failed")
            else:
                if req.resolve_delivered(result=res):
                    self.telemetry.record_request(done - req.enqueued_at,
                                                  "ok")
        return len(live)

    def _worker_loop(self) -> None:
        while not self._closed:
            try:
                self.run_once(wait_timeout_s=0.05)
            except Exception:  # noqa: BLE001 - the loop must survive
                # individual-batch failures already resolve per-request;
                # anything reaching here is a scheduler bug - keep serving
                import logging

                logging.getLogger("transmogrifai_tpu.serving").exception(
                    "serving batch loop error"
                )

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop the worker and fail any still-pending requests loudly.
        Admission closes FIRST (under the queue lock), so no request can
        slip in after the final drain and strand its caller."""
        self._closed = True
        self.admission.close()
        if self._worker is not None:
            self._worker.join(timeout_s)
        for req in self.admission.drain():
            req.resolve(error=RuntimeError("scheduler closed"))

    def __enter__(self) -> "MicroBatchScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
