"""Project generator + registry CLI.

Counterpart of the reference cli module (reference: cli/src/main/scala/com/
salesforce/op/cli/ - CliExec.scala `op gen`, SchemaSource.scala auto-infer,
ProblemKind.scala, the gen/templates/simple project template): infer a
schema from CSV (or read an avro .avsc), infer the problem kind FROM THE
RESPONSE DATA (cardinality + type, ProblemKind semantics), and render a
ready-to-run multi-file python project wired to this framework:

    python -m transmogrifai_tpu.cli gen --input data.csv --response y \
        --name MyApp --output ./myapp [--kind binary|multiclass|regression]
        [--override col=PickList ...] [--id-col id]

Generated project: main.py (train + summary), score.py (load + batch
score), serve.py (micro-batched serving endpoint + telemetry),
params.json (OpParams), test_smoke.py (pytest e2e on a sample),
README.md.

Model-lifecycle commands over a versioned registry (registry/; alias
``tx`` for ``python -m transmogrifai_tpu.cli``):

    tx registry list     --root ./registry            # versions + stages
    tx registry verify   --root ./registry [--version vN]
    tx registry promote  --root ./registry --version vN [--to stable|canary]
    tx registry rollback --root ./registry [--version vN] [--reason ...]

Each prints one JSON document; ``verify`` exits non-zero when any
checksum fails (the prior version must still verify after a crashed
publish - drilled by ``bench.py --registry``).

Observability commands over the unified plane (obs/; artifacts written
by the runner's ``metrics_path`` knob or ``obs.export_obs``):

    tx obs metrics --path <dir-or-metrics.json> [--format prometheus|json]
    tx obs trace   --path <dir-or-spans.jsonl> [--trace-id ID] [--slowest N]

``metrics`` renders a saved registry document as Prometheus text
exposition (the SAME renderer a live scrape uses) or JSON; ``trace``
reconstructs span trees from a JSONL export, optionally only the
slowest N roots (the profiler's p99-exemplar view, offline).

Fleet commands over the scale-out serving fleet (fleet/; ISSUE 14):

    tx fleet status --path <control-or-agg-dir>   # one fleet document
    tx fleet drain  --path <control-dir> --replica replica-1 [--undrain]

``status`` prefers the controller's atomically-published
``fleet_status.json`` (per-replica generation, heartbeat age,
in-flight, restart budget) and falls back to assembling the view from
the obs aggregation shards; ``drain`` queues a command file the live
controller applies (the router stops dispatching to the replica while
it stays warm - the manual half of a rolling deploy).  On a
multi-model fleet (ISSUE 20) both paths carry per-model rows - hosted
version, residency, cold hits, any in-flight canary - and the
placement plan.
"""
from __future__ import annotations

import argparse
import json
import os
import re
from typing import Optional

from .readers.csv_reader import CSVReader
from .types import feature_types as ft

_SELECTOR = {
    "binary": "BinaryClassificationModelSelector",
    "multiclass": "MultiClassificationModelSelector",
    "regression": "RegressionModelSelector",
}
_EVAL = {
    "binary": ("binary", "OpBinaryClassificationEvaluator"),
    "multiclass": ("multiclass", "OpMultiClassificationEvaluator"),
    "regression": ("regression", "OpRegressionEvaluator"),
}

_EMAIL_RE = re.compile(r"^[^@\s]+@[^@\s]+\.[^@\s]+$")
_URL_RE = re.compile(r"^(https?|ftp)://\S+$", re.IGNORECASE)
_PHONE_RE = re.compile(r"^[+()\d][\d\s().-]{6,18}$")


def _refine_text_type(values: list) -> type:
    """Pattern-refine inferred Text columns (reference: SchemaSource
    auto-infer heuristics): emails/urls/phones/low-cardinality picklists."""
    sample = [v for v in values if isinstance(v, str)][:500]
    if not sample:
        return ft.Text
    if all(_EMAIL_RE.match(v) for v in sample):
        return ft.Email
    if all(_URL_RE.match(v) for v in sample):
        return ft.URL
    if sum(bool(_PHONE_RE.match(v)) for v in sample) >= 0.9 * len(sample):
        return ft.Phone
    distinct = len(set(sample))
    if distinct <= max(20, len(sample) // 20):
        return ft.PickList
    return ft.Text


def _is_missing_label(v) -> bool:
    """Shared missing-label rule for kind inference AND the dirty-response
    gate: None, or any value that PARSES to a non-finite number - which
    deliberately includes the textual placeholders 'nan'/'inf'/'1e999'
    even in otherwise-text label columns (they are missing-data markers,
    as pandas also treats them, never legitimate classes).  Text that does
    not parse as a number is never missing."""
    import math

    if v is None:
        return True
    try:
        return not math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


def infer_problem_kind(values: list) -> tuple[str, list]:
    """ProblemKind.scala semantics: the response's distinct values decide.
    Returns (kind, labels) - labels non-empty when the response needs
    indexing: non-numeric classes always; numeric classes whenever they
    are not already the canonical 0..k-1 encoding (e.g. {1,2} binary or
    {1,3,7} multiclass must be re-indexed, not fed raw into log-loss)."""
    present = [v for v in values if not _is_missing_label(v)]
    numeric = []
    for v in present:
        try:
            numeric.append(float(v))
        except (TypeError, ValueError):
            numeric = None
            break
    if numeric is not None and not numeric:
        raise ValueError(
            "response column has no usable values (all missing or "
            "non-finite); cannot infer a problem kind"
        )
    if numeric is not None:
        distinct = sorted(set(numeric))
        canonical = distinct == [float(i) for i in range(len(distinct))]
        if len(distinct) == 2:
            return "binary", ([] if canonical else distinct)
        if (
            len(distinct) <= 30
            and all(float(v).is_integer() for v in distinct)
        ):
            return "multiclass", ([] if canonical else distinct)
        return "regression", []
    labels = sorted({str(v) for v in present})
    return ("binary" if len(labels) == 2 else "multiclass"), labels


# ---------------------------------------------------------------------------
# templates
# ---------------------------------------------------------------------------
_MAIN_TEMPLATE = '''"""{name}: generated by transmogrifai_tpu `gen` (edit freely)."""
import json
import os

import transmogrifai_tpu.dsl  # noqa: F401 - enables feature operators
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.evaluators.{eval_mod} import {eval_cls}
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.readers.csv_reader import CSVReader
from transmogrifai_tpu.selector.factories import {selector}
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

HERE = os.path.dirname(os.path.abspath(__file__))
DATA_PATH = {data_path!r}
MODEL_DIR = os.path.join(HERE, "model")
{labels_block}
# -- raw feature definitions (inferred; adjust types as needed) -------------
{feature_defs}

def build_workflow():
    predictors = [{predictor_names}]
{label_wiring}
    features = transmogrify(predictors)
    checked = label.sanity_check(features, remove_bad_features=True)
    prediction = (
        {selector}.with_cross_validation(num_folds=3)
        .set_input(label, checked)
        .get_output()
    )
    with open(os.path.join(HERE, "params.json")) as f:
        run_params = json.load(f)
    wf = (
        OpWorkflow()
        .set_result_features(prediction)
        .set_reader(CSVReader(DATA_PATH))
        .set_parameters(**run_params)
    )
    return wf, prediction, {eval_cls}()


def main():
    wf, prediction, evaluator = build_workflow()
    runner = OpWorkflowRunner(wf, evaluator=evaluator)
    result = runner.run("train")
    print(result.model.summary_pretty())
    result.model.save(MODEL_DIR)
    print(f"model saved to {{MODEL_DIR}}")


if __name__ == "__main__":
    main()
'''

_SCORE_TEMPLATE = '''"""Batch scorer for {name}: loads the trained model and scores a CSV.

The scored CSV does NOT need the response column - missing raw features
(typically the label on production data) are filled as nulls.
"""
import sys

from transmogrifai_tpu.readers.csv_reader import CSVReader
from transmogrifai_tpu.workflow.workflow import OpWorkflowModel

from main import MODEL_DIR, build_workflow


def main():
    csv_path = sys.argv[1] if len(sys.argv) > 1 else None
    wf, prediction, _ = build_workflow()
    model = OpWorkflowModel.load(MODEL_DIR, wf)
    if csv_path:
        raw = CSVReader(csv_path).read_raw()
        n = len(next(iter(raw.values()))) if raw else 0
        data = {{f.name: raw.get(f.name, [None] * n)
                for f in wf.raw_features}}
    else:
        data = wf.generate_raw_data()
    scored = model.score(data)
    col = scored[prediction.name]
    for row in col.to_list()[:20]:
        print(row)
    print(f"... scored {{len(col)}} rows")


if __name__ == "__main__":
    main()
'''

_SERVE_TEMPLATE = '''"""Serving loop for {name}: compiled endpoint + micro-batcher.

Loads the trained model, compiles the batch-first serving endpoint
(shape-bucketed, warmed), pumps the rows of a CSV through the
micro-batching scheduler as individual requests, and prints the
latency/throughput telemetry (p50/p95/p99, rows/s, batch fill).
"""
import json
import sys

from transmogrifai_tpu.serving import (
    MicroBatchScheduler,
    RowScoringError,
    compile_endpoint,
)
from transmogrifai_tpu.readers.csv_reader import CSVReader
from transmogrifai_tpu.workflow.workflow import OpWorkflowModel

from main import DATA_PATH, MODEL_DIR, build_workflow


def main():
    csv_path = sys.argv[1] if len(sys.argv) > 1 else DATA_PATH
    wf, prediction, _ = build_workflow()
    model = OpWorkflowModel.load(MODEL_DIR, wf)
    raw = CSVReader(csv_path).read_raw()
    n = len(next(iter(raw.values()))) if raw else 0
    records = [
        {{f.name: raw.get(f.name, [None] * n)[i] for f in wf.raw_features}}
        for i in range(n)
    ]
    endpoint = compile_endpoint(model)
    with MicroBatchScheduler(endpoint, max_wait_us=2000) as scheduler:
        results = list(scheduler.score_stream(records, window=256))
    failed = sum(isinstance(r, RowScoringError) for r in results)
    print(f"served {{len(results)}} rows ({{failed}} failed)")
    print(json.dumps(endpoint.telemetry.snapshot(), indent=1))


if __name__ == "__main__":
    main()
'''

_TEST_TEMPLATE = '''"""Smoke test for the generated {name} project."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def test_trains_and_scores(tmp_path):
    import main as app

    wf, prediction, evaluator = app.build_workflow()
    model = wf.train()
    scored = model.score(wf.generate_raw_data())
    assert prediction.name in scored
    model.save(str(tmp_path / "model"))
'''

_README_TEMPLATE = """# {name}

Generated by `python -m transmogrifai_tpu.cli gen` - a {kind} AutoML
project on `{data_basename}` (response: `{response}`).

## Commands

- `python main.py` - train (3-fold CV model selection), print the model
  summary, save the fitted model to `./model/`
- `python score.py [other.csv]` - load the saved model and batch-score
- `python serve.py [other.csv]` - micro-batched serving loop with
  latency/throughput telemetry (p50/p95/p99, rows/s)
- `python -m pytest test_smoke.py` - end-to-end smoke test

## Files

- `main.py` - feature definitions (edit the inferred types freely) + train
- `params.json` - OpParams run configuration (test fraction, seeds)
- `score.py` - engine-free batch scoring against the saved model
- `serve.py` - compiled serving endpoint behind the micro-batch scheduler
"""


def _avsc_to_schema(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    mapping = {
        "int": ft.Integral, "long": ft.Integral, "float": ft.Real,
        "double": ft.Real, "boolean": ft.Binary, "string": ft.Text,
    }
    schema = {}
    for field in doc.get("fields", []):
        t = field["type"]
        if isinstance(t, list):
            t = next((x for x in t if x != "null"), "string")
        schema[field["name"]] = mapping.get(t, ft.Text)
    return schema


# -- interactive question dialogue (reference: cli/gen/Ops.scala UserIO +
# CliParameters.answersFile) -------------------------------------------------
def load_answers(path: str) -> dict[str, str]:
    """Answers file: 'question-prefix => answer' lines (reference
    Ops.scala:90-102); prefixes match lowercased question starts, so the
    same file drives a scripted non-interactive generation."""
    out: dict[str, str] = {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            stripped = line.strip()
            if not stripped or stripped.startswith("#"):
                continue
            if "=>" not in stripped:
                # malformed lines must not silently vanish: a dropped
                # entry turns a scripted run interactive (review r5)
                raise ValueError(
                    f"{path}:{lineno}: expected 'prefix => answer', "
                    f"got {stripped!r}"
                )
            k, v = stripped.split("=>", 1)
            out[k.strip().lower()] = v.strip()
    return out


def ask(question, options, answers=None, input_fn=input, strict=False):
    """Indexed-choice prompt (reference Ops.scala ask: 'Q? [0] a [1] b:').

    ``options``: list of (value, [aliases...]) - the first alias is
    displayed; the index or any alias (case-insensitive) is accepted;
    invalid input re-prompts.  ``answers`` short-circuits stdin by
    lowercased question prefix, exactly like the reference's answers
    file."""
    normalized: dict[str, object] = {}
    descs = []
    for i, (value, aliases) in enumerate(options):
        if not aliases:
            raise ValueError("ask needs at least one alias per option")
        descs.append(f"[{i}] {aliases[0]}")
        normalized[str(i)] = value
        for a in aliases:
            normalized[a.lower()] = value
    q = question + " " + " ".join(descs) + ": "
    ql = question.strip().lower()
    if answers is not None:  # {} is still a scripted run: strict applies
        # layered prefixes: the FIRST matching entry with a VALID answer
        # wins (an earlier broader prefix with an answer outside this
        # question's options defers to a later, more specific one)
        matched = []
        for prefix, ans in answers.items():
            if ql.startswith(prefix):
                matched.append((prefix, ans))
                key = ans.strip().lower()
                if key in normalized:
                    return normalized[key]
        if strict:
            # purely scripted runs must fail fast, not block on stdin
            if matched:
                raise ValueError(
                    f"answers file maps {matched[0][0]!r} to invalid "
                    f"answer {matched[0][1]!r} for question: {question}"
                )
            raise ValueError(
                f"answers file has no entry for question: {question}"
            )
    while True:
        try:
            resp = input_fn(q)
        except EOFError:
            raise ValueError(
                f"no answer available for question: {question}"
            ) from None
        key = str(resp).strip().lower()
        if key in normalized:
            return normalized[key]


def generate(
    input_path: str,
    response: str,
    name: str,
    output: str,
    kind: Optional[str] = None,
    overrides: Optional[dict] = None,
    id_col: Optional[str] = None,
    interactive: bool = False,
    answers: Optional[dict] = None,
    input_fn=input,
    strict_answers: Optional[bool] = None,
) -> str:
    # strict answers = fail fast on an unanswerable question rather than
    # falling through to a stdin prompt that could block a scripted run
    # forever.  Default: strict exactly when an answers map drives the
    # dialogue; callers who combine --interactive with a PARTIAL answers
    # file pass strict_answers=False to get prompted for the rest.
    if strict_answers is None:
        strict_answers = answers is not None
    raw_values: dict[str, list] = {}
    if input_path.endswith(".avsc"):
        schema = _avsc_to_schema(input_path)
        data_path = input_path[: -len(".avsc")] + ".avro"
    else:
        reader = CSVReader(input_path)
        raw_values = reader.read_raw()  # one parse: schema derives from it
        schema = reader.infer_schema(raw_values)
        data_path = input_path
        # pattern-refine text columns (emails/urls/phones/picklists)
        for col, t in list(schema.items()):
            if t is ft.Text and col in raw_values:
                schema[col] = _refine_text_type(raw_values[col])
    for col, t in (overrides or {}).items():
        if col not in schema:
            raise KeyError(f"override column {col!r} not in schema")
        schema[col] = t
    if response not in schema:
        raise KeyError(
            f"response {response!r} not found; columns: {sorted(schema)}"
        )

    if id_col is not None and id_col not in schema:
        raise KeyError(
            f"id column {id_col!r} not found; columns: {sorted(schema)}"
        )
    if id_col == response:
        raise ValueError("--id-col and --response cannot be the same column")

    # a dirty response corrupts training silently (missing / textual-nan
    # labels would collapse into class 0): demand a clean label column
    if response in raw_values:
        bad = sum(_is_missing_label(v) for v in raw_values[response])
        if bad:
            raise ValueError(
                f"response column {response!r} has {bad} missing/non-finite "
                f"values out of {len(raw_values[response])}; clean the data "
                "before generating (labels cannot be imputed)"
            )

    # interactive dialogue (reference ProblemKind.askKind + the id-field
    # question in ProblemSchema.scala): confirm the inferred kind and pick
    # the row-id column; an answers map scripts both
    if interactive:
        if kind is None:
            inferred = None
            if response in raw_values:
                inferred, _ = infer_problem_kind(raw_values[response])
            opts = []
            if inferred:
                opts.append((inferred, [inferred, "yes", "inferred"]))
            opts.extend(
                (k, [k]) for k in _SELECTOR if k != inferred
            )
            kind = ask(
                f"Problem kind for response '{response}'"
                + (f" (inferred: {inferred})" if inferred else ""),
                opts, answers=answers, input_fn=input_fn,
                strict=strict_answers,
            )
        if id_col is None:
            candidates = [c for c in schema if c != response]
            opts = [(None, ["none", "no id column"])] + [
                (c, [c]) for c in candidates
            ]
            id_col = ask(
                "Which column is the row id (excluded from predictors)?",
                opts, answers=answers, input_fn=input_fn,
                strict=strict_answers,
            )

    labels: list = []
    if kind is None:
        if response in raw_values:
            kind, labels = infer_problem_kind(raw_values[response])
        else:
            kind = "regression" if schema[response] is ft.Real else "binary"
    elif response in raw_values:
        _, labels = infer_problem_kind(raw_values[response])
        if kind == "regression":
            labels = []
    numeric_labels = bool(labels) and isinstance(labels[0], float)

    eval_mod, eval_cls = _EVAL[kind]

    # names owned by the generated module/function - feature variables must
    # never shadow them (a response column literally named 'label' is
    # common), and every emitted name must be a unique valid identifier
    import keyword

    reserved = {
        "label", "predictors", "features", "checked", "prediction", "wf",
        "run_params", "main", "build_workflow", "f", "json", "os", "ft",
        "transmogrify", "FeatureBuilder", "OpWorkflow", "CSVReader",
        "OpWorkflowRunner", "LABELS", "DATA_PATH", "MODEL_DIR", "HERE",
    }
    used: set[str] = set()

    def _var_for(col: str) -> str:
        # per-char identifier test: isalnum() admits characters like '²'
        # that are not valid in identifiers
        var = "".join(
            c if ("_" + c).isidentifier() else "_" for c in col
        ).lower()
        if not var.isidentifier():
            # leading digit or identifier-continue-only start (e.g. a
            # combining mark): a letter prefix makes every kept char legal
            var = "c_" + var
        while keyword.iskeyword(var) or var in reserved or var in used:
            var += "_"
        used.add(var)
        return var

    defs, pred_names = [], []
    response_var = None
    for col, t in sorted(schema.items()):
        if col == id_col:
            continue  # row keys never become predictors
        var = _var_for(col)
        if col == response:
            response_var = var
            rtype = "RealNN" if (not labels or numeric_labels) else "PickList"
            defs.append(
                f"{var} = FeatureBuilder(ft.{rtype}, {col!r}).as_response()"
            )
        else:
            defs.append(
                f"{var} = FeatureBuilder(ft.{t.__name__}, {col!r})"
                ".as_predictor()"
            )
            pred_names.append(var)

    if labels:
        labels_block = f"LABELS = {labels!r}\n"
        # unseen labels (absent from the generation-time sample) map to
        # None rather than crashing a retrain/score on fuller data;
        # numeric class values compare as floats
        probe = "float(v)" if numeric_labels else "v"
        label_wiring = (
            f"    label = {response_var}.map_values(\n"
            f"        lambda v: float(LABELS.index({probe}))"
            f" if v is not None and {probe} in LABELS else None,\n"
            "        ft.RealNN,\n    )"
        )
    else:
        labels_block = ""
        label_wiring = f"    label = {response_var}"

    os.makedirs(output, exist_ok=True)
    files = {
        "main.py": _MAIN_TEMPLATE.format(
            name=name,
            data_path=os.path.abspath(data_path),
            feature_defs="\n".join(defs),
            predictor_names=", ".join(pred_names),
            labels_block=labels_block,
            label_wiring=label_wiring,
            selector=_SELECTOR[kind],
            eval_mod=eval_mod,
            eval_cls=eval_cls,
        ),
        "score.py": _SCORE_TEMPLATE.format(name=name),
        "serve.py": _SERVE_TEMPLATE.format(name=name),
        "test_smoke.py": _TEST_TEMPLATE.format(name=name),
        "params.json": json.dumps(
            {"reserve_test_fraction": 0.1, "split_seed": 42}, indent=2
        ),
        "README.md": _README_TEMPLATE.format(
            name=name, kind=kind, response=response,
            data_basename=os.path.basename(data_path),
        ),
    }
    for fname, content in files.items():
        with open(os.path.join(output, fname), "w") as f:
            f.write(content)
    return os.path.join(output, "main.py")


def _parse_override(s: str) -> tuple[str, type]:
    col, _, tname = s.partition("=")
    if not tname:
        raise argparse.ArgumentTypeError(
            f"override must be col=FeatureType, got {s!r}"
        )
    try:
        t = ft.feature_type_by_name(tname)
    except KeyError as e:
        raise argparse.ArgumentTypeError(str(e)) from e
    return col, t


# ---------------------------------------------------------------------------
# observability commands (obs/: metrics exposition + span trees + SLOs)
# ---------------------------------------------------------------------------
def _obs_resolve(path: str, default_name: str) -> str:
    """Accept either the export directory (the ``metrics_path`` knob's
    output) or the file itself."""
    if os.path.isdir(path):
        return os.path.join(path, default_name)
    return path


def _is_agg_dir(path: str) -> bool:
    """A fleet aggregation dir: per-process ``*.obsshard.json`` files
    (obs.fleet shippers) rather than a single-process export."""
    from .obs.fleet import SHARD_SUFFIX

    if not os.path.isdir(path):
        return False
    try:
        return any(n.endswith(SHARD_SUFFIX) for n in os.listdir(path))
    except OSError:
        return False


def _obs_load_spans(args) -> tuple[list, int, Optional[dict]]:
    """-> (records, lines_skipped, fleet_report).  An aggregation dir
    merges every live shard's spans (dead processes age out, torn
    shards are counted); a plain export reads spans.jsonl through the
    torn-read-safe loader - a process killed mid-export truncates its
    LAST line, which must cost one span, not the whole read."""
    from .obs.fleet import FleetAggregator, read_jsonl_tolerant

    if _is_agg_dir(args.path):
        agg = FleetAggregator(args.path,
                              stale_after_s=args.stale_after_s)
        return agg.merged_spans(), 0, dict(agg.last_report)
    records, skipped = read_jsonl_tolerant(
        _obs_resolve(args.path, "spans.jsonl"))
    return records, skipped, None


def _obs_main(args) -> int:
    from .obs import build_trees, prometheus_text_from_json
    from .obs.fleet import FleetAggregator

    if args.obs_cmd == "metrics":
        if _is_agg_dir(args.path):
            agg = FleetAggregator(args.path,
                                  stale_after_s=args.stale_after_s)
            if args.format == "prometheus":
                print(agg.prometheus_text(), end="")
            else:
                print(json.dumps(agg.to_json(), indent=1, sort_keys=True,
                                 default=str))
            return 0
        path = _obs_resolve(args.path, "metrics.json")
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 2
        if args.format == "prometheus":
            print(prometheus_text_from_json(doc), end="")
        else:
            print(json.dumps(doc, indent=1, sort_keys=True, default=str))
        return 0
    if args.obs_cmd == "trace":
        try:
            records, skipped, fleet_report = _obs_load_spans(args)
        except OSError as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 2
        if args.trace_id:
            records = [r for r in records if r.get("trace") == args.trace_id]
        trees = build_trees(records)
        if args.slowest:
            trees = sorted(
                trees, key=lambda t: -float(t.get("wall_ms", 0.0))
            )[: args.slowest]
        out = {
            "spans": len(records),
            "roots": len(trees),
            "lines_skipped": skipped,
            "trees": trees,
        }
        if fleet_report is not None:
            out["fleet"] = fleet_report
        print(json.dumps(out, indent=1, sort_keys=True, default=str))
        return 0
    if args.obs_cmd == "slo":
        from .obs.slo import SLOEngine, default_objectives, load_slo_config

        try:
            objectives = (load_slo_config(args.config) if args.config
                          else default_objectives())
            if _is_agg_dir(args.path):
                agg = FleetAggregator(args.path,
                                      stale_after_s=args.stale_after_s)
                docs = agg.merged_metrics_docs()
            else:
                with open(_obs_resolve(args.path, "metrics.json")) as f:
                    docs = json.load(f)
        except (OSError, ValueError) as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 2
        # one-shot evaluation: cumulative totals ARE the window (the
        # engine's baseline-less fallback), so a saved artifact whose
        # lifetime error ratio blew the objective reads as firing
        engine = SLOEngine(objectives, register=False)
        engine.observe(docs)
        report = engine.report()
        print(json.dumps(report, indent=1, sort_keys=True, default=str))
        return 1 if report["firing"] else 0
    raise AssertionError(f"unhandled obs command {args.obs_cmd}")


def _add_obs_parser(sub) -> None:
    o = sub.add_parser("obs", help="unified observability plane "
                                   "(metrics exposition, span trees, "
                                   "SLO evaluation)")
    osub = o.add_subparsers(dest="obs_cmd", required=True)
    m = osub.add_parser("metrics",
                        help="render an exported metrics document or a "
                             "fleet aggregation dir")
    m.add_argument("--path", required=True,
                   help="export dir (metrics_path knob), metrics.json, "
                        "or a fleet aggregation dir (obsshard files)")
    m.add_argument("--format", choices=("prometheus", "json"),
                   default="prometheus")
    t = osub.add_parser("trace", help="reconstruct span trees from a "
                                      "spans.jsonl export or a fleet "
                                      "aggregation dir")
    t.add_argument("--path", required=True,
                   help="export dir (metrics_path knob), spans.jsonl, "
                        "or a fleet aggregation dir")
    t.add_argument("--trace-id", default=None,
                   help="only this trace id")
    t.add_argument("--slowest", type=int, default=None, metavar="N",
                   help="only the N slowest root spans")
    s = osub.add_parser("slo", help="evaluate declarative SLOs against "
                                    "exported/aggregated metrics "
                                    "(exit 1 when any alert fires)")
    s.add_argument("--path", required=True,
                   help="export dir, metrics.json, or aggregation dir")
    s.add_argument("--config", default=None,
                   help="SLO config JSON ({'slos': [...]}); default: "
                        "the built-in serving objectives")
    for cmd in (m, t, s):
        cmd.add_argument("--stale-after-s", type=float, default=None,
                         dest="stale_after_s", metavar="S",
                         help="aggregation-dir heartbeat staleness "
                              "cutoff (default TX_OBS_FLEET_STALE_S/60)")


# ---------------------------------------------------------------------------
# autotune commands (autotune/: cost model + decision trails, ISSUE 13)
# ---------------------------------------------------------------------------
def _autotune_main(args) -> int:
    from .autotune import report_from_path

    if args.autotune_cmd == "report":
        try:
            doc = report_from_path(args.path)
        except (OSError, ValueError) as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 2
        print(json.dumps(doc, indent=1, sort_keys=True, default=str))
        return 0
    raise AssertionError(f"unhandled autotune command {args.autotune_cmd}")


def _add_autotune_parser(sub) -> None:
    a = sub.add_parser(
        "autotune",
        help="cost-model-driven autotuning (selection pruning trails, "
             "cost-model state, tuned knobs)",
    )
    asub = a.add_subparsers(dest="autotune_cmd", required=True)
    r = asub.add_parser(
        "report",
        help="render the autotune decision trail: pruning rungs, "
             "predicted-vs-actual times, cost-model state, tuned knobs",
    )
    r.add_argument(
        "--path", required=True,
        help="a trained model directory (summary.json + autotune.json "
             "written by a train run with the autotune knob) or an obs "
             "export dir (metrics_path knob: metrics.json + spans.jsonl)",
    )


# ---------------------------------------------------------------------------
# fleet commands (fleet/: replica status + operator drain, ISSUE 14)
# ---------------------------------------------------------------------------
def _fleet_status_doc(path: str, stale_after_s=None) -> dict:
    """Build the fleet status document for ``path``: the controller's
    one consistent ``fleet_status.json`` when present (a control dir,
    a fleet work dir, or the file itself), else assembled from the obs
    aggregation shards (per-replica ``fleet`` info + serving views +
    heartbeat ages)."""
    from .fleet.controller import STATUS_FILENAME
    from .obs.fleet import (
        SHARD_SUFFIX,
        FleetAggregator,
        autoscaler_views,
        health_views,
        read_json_torn_safe,
        serving_views,
    )
    from .workflow.supervisor import staleness

    candidates = [path] if path.endswith(".json") else [
        os.path.join(path, STATUS_FILENAME),
        os.path.join(path, "control", STATUS_FILENAME),
    ]
    for cand in candidates:
        if os.path.exists(cand):
            doc = read_json_torn_safe(cand)
            if doc is not None:
                return {"source": cand, "status": doc}
            raise ValueError(f"{cand}: torn/unreadable status document")
    for agg_path in (path, os.path.join(path, "obs")):
        if _is_agg_dir(agg_path):
            agg = FleetAggregator(agg_path, stale_after_s=stale_after_s)
            shards = agg.shards()
            # the router's own shard (ship_router_obs) carries the
            # fleet_health view: per-replica transport-health columns
            # (ejected/probing/healthy, consecutive failures, last RTT)
            # from ONE consistent document, no shard re-reads
            health_by_replica: dict = {}
            fleet_health: dict = {}
            autoscaler: dict = {}
            for shard in shards:
                for _key, snap in health_views(
                        shard.get("metrics", {})):
                    for inst, h in (snap.get("replicas") or {}).items():
                        health_by_replica[str(inst)] = h
                    fleet_health = {k: v for k, v in snap.items()
                                    if k != "replicas"}
                # the ISSUE-19 capacity control loop ships one
                # autoscaler view from wherever it runs; fold the
                # freshest one in as its own status column
                for _key, snap in autoscaler_views(
                        shard.get("metrics", {})):
                    if snap.get("steps", 0) >= autoscaler.get(
                            "steps", -1):
                        autoscaler = snap
            replicas = {}
            for shard in shards:
                inst = str(shard.get("instance"))
                if inst == "router":
                    continue  # its health view is folded in above
                shard_file = os.path.join(agg_path,
                                          inst + SHARD_SUFFIX)
                serving = {}
                for _key, snap in serving_views(
                        shard.get("metrics", {})):
                    if snap.get("rows_scored", 0) >= serving.get(
                            "rows_scored", -1):
                        serving = {
                            "version": snap.get("model_version"),
                            "generation": snap.get("generation"),
                            "rows_scored": snap.get("rows_scored"),
                            "rows_per_s": snap.get("rows_per_s"),
                            "p99_ms": (snap.get("latency_ms")
                                       or {}).get("p99"),
                        }
                age = staleness(shard_file)
                replicas[inst] = {
                    "heartbeat_age_s": (None if age is None
                                        else round(age, 3)),
                    "fleet": shard.get("fleet"),
                    "serving": serving or None,
                    "health": health_by_replica.get(inst),
                }
            out = {"source": agg_path,
                   "shards": dict(agg.last_report),
                   "replicas": replicas}
            models = _fold_model_rows(replicas)
            if models:
                out["models"] = models
            if fleet_health:
                out["fleet_health"] = fleet_health
            if autoscaler:
                out["autoscaler"] = autoscaler
            return out
    raise ValueError(
        f"{path!r} holds neither a fleet status document nor an obs "
        "aggregation dir")


def _fold_model_rows(replicas: dict) -> dict:
    """Per-model aggregate rows (ISSUE 20) from each replica's
    ``fleet.models`` table rows: where it is hosted/resident, the
    summed row/cold-hit counters, any in-flight canary."""
    models: dict = {}
    for inst in sorted(replicas):
        fleet = replicas[inst].get("fleet") or {}
        for row in fleet.get("models") or []:
            mid = str(row.get("model_id"))
            m = models.setdefault(mid, {
                "version": row.get("version"),
                "hosts": [], "resident_on": [], "evicted_on": [],
                "rows_scored": 0, "cold_hits": 0, "rehydrations": 0,
                "canary_version": None,
            })
            m["hosts"].append(inst)
            m["resident_on" if row.get("resident")
              else "evicted_on"].append(inst)
            m["rows_scored"] += int(row.get("rows_scored") or 0)
            m["cold_hits"] += int(row.get("cold_hits") or 0)
            m["rehydrations"] += int(row.get("rehydrations") or 0)
            if row.get("canary_version"):
                m["canary_version"] = row["canary_version"]
    return models


def _fleet_main(args) -> int:
    from .fleet.controller import COMMANDS_DIR

    if args.fleet_cmd == "status":
        try:
            doc = _fleet_status_doc(args.path,
                                    stale_after_s=args.stale_after_s)
        except (OSError, ValueError) as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 2
        print(json.dumps(doc, indent=1, sort_keys=True, default=str))
        return 0
    if args.fleet_cmd == "drain":
        import tempfile
        import time as _time

        cdir = os.path.join(args.path, COMMANDS_DIR)
        try:
            os.makedirs(cdir, exist_ok=True)
            doc = {"replica": args.replica,
                   "drain": not args.undrain,
                   "t": _time.time()}
            # atomic drop: the controller's poll must never read a torn
            # command and apply half an intention
            fd, tmp = tempfile.mkstemp(dir=cdir, suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, os.path.join(cdir, args.replica + ".json"))
        except OSError as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 2
        print(json.dumps({"queued": doc,
                          "path": os.path.join(cdir,
                                               args.replica + ".json")}))
        return 0
    raise AssertionError(f"unhandled fleet command {args.fleet_cmd}")


def _add_fleet_parser(sub) -> None:
    f = sub.add_parser(
        "fleet",
        help="scale-out serving fleet (replica status, operator drain)")
    fsub = f.add_subparsers(dest="fleet_cmd", required=True)
    s = fsub.add_parser(
        "status",
        help="one consistent fleet document: per-replica generation, "
             "heartbeat age, in-flight, router counters")
    s.add_argument("--path", required=True,
                   help="fleet control dir (fleet_status.json), fleet "
                        "work dir, or obs aggregation dir")
    s.add_argument("--stale-after-s", type=float, default=None,
                   dest="stale_after_s", metavar="S",
                   help="shard heartbeat staleness cutoff when reading "
                        "an aggregation dir")
    d = fsub.add_parser(
        "drain",
        help="queue a drain (or --undrain) command the fleet "
             "controller applies: the router stops dispatching to the "
             "replica while it stays warm")
    d.add_argument("--path", required=True,
                   help="fleet control dir (the controller polls its "
                        "commands/ subdirectory)")
    d.add_argument("--replica", required=True,
                   help="replica instance name, e.g. replica-1")
    d.add_argument("--undrain", action="store_true",
                   help="resume dispatch to the replica")


# ---------------------------------------------------------------------------
# continuous commands (continuous/: drift-triggered refit controller)
# ---------------------------------------------------------------------------
def _continuous_status_doc(path: str) -> dict:
    """The continuous trainer's atomically-published status document:
    ``path`` may be the ``continuous_status.json`` file itself or a
    directory holding one (the trainer's status dir / watch dir)."""
    from .continuous import STATUS_FILENAME
    from .obs.fleet import read_json_torn_safe

    candidates = [path] if path.endswith(".json") else [
        os.path.join(path, STATUS_FILENAME),
    ]
    for cand in candidates:
        if os.path.exists(cand):
            doc = read_json_torn_safe(cand)
            if doc is not None:
                return {"source": cand, "status": doc}
            raise ValueError(f"{cand}: torn/unreadable status document")
    raise ValueError(
        f"{path!r} holds no continuous status document "
        f"({STATUS_FILENAME})")


def _continuous_main(args) -> int:
    if args.continuous_cmd == "status":
        try:
            doc = _continuous_status_doc(args.path)
        except (OSError, ValueError) as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 2
        print(json.dumps(doc, indent=1, sort_keys=True, default=str))
        return 0
    raise AssertionError(
        f"unhandled continuous command {args.continuous_cmd}")


def _add_continuous_parser(sub) -> None:
    c = sub.add_parser(
        "continuous",
        help="drift-triggered continuous training loop (cycle "
             "counters, governor state, last cycle verdict)")
    csub = c.add_subparsers(dest="continuous_cmd", required=True)
    s = csub.add_parser(
        "status",
        help="the trainer's atomically-published status document: "
             "cycles/refits/promotes/rollbacks, hysteresis + cooldown "
             "state, last cycle trace id")
    s.add_argument("--path", required=True,
                   help="continuous status dir (continuous_status.json)"
                        " or the file itself")


# ---------------------------------------------------------------------------
# bulk scoring commands (bulk/: exactly-once checkpointed batch inference)
# ---------------------------------------------------------------------------
def _bulk_main(args) -> int:
    from .bulk import BulkJournal, TornJournalError

    if args.bulk_cmd == "status":
        try:
            doc = BulkJournal.load(args.job_dir).status_doc()
        except TornJournalError as e:
            # exit 1 is the torn-journal verdict an operator scripts
            # against (both the primary and .last-good failed their
            # checksums) - everything else is a plain error (2)
            print(json.dumps({"error": f"TornJournalError: {e}"}))
            return 1
        except (OSError, ValueError) as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 2
        print(json.dumps(doc, indent=1, sort_keys=True, default=str))
        return 0
    raise AssertionError(f"unhandled bulk command {args.bulk_cmd}")


def _add_bulk_parser(sub) -> None:
    b = sub.add_parser(
        "bulk",
        help="exactly-once bulk scoring jobs (checkpointed journal, "
             "kill-survivable resume)")
    bsub = b.add_subparsers(dest="bulk_cmd", required=True)
    s = bsub.add_parser(
        "status",
        help="the job journal as JSON: per-shard states, the "
             "double-entry row ledger, resume history; exit 1 when "
             "the journal (and its .last-good fallback) is torn")
    s.add_argument("job_dir", help="bulk job directory (holds journal.json)")


# ---------------------------------------------------------------------------
# registry commands (registry/: versioned store + lifecycle)
# ---------------------------------------------------------------------------
def _registry_main(args) -> int:
    from .registry import ModelRegistry, RegistryError

    try:
        reg = ModelRegistry(args.root, create=False)
    except RegistryError as e:
        print(json.dumps({"error": str(e)}))
        return 2
    try:
        if args.registry_cmd == "list":
            doc = reg.describe(lineage=args.lineage)
            print(json.dumps(doc, indent=1, sort_keys=True, default=str))
            return 0
        if args.registry_cmd == "verify":
            report = reg.verify(args.version)
            print(json.dumps(report, indent=1, sort_keys=True))
            return 0 if report["ok"] else 1
        if args.registry_cmd == "promote":
            entry = reg.promote(args.version, to=args.to)
            print(json.dumps(entry.to_json(), indent=1, sort_keys=True,
                             default=str))
            return 0
        if args.registry_cmd == "rollback":
            event = reg.rollback(version=args.version,
                                 reason=args.reason or "cli")
            print(json.dumps(event, indent=1, sort_keys=True, default=str))
            return 0
    except RegistryError as e:
        print(json.dumps({"error": str(e)}))
        return 2
    raise AssertionError(f"unhandled registry command {args.registry_cmd}")


def _add_registry_parser(sub) -> None:
    r = sub.add_parser("registry",
                       help="versioned model registry lifecycle")
    rsub = r.add_subparsers(dest="registry_cmd", required=True)
    for name, helptext in (
        ("list", "versions, stages, stable/canary pointers"),
        ("verify", "checksum-verify the index and artifacts"),
        ("promote", "candidate->canary or candidate/canary->stable"),
        ("rollback", "demote the canary (or revert stable to parent)"),
    ):
        c = rsub.add_parser(name, help=helptext)
        c.add_argument("--root", required=True,
                       help="registry root directory")
        if name == "list":
            c.add_argument("--lineage", action="store_true",
                           help="include the lineage event log")
        if name == "verify":
            c.add_argument("--version", default=None,
                           help="verify one version (default: all)")
        if name == "promote":
            c.add_argument("--version", required=True)
            c.add_argument("--to", choices=("stable", "canary"),
                           default="stable")
        if name == "rollback":
            c.add_argument("--version", default=None,
                           help="default: the live canary, else stable")
            c.add_argument("--reason", default=None)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="transmogrifai_tpu.cli")
    sub = p.add_subparsers(dest="cmd", required=True)
    _add_registry_parser(sub)
    _add_obs_parser(sub)
    _add_autotune_parser(sub)
    _add_fleet_parser(sub)
    _add_continuous_parser(sub)
    _add_bulk_parser(sub)
    g = sub.add_parser("gen", help="generate a project from data")
    g.add_argument("--input", required=True, help="CSV or .avsc path")
    g.add_argument("--response", required=True)
    g.add_argument("--name", default="GeneratedApp")
    g.add_argument("--output", required=True)
    g.add_argument("--kind", choices=list(_SELECTOR), default=None,
                   help="problem kind (default: inferred from the response)")
    g.add_argument("--override", action="append", type=_parse_override,
                   default=[], metavar="COL=TYPE",
                   help="feature type override, e.g. cabin=PickList")
    g.add_argument("--id-col", default=None,
                   help="row-key column excluded from predictors")
    g.add_argument("--interactive", action="store_true",
                   help="ask the generator questions (problem kind, id "
                        "column) instead of relying on flags/inference")
    g.add_argument("--answers", default=None, metavar="FILE",
                   help="'question-prefix => answer' lines scripting the "
                        "interactive questions (reference: op gen "
                        "--answers)")
    args = p.parse_args(argv)
    if args.cmd == "registry":
        return _registry_main(args)
    if args.cmd == "obs":
        return _obs_main(args)
    if args.cmd == "autotune":
        return _autotune_main(args)
    if args.cmd == "fleet":
        return _fleet_main(args)
    if args.cmd == "continuous":
        return _continuous_main(args)
    if args.cmd == "bulk":
        return _bulk_main(args)
    answers = load_answers(args.answers) if args.answers else None
    path = generate(
        args.input, args.response, args.name, args.output, args.kind,
        overrides=dict(args.override), id_col=args.id_col,
        interactive=args.interactive or answers is not None,
        answers=answers,
        # explicit --interactive + partial --answers: prompt for the rest
        strict_answers=not args.interactive,
    )
    print(f"generated {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
