"""Cross-process observability: trace-context propagation helpers,
per-process metric/span shipping, and fleet aggregation (ISSUE 11).

PR 7's plane is complete but single-process: every ROADMAP direction
that remains (scale-out serving fleet, continuous training through
supervisor re-dispatch) spans processes, and N processes each holding a
perfect local registry is still zero fleet observability.  This module
is the substrate those items stand on:

* **trace-context propagation** - :func:`child_env` packages the
  ambient span's ``<trace_id>:<span_id>`` into the
  ``TX_OBS_TRACE_CONTEXT`` env seam (``trace.TRACE_CONTEXT_ENV``); a
  child's Tracer adopts it at construction, so one trace id follows a
  parent run into every process it spawns - supervisor re-dispatch,
  mesh-peer bootstrap children, deploy-drill children.
* **shipping** - :func:`ship_now` / :class:`ObsShipper` write this
  process's whole plane (MetricsRegistry document + tracer span ring)
  to ONE per-process file in an aggregation directory, by tempfile +
  atomic ``os.replace`` so a reader can never observe a torn shard,
  mtime-heartbeat-stamped exactly like ``parallel.resilience.
  PeerHealth`` peers (liveness rides the filesystem; the process being
  dead is exactly when it cannot be asked).
* **aggregation** - :class:`FleetAggregator` merges the LIVE shards
  (stale heartbeats age out, torn/partial files are skipped and
  counted, never raised) into one Prometheus exposition with
  per-process ``instance`` labels plus fleet-level sums/maxes, and
  merges the span shards into one tree for ``tx obs trace`` - made
  linkable across pids by trace.py's collision-safe span ids.

Every read of a shard or spans.jsonl goes through the torn-read-safe
loaders :func:`read_json_torn_safe` / :func:`read_jsonl_tolerant`
(style-gated in tests/test_style.py): a process SIGKILLed mid-export
must cost the fleet one shard's freshness, never the whole scrape.

Stdlib-only and importable before jax/numpy init, like the rest of
obs/ - the measurement plane must not depend on the stack it measures.
"""
from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from typing import Iterable, Optional

from .metrics import (
    metrics_registry,
    process_instance,
    prometheus_text_from_json,
    sanitize_metric_name,
    _fmt_value,
    _numeric_leaves,
    _sanitize_instance,
)
from .trace import TRACE_CONTEXT_ENV, build_trees, tracer

log = logging.getLogger("transmogrifai_tpu.obs")

__all__ = [
    "FleetAggregator",
    "ObsShipper",
    "SHARD_SUFFIX",
    "child_env",
    "read_json_torn_safe",
    "read_jsonl_tolerant",
    "serving_views",
    "ship_now",
]

#: per-process shard files in an aggregation dir: ``<instance>`` +
#: this suffix (tempfiles carry ``.tmp`` and are never read)
SHARD_SUFFIX = ".obsshard.json"

#: a shard whose mtime-heartbeat is older than this is a dead process
#: (the PeerHealth staleness convention); env knob for fleets whose
#: shippers beat slower
DEFAULT_STALE_S = 60.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# trace-context propagation
# ---------------------------------------------------------------------------
def child_env(env: Optional[dict] = None) -> dict:
    """Environment for a child process that should JOIN this process's
    trace: a copy of ``env`` (default ``os.environ``) with
    ``TX_OBS_TRACE_CONTEXT`` set to the ambient span's context.  With
    no exportable context (tracer disabled, no span open, nothing
    adopted) the var is REMOVED - a stale inherited context must not
    graft a child onto a long-finished trace."""
    out = dict(os.environ if env is None else env)
    ctx = tracer().current_context()
    if ctx:
        out[TRACE_CONTEXT_ENV] = ctx
    else:
        out.pop(TRACE_CONTEXT_ENV, None)
    return out


# ---------------------------------------------------------------------------
# torn-read-safe loaders (THE way fleet files are read; style-gated)
# ---------------------------------------------------------------------------
def read_json_torn_safe(path: str) -> Optional[dict]:
    """Read one JSON document, returning ``None`` for ANY torn state -
    vanished file (shipper replaced it mid-listing), partial/corrupt
    bytes (a writer SIGKILLed mid-write on a filesystem whose rename
    discipline failed), or a non-dict payload.  Callers count the None,
    they never see the exception: one dying process must not take down
    the fleet scrape."""
    try:
        with open(path, "rb") as f:
            doc = json.loads(f.read().decode("utf-8", "replace"))
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def read_jsonl_tolerant(path: str) -> tuple[list[dict], int]:
    """Read a JSONL file skip-and-count style: returns the parseable
    records plus how many lines were skipped (truncated tail from a
    process killed mid-export, corrupt bytes).  Shared by the fleet
    span merger and ``tx obs trace`` - a partial last line must cost
    one span, not the whole trace read."""
    records: list[dict] = []
    skipped = 0
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line.decode("utf-8", "replace"))
            except ValueError:
                skipped += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                skipped += 1
    return records, skipped


def serving_views(metrics_doc: dict):
    """``(view_key, snapshot)`` pairs for every ServingTelemetry view
    registered in one metrics document - THE shared filter for every
    fleet consumer that walks shard serving state (router dispatch
    weights, fleet canary snapshots, ``tx fleet status``,
    ``tx autotune report`` over an aggregation dir), so the view-key
    scheme has one reader, not four copies."""
    for key, snap in (metrics_doc.get("views") or {}).items():
        if key.partition("/")[0] == "serving" and isinstance(snap, dict):
            yield key, snap


# ---------------------------------------------------------------------------
# shipping (per-process -> aggregation dir)
# ---------------------------------------------------------------------------
def ship_now(agg_dir: str, instance: Optional[str] = None,
             extra: Optional[dict] = None) -> str:
    """Export this process's whole observability plane into its
    per-process shard file: the MetricsRegistry document (stamped with
    the process ``instance``) plus the tracer's retained span ring.
    Tempfile + atomic ``os.replace`` - a reader sees the previous
    complete shard or the new complete shard, nothing between; the
    resulting mtime IS the heartbeat."""
    os.makedirs(agg_dir, exist_ok=True)
    # sanitized: the instance becomes a label value AND this filename -
    # a path separator in a caller-supplied replica name must not
    # escape the aggregation dir
    inst = _sanitize_instance(instance) if instance \
        else process_instance()
    doc = {
        "instance": inst,
        "pid": os.getpid(),
        "shipped_at": time.time(),  # epoch stamp (correlation only;
        # liveness is judged from the file's mtime, not this field)
        "metrics": dict(metrics_registry().to_json(), instance=inst),
        "spans": tracer().spans(),
    }
    if extra:
        doc.update(extra)
    path = os.path.join(agg_dir, inst + SHARD_SUFFIX)
    # dumps-then-write, compact separators: streaming json.dump to the
    # file handle measured ~3.5x slower per ship on a full 8192-span
    # ring (121ms -> ~35ms) - the shipper beats once a second forever,
    # so this IS a hot path
    payload = json.dumps(doc, separators=(",", ":"), default=str)
    fd, tmp = tempfile.mkstemp(dir=agg_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass  # the replace may already have consumed it
        raise
    return path


class ObsShipper:
    """Background thread shipping this process's plane every
    ``interval_s`` (and once more at ``stop()``, so the final state of
    a cleanly-exiting process is never lost).  A failed ship is counted
    and retried next beat, never raised into the process being
    observed.  Context manager; every wait is bounded (the parallel/
    discipline - a shipper must never be the thing that wedges exit)."""

    def __init__(self, agg_dir: str, interval_s: float = 1.0,
                 instance: Optional[str] = None,
                 extra_fn=None) -> None:
        self.agg_dir = agg_dir
        self.interval_s = max(0.01, float(interval_s))
        self.instance = instance or process_instance()
        #: zero-arg callable whose dict is merged into every shipped
        #: shard (ISSUE 14: a fleet replica stamps its per-replica
        #: ``fleet`` info - generation, rows scored, in-flight - so the
        #: aggregation dir carries replica state, not just metrics)
        self.extra_fn = extra_fn
        self.ships_ok = 0
        self.ships_failed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _ship_once(self) -> None:
        extra = None
        if self.extra_fn is not None:
            try:
                extra = dict(self.extra_fn())
            except Exception as e:  # noqa: BLE001 - shipping stays up
                log.warning("obs shipper: extra_fn failed: %s", e)
        try:
            ship_now(self.agg_dir, instance=self.instance, extra=extra)
            self.ships_ok += 1
        except OSError as e:
            self.ships_failed += 1
            log.warning("obs shipper: export to %s failed: %s",
                        self.agg_dir, e)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._ship_once()

    def start(self) -> "ObsShipper":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tx-obs-shipper")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
        self._ship_once()  # final state, post-thread

    def __enter__(self) -> "ObsShipper":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# aggregation (aggregation dir -> one scrape / one trace tree)
# ---------------------------------------------------------------------------
class FleetAggregator:
    """Merge the live per-process shards of an aggregation dir.

    *Live* means the shard file's mtime-heartbeat is fresher than
    ``stale_after_s`` (``TX_OBS_FLEET_STALE_S``, default 60): a
    SIGKILLed process stops beating and ages out of the scrape instead
    of serving its last numbers forever.  Torn/partial shards are
    skipped and counted (:func:`read_json_torn_safe` is the only way
    this class touches shard bytes - style-gated)."""

    def __init__(self, agg_dir: str,
                 stale_after_s: Optional[float] = None) -> None:
        self.agg_dir = agg_dir
        self.stale_after_s = (
            _env_float("TX_OBS_FLEET_STALE_S", DEFAULT_STALE_S)
            if stale_after_s is None else float(stale_after_s)
        )
        self.last_report: dict = {}

    # -- collection ---------------------------------------------------------
    def _staleness_s(self, path: str) -> Optional[float]:
        """Seconds since the shard's last heartbeat (mtime), clamped at
        0 for clock skew; None when the file vanished.  Epoch-clock
        subtraction is allowlisted in tests/test_style.py: mtimes only
        exist on the epoch timeline (the supervisor.staleness
        precedent)."""
        try:
            return max(0.0, time.time() - os.path.getmtime(path))
        except OSError:
            return None

    def shards(self) -> list[dict]:
        """The live, readable shard documents (sorted by instance).
        Side effect: ``last_report`` records how many shards were live,
        stale, and torn - silent exclusion is how a half-dead fleet
        reads as healthy."""
        live: list[dict] = []
        stale = torn = 0
        try:
            names = sorted(os.listdir(self.agg_dir))
        except OSError:
            names = []
        for name in names:
            if not name.endswith(SHARD_SUFFIX):
                continue
            path = os.path.join(self.agg_dir, name)
            s = self._staleness_s(path)
            if s is None or s > self.stale_after_s:
                stale += 1
                continue
            doc = read_json_torn_safe(path)
            if doc is None:
                torn += 1
                continue
            doc.setdefault("instance", name[: -len(SHARD_SUFFIX)])
            live.append(doc)
        live.sort(key=lambda d: str(d.get("instance")))
        self.last_report = {
            "shards_live": len(live),
            "shards_stale": stale,
            "shards_torn": torn,
            "instances": [str(d.get("instance")) for d in live],
        }
        return live

    # -- metrics ------------------------------------------------------------
    @staticmethod
    def _flat_series(metrics_doc: dict) -> dict[str, tuple]:
        """Flatten one shard's metrics document to the same sample
        names its exposition carries (``tx_<name>`` /
        ``tx_<kind>_<path>``), each as a ``(sum, max)`` pair - a
        process can hold SEVERAL views of one kind (a deploy's stable +
        canary ServingTelemetry both flatten to
        ``tx_serving_rows_scored``), and last-one-wins would silently
        drop all but one from the fleet rollup."""
        out: dict[str, tuple] = {}

        def _acc(name: str, v: float) -> None:
            prev = out.get(name)
            out[name] = (v, v) if prev is None else (
                prev[0] + v, v if v > prev[1] else prev[1])

        for name, s in metrics_doc.get("series", {}).items():
            pname = sanitize_metric_name(name)
            if s.get("type") == "histogram":
                _acc(pname + "_sum", float(s.get("sum", 0.0)))
                _acc(pname + "_count", float(s.get("count", 0)))
            else:
                _acc(pname, float(s.get("value", 0.0)))
        for key, snap in metrics_doc.get("views", {}).items():
            kind = key.partition("/")[0]
            for path, value in _numeric_leaves(snap):
                _acc(sanitize_metric_name(
                    kind + "_" + "_".join(path)), float(value))
        return out

    def fleet_rollup(self,
                     shards: Optional[Iterable[dict]] = None) -> dict:
        """Fleet-level aggregates over the live shards: per flattened
        sample name, the SUM and the MAX across processes (sum answers
        "how many rows did the fleet score", max answers "what is the
        worst replica's p99"), plus which instances contributed."""
        if shards is None:
            shards = self.shards()
        sums: dict[str, float] = {}
        maxes: dict[str, float] = {}
        instances = []
        for doc in shards:
            instances.append(str(doc.get("instance")))
            for name, (s, m) in self._flat_series(
                    doc.get("metrics", {})).items():
                sums[name] = sums.get(name, 0.0) + s
                if name not in maxes or m > maxes[name]:
                    maxes[name] = m
        return {"instances": instances, "sum": sums, "max": maxes}

    def prometheus_text(self) -> str:
        """One scrape for the whole fleet: every live shard rendered by
        THE shared renderer under its own ``instance`` label (comment
        lines deduplicated - one ``# TYPE`` per metric), then the
        fleet rollup as ``instance="fleet"`` samples with an ``agg``
        label (``sum``/``max``)."""
        shards = self.shards()
        lines: list[str] = []
        seen_comments: set[str] = set()
        for doc in shards:
            text = prometheus_text_from_json(
                doc.get("metrics", {}), instance=str(doc.get("instance"))
            )
            for line in text.splitlines():
                if line.startswith("#"):
                    if line in seen_comments:
                        continue
                    seen_comments.add(line)
                lines.append(line)
        rollup = self.fleet_rollup(shards)
        for agg in ("sum", "max"):
            for name in sorted(rollup[agg]):
                lines.append(
                    f'{name}{{instance="fleet",agg="{agg}"}} '
                    f"{_fmt_value(rollup[agg][name])}")
        return "\n".join(lines) + "\n"

    def merged_metrics_docs(self) -> list[dict]:
        """The live shards' registry documents (each stamped with its
        instance) - the multi-process evaluation surface the SLO engine
        consumes (slo.py resolves sums/maxes across them)."""
        return [
            dict(d.get("metrics", {}), instance=str(d.get("instance")))
            for d in self.shards()
        ]

    # -- spans --------------------------------------------------------------
    def merged_spans(self) -> list[dict]:
        """Every live shard's span records concatenated, each stamped
        with the pid it came from; collision-safe span ids (trace.py)
        mean records from different processes link into one tree when
        the child adopted the parent's exported context."""
        out: list[dict] = []
        for doc in self.shards():
            pid = doc.get("pid")
            for rec in doc.get("spans", ()):
                if isinstance(rec, dict):
                    out.append(dict(rec, pid=pid))
        return out

    def span_trees(self) -> list[dict]:
        """The fleet's merged trace forest (``tx obs trace`` over an
        aggregation dir renders exactly this)."""
        return build_trees(self.merged_spans())

    def to_json(self) -> dict:
        """One document for the whole fleet: shard membership report,
        rollup, and per-instance registry documents."""
        shards = self.shards()
        return {
            "report": dict(self.last_report),
            "fleet": self.fleet_rollup(shards),
            "processes": {
                str(d.get("instance")): d.get("metrics", {})
                for d in shards
            },
        }


def health_views(metrics_doc: dict):
    """``(view_key, snapshot)`` pairs for every ``fleet_health`` view in
    one metrics document (the ISSUE-17 per-replica failure-detector
    plane the router registers) - the shared filter for consumers that
    surface ejection/readmission state from a router shard
    (``tx fleet status`` over an aggregation dir, dashboards scraping
    ``tx_fleet_health_*``).  Placed after :class:`FleetAggregator` so
    the style-gate's epoch-subtraction allowlist line stays put."""
    for key, snap in (metrics_doc.get("views") or {}).items():
        if key.partition("/")[0] == "fleet_health" \
                and isinstance(snap, dict):
            yield key, snap


def autoscaler_views(metrics_doc: dict):
    """``(view_key, snapshot)`` pairs for every ``autoscaler`` view in
    one metrics document (the ISSUE-19 elastic-capacity control loop
    registers exactly one per process) - the shared filter for
    consumers surfacing scale-decision state from a control-plane
    shard (``tx fleet status`` over an aggregation dir, dashboards
    scraping ``tx_autoscaler_*``)."""
    for key, snap in (metrics_doc.get("views") or {}).items():
        if key.partition("/")[0] == "autoscaler" \
                and isinstance(snap, dict):
            yield key, snap
