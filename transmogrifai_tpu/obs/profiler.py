"""Always-on low-overhead span profiler: EWMA + histogram per span
name, with a tail sampler that keeps FULL span trees only for slow
outliers.

The deep-profiling role the Spark UI / JAX xplane dumps play is
offline and heavyweight; this profiler is the opposite end of the
tradeoff - cheap enough to leave enabled in the serving hot path
forever (proved by ``bench.py --obs``), detailed enough that when a
batch lands past the p99 it retains the batch's WHOLE span tree as an
exemplar, so the slow request links directly to its stage-level
breakdown instead of to an aggregate.

Per span name it keeps: count, EWMA of wall-ms (recency-weighted
"current speed"), a fixed-bucket histogram (bounded memory, quantiles
interpolated from buckets - the same :class:`~transmogrifai_tpu.obs.
metrics.Histogram` the metrics plane exposes), and min/max.  The tail
sampler arms only after ``min_samples`` observations (cold-start
compiles must not hoard the exemplar slots) and refreshes its p99
threshold every ``threshold_refresh`` observations so the quantile walk
stays OFF the per-span path.

Stdlib only; importable before jax/numpy init like the rest of obs/.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from .metrics import DEFAULT_BUCKETS_MS, Histogram, percentiles  # noqa: F401

__all__ = ["SpanProfiler"]


class _NameStats:
    __slots__ = ("count", "ewma_ms", "max_ms", "hist",
                 "threshold_ms", "roots_seen")

    def __init__(self) -> None:
        self.count = 0
        self.ewma_ms: Optional[float] = None
        self.max_ms = 0.0
        self.hist = Histogram("span_wall_ms", buckets=DEFAULT_BUCKETS_MS)
        self.threshold_ms: Optional[float] = None
        self.roots_seen = 0


class SpanProfiler:
    """Per-span-name accumulation + p99 exemplar retention.

    ``observe`` is the tracer's completion hook: ``tree`` is the full
    nested span tree when the finished span was a trace ROOT (only
    roots are exemplar candidates - a child's slowness is visible
    inside its root's tree), else None.
    """

    def __init__(self, ewma_alpha: float = 0.05,
                 exemplar_capacity: int = 16,
                 min_samples: int = 64,
                 tail_quantile: float = 99.0,
                 threshold_refresh: int = 64) -> None:
        self.ewma_alpha = float(ewma_alpha)
        self.min_samples = int(min_samples)
        self.tail_quantile = float(tail_quantile)
        self.threshold_refresh = max(1, int(threshold_refresh))
        self._lock = threading.Lock()
        self._stats: dict[str, _NameStats] = {}
        self._exemplars: deque = deque(maxlen=int(exemplar_capacity))
        self.exemplars_retained = 0
        self.exemplars_evicted = 0
        self.roots_considered = 0

    # -- hot path ------------------------------------------------------------
    def observe(self, name: str, wall_ms: float,
                tree: Optional[dict] = None) -> None:
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _NameStats()
            st.count += 1
            st.ewma_ms = wall_ms if st.ewma_ms is None else (
                self.ewma_alpha * wall_ms
                + (1.0 - self.ewma_alpha) * st.ewma_ms
            )
            if wall_ms > st.max_ms:
                st.max_ms = wall_ms
            retain = False
            if tree is not None:
                st.roots_seen += 1
                self.roots_considered += 1
                if st.count >= self.min_samples:
                    if (st.threshold_ms is None
                            or st.count % self.threshold_refresh == 0):
                        # amortized: the bucket walk runs once per
                        # refresh window, never per span.  The UPPER-
                        # edge quantile: a span must clear its p99
                        # bucket outright to count as an outlier
                        st.threshold_ms = st.hist.quantile_upper(
                            self.tail_quantile
                        )
                    t = st.threshold_ms
                    retain = t == t and wall_ms > t  # NaN-safe
            if retain:
                if len(self._exemplars) == self._exemplars.maxlen:
                    self.exemplars_evicted += 1
                self._exemplars.append({
                    "name": name,
                    "trace": tree.get("trace"),
                    "wall_ms": wall_ms,
                    "threshold_ms": round(st.threshold_ms, 6),
                    "tree": tree,
                })
                self.exemplars_retained += 1
        # outside the profiler lock: the histogram has its own
        st.hist.observe(wall_ms)

    # -- reporting -----------------------------------------------------------
    def exemplars(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._exemplars]

    def observations(self) -> list[dict]:
        """Per-span-name observation rows (ISSUE 13): the profiler's
        accumulated timings as flat records a cost model can train
        from (``CostModel.ingest_profiler``) without reaching into any
        internal state - name, count, EWMA, histogram quantiles, max.
        One row per name, sorted by name for determinism."""
        snap = self.snapshot()
        return [
            dict(st, name=name) for name, st in snap["spans"].items()
        ]

    def snapshot(self) -> dict:
        with self._lock:
            names = dict(self._stats)
            tail = {
                "roots_considered": self.roots_considered,
                "exemplars_retained": self.exemplars_retained,
                "exemplars_evicted": self.exemplars_evicted,
                "exemplars_held": len(self._exemplars),
                "min_samples": self.min_samples,
                "tail_quantile": self.tail_quantile,
            }
        spans = {}
        for name, st in sorted(names.items()):
            h = st.hist
            spans[name] = {
                "count": st.count,
                "ewma_ms": None if st.ewma_ms is None
                else round(st.ewma_ms, 6),
                "max_ms": round(st.max_ms, 6),
                "p50_ms": _finite(h.quantile(50.0)),
                "p95_ms": _finite(h.quantile(95.0)),
                "p99_ms": _finite(h.quantile(99.0)),
                "tail_threshold_ms": None if st.threshold_ms is None
                or st.threshold_ms != st.threshold_ms
                else round(st.threshold_ms, 6),
            }
        return {"spans": spans, "tail": tail}


def _finite(v: float):
    return None if v != v else round(v, 6)
