"""transmogrifai_tpu.obs: the unified observability plane.

One package, three coupled pieces (ISSUE 7):

* :mod:`~transmogrifai_tpu.obs.trace` - run-scoped trace spans
  (contextvar-propagated, ``perf_counter_ns``-timed, bounded ring
  buffer, JSONL export): one trace id follows
  ingest -> fit -> save -> publish -> swap -> serve.
* :mod:`~transmogrifai_tpu.obs.metrics` - ONE metrics registry
  (counters / gauges / fixed-bucket histograms, the shared percentile
  implementation) into which the four legacy telemetry classes register
  their snapshots as views; exported as JSON and Prometheus text via
  ``tx obs`` and the runner's ``metrics_path`` knob.
* :mod:`~transmogrifai_tpu.obs.profiler` - always-on per-span EWMA +
  histogram with a p99 tail sampler retaining full span trees for slow
  outliers.
* :mod:`~transmogrifai_tpu.obs.fleet` (ISSUE 11) - cross-process
  trace-context propagation (``TX_OBS_TRACE_CONTEXT``), per-process
  metric/span shipping into an aggregation dir, and the
  :class:`FleetAggregator` merging live shards into one scrape + one
  trace forest.
* :mod:`~transmogrifai_tpu.obs.slo` (ISSUE 11) - declarative SLOs
  with multi-window burn-rate alerting over the (fleet-)aggregated
  plane; consumed by ``tx obs slo``, the runner ``slo_path`` knob, and
  ``RollbackPolicy.slo_engine``.

The whole package is stdlib-only and importable before jax/numpy init
(gated by tests/test_style.py), exactly like ``utils/tracing.py`` - the
measurement plane must not depend on the stack it measures.
"""
from __future__ import annotations

import os
from typing import Optional

from .fleet import (
    FleetAggregator,
    ObsShipper,
    child_env,
    read_json_torn_safe,
    read_jsonl_tolerant,
    ship_now,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    metrics_registry,
    percentiles,
    process_instance,
    prometheus_text_from_json,
    reset_metrics_registry,
    sanitize_metric_name,
    set_process_instance,
    write_json_artifact,
)
from .profiler import SpanProfiler
from .slo import (
    SLOEngine,
    SLObjective,
    default_objectives,
    load_slo_config,
    resolve_metric,
)
from .trace import (
    TRACE_CONTEXT_ENV,
    Span,
    Tracer,
    build_trees,
    current_context,
    parse_context,
    reset_tracer,
    set_enabled,
    span,
    tracer,
)

__all__ = [
    "Counter",
    "FleetAggregator",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "ObsShipper",
    "SLOEngine",
    "SLObjective",
    "Span",
    "SpanProfiler",
    "TRACE_CONTEXT_ENV",
    "Tracer",
    "build_trees",
    "child_env",
    "current_context",
    "default_objectives",
    "export_obs",
    "load_slo_config",
    "metrics_registry",
    "parse_context",
    "percentiles",
    "process_instance",
    "prometheus_text_from_json",
    "read_json_torn_safe",
    "read_jsonl_tolerant",
    "reset_metrics_registry",
    "reset_tracer",
    "resolve_metric",
    "sanitize_metric_name",
    "set_enabled",
    "set_process_instance",
    "ship_now",
    "span",
    "tracer",
    "write_json_artifact",
]


def export_obs(path: str, extra: Optional[dict] = None) -> dict:
    """Export the whole observability plane into directory ``path``:
    ``metrics.json`` (the registry document - native series + every
    registered telemetry view), ``metrics.prom`` (the same document as
    Prometheus text exposition), and ``spans.jsonl`` (the tracer's
    retained spans).  The runner's ``metrics_path`` knob and callers
    who want a one-call dump share this.  Returns the JSON document."""
    os.makedirs(path, exist_ok=True)
    reg = metrics_registry()
    # stamped with the writing process's identity: the saved artifact
    # renders under the instance that produced it, not whoever reads it
    doc = dict(reg.to_json(), instance=process_instance())
    if extra:
        doc = dict(doc, **extra)
    write_json_artifact(os.path.join(path, "metrics.json"), doc)
    with open(os.path.join(path, "metrics.prom"), "w") as f:
        f.write(prometheus_text_from_json(doc))
    tracer().export_jsonl(os.path.join(path, "spans.jsonl"))
    return doc
