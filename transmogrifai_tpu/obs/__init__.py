"""transmogrifai_tpu.obs: the unified observability plane.

One package, three coupled pieces (ISSUE 7):

* :mod:`~transmogrifai_tpu.obs.trace` - run-scoped trace spans
  (contextvar-propagated, ``perf_counter_ns``-timed, bounded ring
  buffer, JSONL export): one trace id follows
  ingest -> fit -> save -> publish -> swap -> serve.
* :mod:`~transmogrifai_tpu.obs.metrics` - ONE metrics registry
  (counters / gauges / fixed-bucket histograms, the shared percentile
  implementation) into which the four legacy telemetry classes register
  their snapshots as views; exported as JSON and Prometheus text via
  ``tx obs`` and the runner's ``metrics_path`` knob.
* :mod:`~transmogrifai_tpu.obs.profiler` - always-on per-span EWMA +
  histogram with a p99 tail sampler retaining full span trees for slow
  outliers.

The whole package is stdlib-only and importable before jax/numpy init
(gated by tests/test_style.py), exactly like ``utils/tracing.py`` - the
measurement plane must not depend on the stack it measures.
"""
from __future__ import annotations

import os
from typing import Optional

from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    metrics_registry,
    percentiles,
    prometheus_text_from_json,
    reset_metrics_registry,
    sanitize_metric_name,
    write_json_artifact,
)
from .profiler import SpanProfiler
from .trace import (
    Span,
    Tracer,
    build_trees,
    reset_tracer,
    set_enabled,
    span,
    tracer,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Span",
    "SpanProfiler",
    "Tracer",
    "build_trees",
    "export_obs",
    "metrics_registry",
    "percentiles",
    "prometheus_text_from_json",
    "reset_metrics_registry",
    "reset_tracer",
    "sanitize_metric_name",
    "set_enabled",
    "span",
    "tracer",
    "write_json_artifact",
]


def export_obs(path: str, extra: Optional[dict] = None) -> dict:
    """Export the whole observability plane into directory ``path``:
    ``metrics.json`` (the registry document - native series + every
    registered telemetry view), ``metrics.prom`` (the same document as
    Prometheus text exposition), and ``spans.jsonl`` (the tracer's
    retained spans).  The runner's ``metrics_path`` knob and callers
    who want a one-call dump share this.  Returns the JSON document."""
    os.makedirs(path, exist_ok=True)
    reg = metrics_registry()
    doc = reg.to_json()
    if extra:
        doc = dict(doc, **extra)
    write_json_artifact(os.path.join(path, "metrics.json"), doc)
    with open(os.path.join(path, "metrics.prom"), "w") as f:
        f.write(prometheus_text_from_json(doc))
    tracer().export_jsonl(os.path.join(path, "spans.jsonl"))
    return doc
