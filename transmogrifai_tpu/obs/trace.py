"""Run-scoped trace spans: contextvar propagation, ns timing, ring
buffer, JSONL export.

The observability tentpole's causal spine: one trace id follows a run
across every subsystem boundary - reader ingest batches, per-stage
fit/transform, model save, registry publish, deployment swap/canary
events, and serving batches (fused and interpreted) all record spans
parented through :data:`contextvars`, so a p99 serving batch, a drift
warning, and the registry generation that served it line up into one
tree instead of four disconnected logs.

Design constraints, in order:

* **hot-path cheap**: a span is two ``time.perf_counter_ns()`` calls, a
  contextvar set/reset, one small dict, and one deque append - no
  string formatting, no I/O, no uuid on the child path (trace ids are
  minted only at roots).  Cheap enough to leave ON in the serving hot
  path forever; ``bench.py --obs`` proves the claim (OBS_BENCH.json).
* **bounded**: completed spans land in a ring buffer
  (``collections.deque(maxlen=...)``); evictions are counted
  (``spans_evicted``), never errors - tracing memory must not grow with
  uptime any more than telemetry reservoirs do.
* **pre-jax importable**: stdlib only, like ``utils/tracing.py`` - the
  trace plane cannot depend on the accelerator stack it measures.

Spans feed the always-on :class:`~transmogrifai_tpu.obs.profiler.
SpanProfiler` at completion (EWMA + histogram per span name, p99 tail
exemplars), and export as JSONL (one span per line) for offline tree
reconstruction (``tx obs trace``).
"""
from __future__ import annotations

import contextvars
import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Optional

from .profiler import SpanProfiler

log = logging.getLogger("transmogrifai_tpu.obs")

__all__ = [
    "Span",
    "TRACE_CONTEXT_ENV",
    "Tracer",
    "current_context",
    "parse_context",
    "reset_tracer",
    "set_enabled",
    "span",
    "tracer",
]

#: the cross-process trace-context seam (ISSUE 11): a parent process
#: exports ``<trace_id>:<span_id>`` of its ambient span into this env
#: var before spawning a child, and the child's Tracer ADOPTS it at
#: construction - every root span the child mints then joins the
#: parent's trace (same trace id, parented to the exporting span), so
#: one trace id follows a run through supervisor re-dispatch, mesh-peer
#: bootstrap, and deploy-drill children.
TRACE_CONTEXT_ENV = "TX_OBS_TRACE_CONTEXT"


def parse_context(value: Optional[str]) -> tuple[Optional[str], Optional[int]]:
    """Parse a ``<trace_id>:<span_id>`` context string (the
    :data:`TRACE_CONTEXT_ENV` format).  Malformed input yields
    ``(None, None)`` - a garbled env var must degrade to a fresh local
    trace, never crash a child at import time."""
    if not value:
        return None, None
    trace_id, sep, span_part = value.strip().rpartition(":")
    if not sep or not trace_id:
        return None, None
    try:
        return trace_id, int(span_part)
    except ValueError:
        return None, None

#: the ambient span (contextvars so nested spans parent correctly per
#: thread/task; a thread started without a copied context roots a new
#: trace - scheduler worker threads are independent traces by design)
_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "tx_obs_current_span", default=None
)

#: ring-buffer capacity (completed spans retained for export)
DEFAULT_CAPACITY = 8192

#: max children a LIVE span accumulates for the profiler's exemplar
#: tree: the ring bounds the flat records, but a long-lived root (a
#: run.serve over millions of batches) would otherwise grow its nested
#: tree without bound.  Past the cap, children are counted
#: (``children_dropped`` on the node, ``tree_children_dropped`` on the
#: tracer) instead of retained.
MAX_TREE_CHILDREN = 256


class Span:
    """One timed operation; used as a context manager.  ``attrs`` are
    JSON-safe key/values (bucket sizes, row counts, fused reasons);
    ``set_attr`` adds outcomes discovered mid-span."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "attrs", "t_epoch", "_start_ns", "_children",
                 "_children_dropped", "_token", "_root")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: int, parent_id: Optional[int],
                 attrs: dict, root: bool = False) -> None:
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.t_epoch = 0.0
        self._start_ns = 0
        self._children: list[dict] = []
        self._children_dropped = 0
        self._token = None
        # local-rootness is a flag, not ``parent_id is None``: a root
        # that ADOPTED a cross-process context carries the remote parent
        # span id, yet is still this process's tree root
        self._root = root

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        self.t_epoch = time.time()  # wall stamp for cross-process
        # correlation only - durations come from perf_counter_ns below
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        end_ns = time.perf_counter_ns()
        _current.reset(self._token)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self.tracer._finish(self, end_ns - self._start_ns)
        # never swallow the exception: spans observe, they do not handle


class _NullSpan:
    """The disabled-tracer stand-in: every operation is a no-op so call
    sites never branch on enablement themselves."""

    __slots__ = ()
    trace_id = None
    span_id = None
    attrs: dict = {}

    def set_attr(self, key: str, value: Any) -> None:
        return None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        return None


_NULL_SPAN = _NullSpan()


class Tracer:
    """Span factory + bounded store of completed spans.

    ``enabled=False`` (or env ``TX_OBS_OFF=1``) turns every ``span()``
    into a shared no-op - the observability-off arm of the overhead
    bench.  Completed spans are flat dicts in a ring buffer; parents
    additionally accumulate up to :data:`MAX_TREE_CHILDREN` children
    (overflow counted, not retained) so the profiler can retain a full
    tree for p99 outliers without the ring needing to."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: Optional[bool] = None,
                 profiler: Optional[SpanProfiler] = None) -> None:
        if enabled is None:
            enabled = os.environ.get("TX_OBS_OFF", "").strip().lower() \
                not in ("1", "true")
        self.enabled = bool(enabled)
        self.profiler = profiler if profiler is not None else SpanProfiler()
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=int(capacity))
        # span ids count up from a random 63-bit base so they stay
        # collision-safe when span shards from MANY processes merge into
        # one tree (fleet.py): a per-process count-from-1 would collide
        # on the very first merged pair.  Still one C-level next() on
        # the hot path - no per-span entropy or formatting.
        self._ids = itertools.count(
            int.from_bytes(os.urandom(8), "big") >> 1 or 1
        )
        # trace ids are prefix+counter, NOT per-root entropy: one
        # os.urandom at construction (it costs ~65us per call on older
        # kernels - measured, OBS_BENCH.json span_record) plus a C-level
        # counter keeps root creation as cheap as child creation.  The
        # prefix is pid + an 8-byte start nonce: pid alone is recycled
        # by the kernel, so two sequential processes could mint the same
        # prefix and collide id-for-id (ISSUE 11).
        self._trace_prefix = f"{os.getpid():x}-{os.urandom(8).hex()}-"
        self._trace_ids = itertools.count(1)
        # cross-process context adoption (the TRACE_CONTEXT_ENV seam):
        # when a parent process exported its ambient span, every root
        # this tracer mints joins that trace instead of starting one
        self._adopted_trace, self._adopted_parent = parse_context(
            os.environ.get(TRACE_CONTEXT_ENV)
        )
        self.contexts_adopted = 1 if self._adopted_trace else 0
        self.spans_recorded = 0
        self.spans_evicted = 0
        self.traces_started = 0
        self.tree_children_dropped = 0

    # -- recording ----------------------------------------------------------
    def span(self, name: str, **attrs: Any):
        """Open a span parented to the ambient one (a new root - and a
        new trace id - when there is none)."""
        if not self.enabled:
            return _NULL_SPAN
        parent = _current.get()
        if parent is None or parent.tracer is not self:
            if self._adopted_trace is not None:
                # adopted cross-process context: this root joins the
                # parent process's trace, parented to the exporting span
                trace_id = self._adopted_trace
                parent_id = self._adopted_parent
            else:
                trace_id = self._trace_prefix + format(
                    next(self._trace_ids), "x")
                parent_id = None
            return Span(self, name, trace_id, next(self._ids),
                        parent_id, attrs, root=True)
        return Span(self, name, parent.trace_id, next(self._ids),
                    parent.span_id, attrs)

    def event(self, name: str, **attrs: Any) -> None:
        """A zero-duration marker span (registry lifecycle events,
        breaker transitions): rides the ambient trace like any child."""
        if not self.enabled:
            return
        with self.span(name, **attrs):
            pass

    def _finish(self, s: Span, wall_ns: int) -> None:
        # no round() here: formatting belongs to export, not to a path
        # that runs once per serving batch
        record = {
            "trace": s.trace_id,
            "span": s.span_id,
            "parent": s.parent_id,
            "name": s.name,
            "t_epoch": s.t_epoch,
            "wall_ms": wall_ns / 1e6,
        }
        if s.attrs:
            record["attrs"] = s.attrs
        # the ring keeps FLAT records; the nested node exists only so a
        # root's full tree can reach the profiler's tail sampler
        node = dict(record, children=s._children) if s._children \
            else record
        if s._children_dropped:
            node = dict(node, children_dropped=s._children_dropped)
        parent = _current.get()  # __exit__ already reset the context
        tree = None
        dropped = 0
        if (s._root or parent is None
                or parent.tracer is not self):
            tree = node
        elif len(parent._children) < MAX_TREE_CHILDREN:
            parent._children.append(node)
        else:
            # bounded tree: keep the first MAX_TREE_CHILDREN exemplar
            # children, count the rest - a long-lived root must not
            # grow memory with every serve batch under it
            parent._children_dropped += 1
            dropped = 1
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.spans_evicted += 1
            self._spans.append(record)
            self.spans_recorded += 1
            self.tree_children_dropped += dropped
            if s._root:
                self.traces_started += 1
        self.profiler.observe(s.name, record["wall_ms"], tree)

    # -- cross-process context ----------------------------------------------
    def adopt_context(self, value: Optional[str]) -> bool:
        """Adopt a foreign trace context IN-PROCESS (the
        :data:`TRACE_CONTEXT_ENV` seam only runs at construction): the
        bulk job's resume path joins the PLANNING process's trace this
        way, so plan -> score -> commit -> resume is one trace across
        kills.  A no-op (False) on a malformed context, when this
        tracer already adopted one, or when a span is open - joining a
        foreign trace mid-span would orphan the open root."""
        trace_id, parent = parse_context(value)
        if trace_id is None or self._adopted_trace is not None:
            return False
        if self.current_context() is not None:
            return False
        self._adopted_trace, self._adopted_parent = trace_id, parent
        self.contexts_adopted += 1
        return True

    def current_context(self) -> Optional[str]:
        """The ambient span's ``<trace_id>:<span_id>`` context string
        (the :data:`TRACE_CONTEXT_ENV` payload), or - with no span open
        - the adopted context this tracer itself inherited, so a
        middle process relays its parent's trace to grandchildren even
        between spans.  None when there is nothing to propagate."""
        cur = _current.get()
        if cur is not None and cur.tracer is self:
            return f"{cur.trace_id}:{cur.span_id}"
        if self._adopted_trace is not None:
            return f"{self._adopted_trace}:{self._adopted_parent}"
        return None

    # -- reading ------------------------------------------------------------
    def spans(self, trace_id: Optional[str] = None) -> list[dict]:
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [r for r in out if r["trace"] == trace_id]
        return out

    def span_tree(self, trace_id: str) -> list[dict]:
        """Reconstruct the span tree(s) for one trace from the ring
        buffer: returns root nodes with nested ``children`` (a parent
        evicted from the ring orphans its subtree into a root - the
        bounded-buffer tradeoff, counted in ``spans_evicted``)."""
        return build_trees(self.spans(trace_id))

    def export_jsonl(self, path: str,
                     trace_id: Optional[str] = None) -> int:
        """Write retained spans one JSON object per line (the format
        ``tx obs trace`` reads back); returns the span count."""
        records = self.spans(trace_id)
        with open(path, "w") as f:
            for r in records:
                f.write(json.dumps(r, sort_keys=True, default=str))
                f.write("\n")
        return len(records)

    def snapshot(self) -> dict:
        """Self-metrics view (registered with the metrics registry so a
        scrape reports trace-plane health next to everything else)."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "capacity": self._spans.maxlen,
                "spans_retained": len(self._spans),
                "spans_recorded": self.spans_recorded,
                "spans_evicted": self.spans_evicted,
                "traces_started": self.traces_started,
                "contexts_adopted": self.contexts_adopted,
                "tree_children_dropped": self.tree_children_dropped,
            }


def build_trees(records: list[dict]) -> list[dict]:
    """Link flat span records (ring buffer or JSONL) into root trees,
    grouped by trace; shared by :meth:`Tracer.span_tree` and the
    ``tx obs trace`` CLI."""
    nodes = {r["span"]: dict(r, children=[]) for r in records}
    roots = []
    for r in records:
        node = nodes[r["span"]]
        parent = nodes.get(r.get("parent"))
        if parent is not None and parent["trace"] == r["trace"]:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots


# ---------------------------------------------------------------------------
# module-level plumbing (the mesh_telemetry()/data_telemetry() pattern)
# ---------------------------------------------------------------------------
_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def tracer() -> Tracer:
    """The process-wide tracer every subsystem records spans into."""
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            _tracer = Tracer()
            _register_views(_tracer)
        return _tracer


def reset_tracer(capacity: int = DEFAULT_CAPACITY,
                 enabled: Optional[bool] = None) -> Tracer:
    """Fresh tracer + profiler (test/bench isolation), re-registered
    with the CURRENT metrics registry."""
    global _tracer
    with _tracer_lock:
        _tracer = Tracer(capacity=capacity, enabled=enabled)
        _register_views(_tracer)
        return _tracer


def _register_views(t: Tracer) -> None:
    from .metrics import metrics_registry

    reg = metrics_registry()
    reg.register_view("obs_tracer", t)
    reg.register_view("profiler", t.profiler)


def set_enabled(enabled: bool) -> None:
    """Flip the default tracer on/off (the overhead bench's A/B switch;
    spans already open complete normally)."""
    tracer().enabled = bool(enabled)


def span(name: str, **attrs: Any):
    """Convenience: a span on the default tracer (the call-site idiom:
    ``with obs_trace.span("serve.batch", bucket=b): ...``)."""
    return tracer().span(name, **attrs)


def current_context() -> Optional[str]:
    """The default tracer's exportable trace context (see
    :meth:`Tracer.current_context`); the payload child-process spawners
    put in :data:`TRACE_CONTEXT_ENV` (``obs.fleet.child_env`` wraps
    the env-dict plumbing)."""
    return tracer().current_context()
