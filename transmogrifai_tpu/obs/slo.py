"""Declarative SLOs with multi-window burn-rate alerting over the
observability plane (ISSUE 11).

The registry's RollbackPolicy already reads raw signals (breaker opens,
drift JS, p99 ratios) straight off one canary's telemetry; an SLO is
the fleet-shaped version of the same idea: a *declared* objective
("error ratio <= 1%", "p99 <= 50ms") evaluated over the aggregated
metrics plane, with the SRE-workbook multi-window burn-rate rule - an
alert fires only when the error budget is burning too fast over BOTH a
long and a short window (the long window keeps one bad batch from
paging; the short window lets a recovered system clear quickly), and
clears when the short window recovers.

Three objective kinds, each selecting metrics by dotted path into the
registry JSON document (``serving.rows_failed`` walks the first
``serving`` view's snapshot; a ``tx_``-sanitized or exact native series
name matches ``series``):

* ``ratio``     - numerator/denominator counters; burn = windowed
  (d num / d den) / objective.  Error ratios, NaN-guard refusal rates.
* ``rate``      - numerator counter per second; burn = windowed
  (d num / dt) / objective.  Breaker opens, quarantine floods.
* ``threshold`` - point-in-time value; burn = value / objective
  (``op=">="`` inverts).  p99 latency, drift JS maxima.

Counters resolve as the SUM across processes and threshold values as
the MAX (the fleet question is "how much total traffic failed" and
"how slow is the worst replica"), so one config evaluates unchanged
over a single process's registry or a FleetAggregator's merged docs.

The engine registers itself as a metrics view (kind ``slo``), so alert
states ride every scrape; ``tx obs slo`` evaluates a config file
against saved/aggregated artifacts, the runner's ``slo_path`` knob
evaluates it live, and ``RollbackPolicy.slo_engine`` consumes firing
alerts as hard rollback signals.

Stdlib-only and importable before jax/numpy init, like the rest of
obs/.
"""
from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional, Sequence, Union

from .metrics import metrics_registry, sanitize_metric_name

__all__ = [
    "SLOEngine",
    "SLObjective",
    "default_objectives",
    "load_slo_config",
    "resolve_metric",
]

#: bounded alert-transition history (the MeshTelemetry event discipline)
_MAX_EVENTS = 256

#: per-objective sample cap: a RollbackPolicy-driven engine observes
#: once per canary check, and a 300s window at high check rates would
#: otherwise grow (and linearly re-scan) tens of thousands of samples
#: on the serving control loop.  Past the cap the MIDDLE decimates
#: (counter burns only read window-boundary samples; threshold maxima
#: lose at most interleaved points).
_MAX_SAMPLES = 4096


# ---------------------------------------------------------------------------
# metric selection
# ---------------------------------------------------------------------------
def _walk(snap: Any, parts: Sequence[str]) -> Optional[float]:
    node = snap
    for p in parts:
        if not isinstance(node, dict) or p not in node:
            return None
        node = node[p]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    if node != node or node in (float("inf"), float("-inf")):
        return None
    return float(node)


def resolve_metric(docs: Union[dict, Iterable[dict]],
                   path: str) -> tuple[float, Optional[float], int]:
    """Resolve a dotted metric path over one registry document or many
    (the fleet case): returns ``(sum, max, matches)`` across every
    match - native series by exact or ``tx_``-sanitized name, then
    ``<kind>.<path...>`` into every view of that kind.  Zero matches
    return ``(0.0, None, 0)``; SLO kinds pick sum (counters) or max
    (point-in-time values)."""
    if isinstance(docs, dict):
        docs = (docs,)
    total, mx, n = 0.0, None, 0
    want = sanitize_metric_name(path)
    parts = path.split(".")
    for doc in docs:
        for name, s in doc.get("series", {}).items():
            if name == path or sanitize_metric_name(name) == want:
                v = _walk(s, ("value",))
                if v is None:  # histogram: sum is its counter reading
                    v = _walk(s, ("sum",))
                if v is not None:
                    total += v
                    mx = v if mx is None or v > mx else mx
                    n += 1
        for key, snap in doc.get("views", {}).items():
            if key.partition("/")[0] != parts[0]:
                continue
            v = _walk(snap, parts[1:])
            if v is not None:
                total += v
                mx = v if mx is None or v > mx else mx
                n += 1
    return total, mx, n


def _paths_sum(docs, paths: Union[str, Sequence[str]],
               agg: str = "sum") -> tuple[Optional[float], int]:
    """Sum one-or-many dotted paths (``rows_scored + rows_failed``
    denominators want both); returns (value, matches)."""
    if isinstance(paths, str):
        paths = (paths,)
    total, n = 0.0, 0
    for p in paths:
        s, m, k = resolve_metric(docs, p)
        total += (m if agg == "max" else s) if k else 0.0
        n += k
    return (total if n else None), n


# ---------------------------------------------------------------------------
# objectives
# ---------------------------------------------------------------------------
@dataclass
class SLObjective:
    """One declarative objective (see module docstring for the kinds).
    ``windows_s`` is (long, short); the alert fires when the burn rate
    exceeds ``burn_threshold`` in BOTH windows and clears when the
    short window drops back under it."""

    name: str
    kind: str = "ratio"  # ratio | rate | threshold
    metric: Union[str, Sequence[str]] = ""        # threshold kinds
    numerator: Union[str, Sequence[str]] = ""     # ratio/rate kinds
    denominator: Union[str, Sequence[str]] = ""   # ratio kind
    objective: float = 0.01
    op: str = "<="  # threshold only: "<=" (cap) or ">=" (floor)
    windows_s: Sequence[float] = (300.0, 60.0)
    burn_threshold: float = 1.0

    def __post_init__(self) -> None:
        if self.kind not in ("ratio", "rate", "threshold"):
            raise ValueError(
                f"SLO {self.name!r}: unknown kind {self.kind!r}")
        if self.kind == "threshold" and not self.metric:
            raise ValueError(f"SLO {self.name!r}: threshold needs 'metric'")
        if self.kind in ("ratio", "rate") and not self.numerator:
            raise ValueError(f"SLO {self.name!r}: {self.kind} needs "
                             "'numerator'")
        if self.kind == "ratio" and not self.denominator:
            raise ValueError(f"SLO {self.name!r}: ratio needs "
                             "'denominator'")
        if self.objective <= 0:
            raise ValueError(f"SLO {self.name!r}: objective must be > 0")
        if len(self.windows_s) != 2 or self.windows_s[0] < self.windows_s[1]:
            raise ValueError(f"SLO {self.name!r}: windows_s must be "
                             "(long, short) with long >= short")

    def to_json(self) -> dict:
        return {
            "name": self.name, "kind": self.kind,
            "metric": self.metric, "numerator": self.numerator,
            "denominator": self.denominator, "objective": self.objective,
            "op": self.op, "windows_s": list(self.windows_s),
            "burn_threshold": self.burn_threshold,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "SLObjective":
        known = {"name", "kind", "metric", "numerator", "denominator",
                 "objective", "op", "windows_s", "burn_threshold"}
        extra = set(doc) - known
        if extra:
            # a typoed key would silently disable the knob it misspells
            raise ValueError(
                f"SLO config: unknown keys {sorted(extra)} in "
                f"{doc.get('name', '<unnamed>')!r}"
            )
        if "name" not in doc:
            raise ValueError("SLO config: every objective needs a 'name'")
        return cls(**doc)


def load_slo_config(path: str) -> list[SLObjective]:
    """Load a config file: ``{"slos": [{...}, ...]}`` (the runner's
    ``slo_path`` knob and ``tx obs slo --config`` format)."""
    with open(path) as f:
        doc = json.load(f)
    objs = doc.get("slos") if isinstance(doc, dict) else doc
    if not isinstance(objs, list) or not objs:
        raise ValueError(f"{path}: expected {{'slos': [...]}} with at "
                         "least one objective")
    out = [SLObjective.from_json(o) for o in objs]
    names = [o.name for o in out]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate SLO names")
    return out


def default_objectives() -> list[SLObjective]:
    """The four objectives the ISSUE names, over serving telemetry:
    p99 latency, error ratio, drift JS, and breaker opens - a usable
    starting config (``tx obs slo`` with no ``--config``)."""
    return [
        SLObjective(name="serving-p99-latency", kind="threshold",
                    metric="serving.latency_ms.p99", objective=250.0,
                    windows_s=(300.0, 60.0)),
        SLObjective(name="serving-error-ratio", kind="ratio",
                    numerator="serving.rows_failed",
                    denominator=("serving.rows_scored",
                                 "serving.rows_failed"),
                    objective=0.01, windows_s=(300.0, 60.0),
                    burn_threshold=2.0),
        SLObjective(name="serving-drift-js", kind="threshold",
                    metric="serving.data_contract.drift_js_max",
                    objective=0.25, windows_s=(300.0, 60.0)),
        SLObjective(name="serving-breaker-opens", kind="rate",
                    numerator="serving.breaker.opens",
                    objective=1.0 / 300.0, windows_s=(300.0, 60.0)),
    ]


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------
class _AlertState:
    __slots__ = ("samples", "state", "since_t", "fired", "cleared",
                 "last")

    def __init__(self) -> None:
        #: (t_perf, numerator, denominator, value) samples; denominator
        #: and value None where the kind does not use them
        self.samples: list[tuple] = []
        self.state = "ok"
        self.since_t: Optional[float] = None
        self.fired = 0
        self.cleared = 0
        self.last: dict = {}


class SLOEngine:
    """Evaluate declarative objectives over registry documents with
    multi-window burn-rate alerting (module docstring).  ``doc_fn``
    produces the evaluation surface per :meth:`observe` call - default
    the live process registry; a fleet passes
    ``FleetAggregator.merged_metrics_docs``.  Registered as a metrics
    view (kind ``slo``) so alert states ride every scrape."""

    def __init__(self, objectives: Optional[Sequence[SLObjective]] = None,
                 doc_fn: Optional[Callable[[], Any]] = None,
                 register: bool = True) -> None:
        self.objectives = list(objectives) if objectives is not None \
            else default_objectives()
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError("duplicate SLO names")
        self._doc_fn = doc_fn or (lambda: metrics_registry().to_json())
        self._lock = threading.Lock()
        self._alerts = {o.name: _AlertState() for o in self.objectives}
        self._events: list[dict] = []
        self._pc_start = time.perf_counter()
        self.evaluations = 0
        if register:
            metrics_registry().register_view("slo", self)

    # -- sampling -----------------------------------------------------------
    def _sample(self, obj: SLObjective, docs) -> tuple:
        t = time.perf_counter()
        if obj.kind == "threshold":
            v, _n = _paths_sum(docs, obj.metric, agg="max")
            return (t, None, None, v)
        num, _n = _paths_sum(docs, obj.numerator, agg="sum")
        den = None
        if obj.kind == "ratio":
            den, _d = _paths_sum(docs, obj.denominator, agg="sum")
        return (t, num, den, None)

    @staticmethod
    def _window(samples: list[tuple], now: float,
                window_s: float) -> list[tuple]:
        cut = now - window_s
        # the newest sample BEFORE the window is the delta baseline:
        # counters need a reference point even when the window holds a
        # single fresh sample
        base = None
        inside = []
        for s in samples:
            if s[0] < cut:
                base = s
            else:
                inside.append(s)
        return ([base] if base is not None else []) + inside

    def _burn(self, obj: SLObjective, samples: list[tuple],
              now: float, window_s: float) -> tuple[float, dict]:
        win = self._window(samples, now, window_s)
        if not win:
            return 0.0, {}
        first, last = win[0], win[-1]
        if obj.kind == "threshold":
            # strictly in-window values only: the prepended baseline is
            # a COUNTER delta reference, not a point-in-time reading - a
            # p99 spike sampled before both windows must age out, never
            # hold (or fire) an alert from stale data.  An empty window
            # burns nothing: no recent data must not page.
            cut = now - window_s
            vals = [s[3] for s in win if s[3] is not None and s[0] >= cut]
            if not vals:
                return 0.0, {}
            v = max(vals)
            if obj.op == ">=":
                burn = obj.objective / v if v > 0 else float("inf")
            else:
                burn = v / obj.objective
            return burn, {"value": v}
        if len(win) == 1:
            # baseline-less (one-shot CLI over a saved artifact, or an
            # engine's very first evaluation): the cumulative totals
            # ARE the window for ratios - a lifetime error ratio past
            # the objective reads as firing.  Rates need a timebase a
            # single sample cannot provide.
            if obj.kind == "rate":
                return 0.0, {"rate_per_s": None}
            dnum, dden = (last[1] or 0.0), (last[2] or 0.0)
        else:
            dnum = (last[1] or 0.0) - (first[1] or 0.0)
            if obj.kind == "rate":
                dt = max(last[0] - first[0], 1e-9)
                rate = max(dnum, 0.0) / dt
                return rate / obj.objective, {"rate_per_s": rate}
            dden = (last[2] or 0.0) - (first[2] or 0.0)
        if dden <= 0:
            return 0.0, {"ratio": None}  # no traffic burns no budget
        ratio = max(dnum, 0.0) / dden
        return ratio / obj.objective, {"ratio": ratio}

    # -- evaluation ---------------------------------------------------------
    def observe(self, docs: Any = None) -> dict:
        """Sample every objective from ``docs`` (default: ``doc_fn()``),
        update burn rates + alert states, return the report.  Called by
        the runner per export, by RollbackPolicy per canary check, by
        ``tx obs slo`` once over saved artifacts."""
        if docs is None:
            docs = self._doc_fn()
        now = time.perf_counter()
        report: dict = {"objectives": {}, "firing": []}
        with self._lock:
            self.evaluations += 1
            for obj in self.objectives:
                st = self._alerts[obj.name]
                st.samples.append(self._sample(obj, docs))
                # prune past the long window (plus one baseline sample),
                # and cap by COUNT so high-frequency observers stay O(1)
                # in memory regardless of window length
                cut = now - obj.windows_s[0]
                while len(st.samples) > 2 and st.samples[1][0] < cut:
                    del st.samples[0]
                if len(st.samples) > _MAX_SAMPLES:
                    del st.samples[1:-1:2]
                long_burn, long_info = self._burn(
                    obj, st.samples, now, obj.windows_s[0])
                short_burn, short_info = self._burn(
                    obj, st.samples, now, obj.windows_s[1])
                breach = (long_burn > obj.burn_threshold
                          and short_burn > obj.burn_threshold)
                recovered = short_burn <= obj.burn_threshold
                if st.state == "ok" and breach:
                    st.state, st.since_t = "firing", now
                    st.fired += 1
                    self._event(alert=obj.name, transition="fired",
                                burn_long=round(long_burn, 4),
                                burn_short=round(short_burn, 4))
                elif st.state == "firing" and recovered:
                    st.state, st.since_t = "ok", now
                    st.cleared += 1
                    self._event(alert=obj.name, transition="cleared",
                                burn_short=round(short_burn, 4))
                st.last = {
                    "kind": obj.kind,
                    "objective": obj.objective,
                    "burn_threshold": obj.burn_threshold,
                    "burn_long": round(long_burn, 6),
                    "burn_short": round(short_burn, 6),
                    "state": st.state,
                    "fired": st.fired,
                    "cleared": st.cleared,
                    **{k: (round(v, 6) if isinstance(v, float) else v)
                       for k, v in {**long_info, **short_info}.items()},
                }
                report["objectives"][obj.name] = dict(st.last)
                if st.state == "firing":
                    report["firing"].append(dict(
                        st.last, name=obj.name))
        return report

    def _event(self, **kw) -> None:
        kw["t"] = round(time.perf_counter() - self._pc_start, 3)
        self._events.append(kw)
        if len(self._events) > _MAX_EVENTS:
            del self._events[0]

    # -- reporting ----------------------------------------------------------
    def firing(self) -> list[dict]:
        """The currently-firing alerts (name + burn evidence) - the
        RollbackPolicy input: each entry becomes a hard rollback
        reason."""
        with self._lock:
            return [
                dict(self._alerts[o.name].last, name=o.name)
                for o in self.objectives
                if self._alerts[o.name].state == "firing"
            ]

    def report(self) -> dict:
        with self._lock:
            return {
                "evaluations": self.evaluations,
                "objectives": {
                    o.name: dict(self._alerts[o.name].last,
                                 state=self._alerts[o.name].state)
                    for o in self.objectives
                },
                "firing": [o.name for o in self.objectives
                           if self._alerts[o.name].state == "firing"],
                "events": [dict(e) for e in self._events],
            }

    def snapshot(self) -> dict:
        """Metrics-view shape: alert states as 0/1 gauges plus burn
        rates, so a scrape carries ``tx_slo_alert_firing_<name>``."""
        with self._lock:
            firing = {}
            burns = {}
            for o in self.objectives:
                st = self._alerts[o.name]
                key = sanitize_metric_name(o.name)[3:]  # strip tx_
                firing[key] = 1 if st.state == "firing" else 0
                if st.last:
                    burns[key] = {
                        "burn_long": st.last.get("burn_long"),
                        "burn_short": st.last.get("burn_short"),
                    }
            return {
                "evaluations": self.evaluations,
                "alerts_firing": sum(firing.values()),
                "alert_firing": firing,
                "burn": burns,
            }
