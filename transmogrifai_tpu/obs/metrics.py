"""Unified metrics plane: one registry, one percentile implementation,
one exposition pipeline (JSON + Prometheus text).

Before this module the system had FOUR disconnected telemetry silos -
``serving.ServingTelemetry``, ``parallel.resilience.MeshTelemetry``,
``schema.quarantine.DataTelemetry``, and ``utils.tracing.AppMetrics`` -
each with its own quantile math and its own JSON-export boilerplate, and
no way to scrape them all from one place.  This module is the connective
tissue (the OpSparkListener->metrics-sink analog the reference got from
the Spark metrics system for free):

* :func:`percentiles` - THE quantile implementation (moved here from
  ``utils/tracing.py``, which keeps a thin alias for compatibility);
  every telemetry class routes through it, pinned identical by test.
* :class:`Counter` / :class:`Gauge` / :class:`Histogram` - native
  series for code that wants first-class metrics (the obs self-metrics,
  the profiler, future cost-model observations).  Histograms use FIXED
  bucket boundaries so merging and exposition never resample.
* :class:`MetricsRegistry` - get-or-create series registry plus
  weakref-registered *snapshot views*: the four legacy telemetry
  classes register their live ``snapshot()`` callables and keep their
  existing shapes (views, not forks); exposition flattens every finite
  numeric leaf into a series, so one scrape reports the whole system.
* :func:`prometheus_text_from_json` - renders the registry's JSON
  document as Prometheus text exposition (RFC-style ``# HELP``/
  ``# TYPE`` + samples).  The registry's own ``prometheus_text()`` and
  the ``tx obs metrics`` CLI share this ONE renderer, so a saved JSON
  artifact round-trips to the exact exposition a live scrape gives.

Like ``utils/tracing.py`` this module must stay importable before
jax/numpy init (stdlib only) - the metrics plane cannot depend on the
accelerator stack it measures.
"""
from __future__ import annotations

import bisect
import json
import logging
import os
import re
import threading
import weakref
from typing import Any, Callable, Iterator, Optional

log = logging.getLogger("transmogrifai_tpu.obs")

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "metrics_registry",
    "percentiles",
    "process_instance",
    "prometheus_text_from_json",
    "reset_metrics_registry",
    "set_process_instance",
    "write_json_artifact",
]


# ---------------------------------------------------------------------------
# process identity (the exposition `instance` label)
# ---------------------------------------------------------------------------
#: pid + an 8-hex start nonce: stable for the life of the process,
#: distinct across processes even when the kernel recycles pids (the
#: trace-prefix reasoning in trace.py, applied to metric identity)
_instance_lock = threading.Lock()
_instance: Optional[str] = None

#: instance identities are interpolated into Prometheus label VALUES
#: and shard FILENAMES: a quote/backslash/newline would corrupt every
#: consumer's scrape, and a path separator would write outside the
#: aggregation dir - sanitize at the trust boundary, not per use
_INSTANCE_BAD = re.compile(r"[^A-Za-z0-9._:-]")


def _sanitize_instance(name: str) -> str:
    return _INSTANCE_BAD.sub("_", str(name))[:128] or "unnamed"


def process_instance() -> str:
    """This process's stable exposition identity (ISSUE 11 satellite):
    ``<pid>-<start-nonce>`` by default, overridable by
    :func:`set_process_instance` or the ``TX_OBS_INSTANCE`` env var
    (fleet replicas get operator-readable names that way); always
    label- and filename-safe."""
    global _instance
    with _instance_lock:
        if _instance is None:
            named = os.environ.get("TX_OBS_INSTANCE", "").strip()
            _instance = (
                _sanitize_instance(named) if named
                else f"{os.getpid()}-{os.urandom(4).hex()}"
            )
        return _instance


def set_process_instance(name: Optional[str]) -> None:
    """Override (or with ``None`` re-derive) the exposition identity -
    a serving replica names itself ``replica-3`` instead of a pid."""
    global _instance
    with _instance_lock:
        _instance = _sanitize_instance(name) if name else None


def percentiles(
    values, qs: tuple = (50.0, 95.0, 99.0)
) -> dict[str, float]:
    """Empirical percentiles keyed 'p50'/'p95'/'p99' (linear interpolation
    between order statistics).  THE shared quantile helper behind every
    telemetry snapshot in the system (serving, mesh, data, stage) -
    ``utils/tracing.percentiles`` aliases this function, and
    tests/test_obs.py pins the implementations identical."""
    out: dict[str, float] = {}
    vals = sorted(float(v) for v in values)
    for q in qs:
        key = f"p{q:g}"
        if not vals:
            out[key] = float("nan")
            continue
        pos = (len(vals) - 1) * (q / 100.0)
        lo = int(pos)
        hi = min(lo + 1, len(vals) - 1)
        out[key] = vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)
    return out


def write_json_artifact(path: str, doc: dict) -> None:
    """THE telemetry-artifact writer (indent=1, sorted keys, trailing
    newline): the four telemetry ``export()`` methods each had their own
    copy of this open/dump/newline block - one implementation means one
    artifact format."""
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True, default=str)
        f.write("\n")


# ---------------------------------------------------------------------------
# native series
# ---------------------------------------------------------------------------
class Counter:
    """Monotonic counter (thread-safe)."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (thread-safe); ``fn`` makes it a pull gauge
    evaluated at snapshot time."""

    __slots__ = ("name", "help", "_lock", "_value", "_fn")

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None) -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = fn

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception as e:  # noqa: BLE001 - a broken pull gauge
                # must not take the whole scrape down, but it must be
                # VISIBLE (the events_dropped discipline)
                log.warning("pull gauge %s failed: %s", self.name, e)
                return float("nan")
        with self._lock:
            return self._value


#: default histogram boundaries: log-spaced milliseconds from 10us to
#: 100s (wide enough for span walls from a fused batch to a full train)
DEFAULT_BUCKETS_MS = (
    0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0, 100.0, 300.0,
    1_000.0, 3_000.0, 10_000.0, 30_000.0, 100_000.0,
)


class Histogram:
    """Fixed-bucket histogram (thread-safe): count, sum, per-bucket
    counts, and interpolated quantiles FROM the buckets - no unbounded
    sample reservoir, so it is safe to leave on a serving hot path
    forever.  Bucket boundaries are upper-inclusive edges; values past
    the last edge land in the +Inf overflow bucket."""

    __slots__ = ("name", "help", "buckets", "_lock", "_counts",
                 "_count", "_sum", "_min", "_max")

    def __init__(self, name: str, help: str = "",
                 buckets: tuple = DEFAULT_BUCKETS_MS) -> None:
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket edge")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf overflow
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Interpolated quantile from the bucket counts (NaN when
        empty).  Within a bucket the mass is assumed uniform; the
        overflow bucket reports the observed max (the only bound we
        have past the last edge)."""
        with self._lock:
            total = self._count
            counts = list(self._counts)
            vmin, vmax = self._min, self._max
        if not total:
            return float("nan")
        target = (q / 100.0) * total
        seen = 0.0
        for i, c in enumerate(counts):
            if seen + c >= target and c:
                lo = self.buckets[i - 1] if i else min(vmin, self.buckets[0])
                hi = self.buckets[i] if i < len(self.buckets) else vmax
                frac = (target - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return vmax

    def quantile_upper(self, q: float) -> float:
        """CONSERVATIVE quantile: the upper edge of the bucket holding
        the q-th observation (observed max for the overflow bucket).
        The tail sampler's threshold - interpolation would under-read a
        distribution massed at a bucket's upper edge (every constant
        1.0ms span would look 'past the p99' of [0.3, 1.0]) and hoard
        exemplars of perfectly normal spans."""
        with self._lock:
            total = self._count
            counts = list(self._counts)
            vmax = self._max
        if not total:
            return float("nan")
        target = (q / 100.0) * total
        seen = 0.0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target and c:
                return (
                    self.buckets[i] if i < len(self.buckets) else vmax
                )
        return vmax

    def to_json(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            count, total = self._count, self._sum
            vmax = self._max
        out = {
            "count": count,
            "sum": round(total, 6),
            "max": None if count == 0 else round(vmax, 6),
            "buckets": {
                f"{edge:g}": c for edge, c in zip(self.buckets, counts)
            },
        }
        out["buckets"]["+Inf"] = counts[-1]
        return out


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """Prometheus-legal metric name ([a-zA-Z_:][a-zA-Z0-9_:]*), prefixed
    ``tx_`` so every series from this system namespaces together."""
    n = _NAME_BAD.sub("_", str(name))
    if not n.startswith("tx_"):
        n = "tx_" + n
    return n


def _numeric_leaves(doc: Any, path: tuple = ()) -> Iterator[tuple]:
    """Yield (path, value) for every finite int/float leaf reachable
    through nested dicts.  Bools, strings, lists, and None/NaN leaves
    are not series (lists hold event detail, not scrapeable scalars)."""
    if isinstance(doc, dict):
        for k, v in doc.items():
            yield from _numeric_leaves(v, path + (str(k),))
        return
    if isinstance(doc, bool) or not isinstance(doc, (int, float)):
        return
    if doc != doc or doc in (float("inf"), float("-inf")):
        return
    yield path, doc


class MetricsRegistry:
    """One process-wide registry for native series + snapshot views.

    *Native series* (``counter``/``gauge``/``histogram``) are
    get-or-create by name.  *Views* are weakly-referenced telemetry
    objects whose ``snapshot()`` is flattened at scrape time - the
    legacy accumulators keep owning their state and their snapshot
    shapes; the registry only READS them, so registration can never
    change behavior or pin an endpoint's telemetry alive."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._series: dict[str, Any] = {}
        self._views: list[tuple[str, int, Any]] = []  # (kind, idx, weakref)
        self._view_counts: dict[str, int] = {}

    # -- native series ------------------------------------------------------
    def _get_or_create(self, name: str, cls, **kw) -> Any:
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = cls(name, **kw)
                self._series[name] = s
            elif not isinstance(s, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(s).__name__}, not {cls.__name__}"
                )
            return s

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        return self._get_or_create(name, Gauge, help=help, fn=fn)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = DEFAULT_BUCKETS_MS) -> Histogram:
        return self._get_or_create(name, Histogram, help=help,
                                   buckets=buckets)

    # -- snapshot views -----------------------------------------------------
    def register_view(self, kind: str, obj: Any) -> int:
        """Register a telemetry object exposing ``snapshot() -> dict``
        under ``kind`` (serving/mesh/data/stage).  Weakly referenced:
        a garbage-collected endpoint's telemetry silently leaves the
        scrape.  Returns the instance index used as the ``instance``
        label (per kind, starting at 0)."""
        with self._lock:
            idx = self._view_counts.get(kind, 0)
            self._view_counts[kind] = idx + 1
            self._views.append((kind, idx, weakref.ref(obj)))
            return idx

    def _live_views(self) -> list[tuple[str, int, Any]]:
        with self._lock:
            views = list(self._views)
        out = []
        dead = False
        for kind, idx, ref in views:
            obj = ref()
            if obj is None:
                dead = True
                continue
            out.append((kind, idx, obj))
        if dead:
            with self._lock:
                self._views = [
                    v for v in self._views if v[2]() is not None
                ]
        return out

    # -- exposition ---------------------------------------------------------
    def to_json(self) -> dict:
        """The whole plane as one JSON document: native series keyed by
        name, views keyed ``<kind>/<instance>`` with their UNCHANGED
        snapshot shapes.  ``tx obs metrics`` renders this document;
        ``prometheus_text`` flattens it."""
        with self._lock:
            series = dict(self._series)
        out: dict = {"series": {}, "views": {}}
        for name, s in sorted(series.items()):
            if isinstance(s, Histogram):
                out["series"][name] = {"type": "histogram",
                                       "help": s.help, **s.to_json()}
            elif isinstance(s, Counter):
                out["series"][name] = {"type": "counter", "help": s.help,
                                       "value": s.value}
            else:
                out["series"][name] = {"type": "gauge", "help": s.help,
                                       "value": s.value}
        for kind, idx, obj in self._live_views():
            try:
                snap = obj.snapshot()
            except Exception as e:  # noqa: BLE001 - one broken view must
                # not take down the scrape, but it must be visible
                log.warning("metrics view %s/%d snapshot failed: %s",
                            kind, idx, e)
                self.counter(
                    "obs.view_errors",
                    help="snapshot() failures during exposition",
                ).inc()
                continue
            out["views"][f"{kind}/{idx}"] = snap
        return out

    def prometheus_text(self) -> str:
        return prometheus_text_from_json(self.to_json())


def _fmt_value(v: float) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def prometheus_text_from_json(doc: dict,
                              instance: Optional[str] = None) -> str:
    """Render a :meth:`MetricsRegistry.to_json` document as Prometheus
    text exposition.  ONE renderer for live scrapes and saved JSON
    artifacts (the ``tx obs metrics --format prometheus`` path), so the
    two can never drift.  Every sample carries an ``instance`` label
    naming the PROCESS it came from (ISSUE 11 satellite - the label
    used to be the per-kind view index, which reads as empty identity
    once shards from many processes merge): ``instance`` argument wins,
    then the document's own ``instance`` stamp (saved artifacts render
    as the process that wrote them, not the process reading them), then
    this process's :func:`process_instance`.  View snapshots flatten
    every finite numeric leaf into a gauge named ``tx_<kind>_<path...>``
    with the per-kind index as a ``view`` label; native histograms emit
    the canonical ``_bucket``/``_sum``/``_count`` triplet."""
    inst = instance if instance is not None else doc.get("instance")
    # re-sanitized here too: a hand-edited/foreign document's stamp (or
    # a caller-supplied replica name) must not inject label syntax
    inst = _sanitize_instance(inst) if inst is not None \
        else process_instance()
    ilabel = f'instance="{inst}"'
    lines: list[str] = []
    for name, s in sorted(doc.get("series", {}).items()):
        pname = sanitize_metric_name(name)
        stype = s.get("type", "gauge")
        if s.get("help"):
            lines.append(f"# HELP {pname} {s['help']}")
        if stype == "histogram":
            lines.append(f"# TYPE {pname} histogram")
            acc = 0
            buckets = s.get("buckets", {})
            # sort edges NUMERICALLY: a saved metrics.json artifact has
            # its keys lexicographically reordered by sort_keys=True
            # ("10" < "3"), and cumulative _bucket values rendered in
            # that order would be non-monotonic garbage
            for edge in sorted((e for e in buckets if e != "+Inf"),
                               key=float):
                acc += int(buckets[edge])
                lines.append(
                    f'{pname}_bucket{{{ilabel},le="{edge}"}} {acc}')
            acc += int(buckets.get("+Inf", 0))
            lines.append(f'{pname}_bucket{{{ilabel},le="+Inf"}} {acc}')
            lines.append(
                f"{pname}_sum{{{ilabel}}} {_fmt_value(s.get('sum', 0.0))}")
            lines.append(f"{pname}_count{{{ilabel}}} {int(s.get('count', 0))}")
            continue
        lines.append(f"# TYPE {pname} {stype}")
        lines.append(f"{pname}{{{ilabel}}} {_fmt_value(s.get('value', 0.0))}")
    for key, snap in sorted(doc.get("views", {}).items()):
        kind, _, idx = key.partition("/")
        labels = f'{ilabel},view="{idx}"'
        # multi-model serving (ISSUE 20): a view that names the hosted
        # model it serves gets a model_id label on every sample, so one
        # scrape separates tx_serving_*{model_id="a"} from model "b"
        # on the same replica (sanitized like instance - a foreign
        # document must not inject label syntax)
        model_id = snap.get("model_id") if isinstance(snap, dict) else None
        if isinstance(model_id, str) and model_id:
            labels += f',model_id="{_sanitize_instance(model_id)}"'
        for path, value in sorted(_numeric_leaves(snap)):
            pname = sanitize_metric_name(kind + "_" + "_".join(path))
            lines.append(
                f'{pname}{{{labels}}} {_fmt_value(value)}')
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# module-level plumbing (the mesh_telemetry()/data_telemetry() pattern)
# ---------------------------------------------------------------------------
_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def metrics_registry() -> MetricsRegistry:
    """The process-wide registry every telemetry class registers into
    and ``tx obs`` / the ``metrics_path`` runner knob export from."""
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = MetricsRegistry()
        return _registry


def reset_metrics_registry() -> MetricsRegistry:
    """Fresh registry (test/bench isolation).  Telemetry objects created
    BEFORE the reset stay registered only in the old registry - tests
    that scrape must create their accumulators after resetting."""
    global _registry
    with _registry_lock:
        _registry = MetricsRegistry()
        return _registry
