"""Seeded random feature-data generators.

Counterpart of the reference testkit (reference: testkit/src/main/scala/
com/salesforce/op/testkit/ - RandomReal.scala:45-110 uniform/normal/
poisson, RandomText, RandomBinary, RandomIntegral, RandomList/Map/Set/
Vector, ProbabilityOfEmpty mixin, RandomData joiner, InfiniteStream):
deterministic generators of typed feature columns for tests and synthetic
benchmarks.
"""
from __future__ import annotations

import itertools
import string
from typing import Any, Iterator, Optional, Sequence, Type

import numpy as np

from ..types import feature_types as ft
from ..types.columns import column_from_list
from ..types.dataset import Dataset


class RandomGenerator:
    """Infinite seeded stream of optional values (ProbabilityOfEmpty
    semantics: each draw is None with probability_of_empty)."""

    def __init__(self, seed: int = 42, probability_of_empty: float = 0.0):
        self.rng = np.random.RandomState(seed)
        self.probability_of_empty = probability_of_empty

    def with_probability_of_empty(self, p: float) -> "RandomGenerator":
        self.probability_of_empty = p
        return self

    def _value(self) -> Any:  # pragma: no cover - abstract
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        while True:
            yield self.next()

    def next(self) -> Any:
        if self.probability_of_empty and self.rng.rand() < self.probability_of_empty:
            return None
        return self._value()

    def limit(self, n: int) -> list:
        return [self.next() for _ in range(n)]


class RandomReal(RandomGenerator):
    """(reference: RandomReal.scala:45-110)"""

    def __init__(self, dist: str = "normal", a: float = 0.0, b: float = 1.0,
                 seed: int = 42, probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.dist, self.a, self.b = dist, a, b

    @staticmethod
    def uniform(low=0.0, high=1.0, seed=42) -> "RandomReal":
        return RandomReal("uniform", low, high, seed)

    @staticmethod
    def normal(mean=0.0, sigma=1.0, seed=42) -> "RandomReal":
        return RandomReal("normal", mean, sigma, seed)

    @staticmethod
    def poisson(mean=1.0, seed=42) -> "RandomReal":
        return RandomReal("poisson", mean, 0.0, seed)

    def _value(self) -> float:
        if self.dist == "uniform":
            return float(self.rng.uniform(self.a, self.b))
        if self.dist == "poisson":
            return float(self.rng.poisson(self.a))
        return float(self.rng.normal(self.a, self.b))


class RandomIntegral(RandomGenerator):
    def __init__(self, low: int = 0, high: int = 100, seed: int = 42,
                 probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.low, self.high = low, high

    def _value(self) -> int:
        return int(self.rng.randint(self.low, self.high))


class RandomBinary(RandomGenerator):
    def __init__(self, probability_of_true: float = 0.5, seed: int = 42,
                 probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.p = probability_of_true

    def _value(self) -> bool:
        return bool(self.rng.rand() < self.p)


class RandomText(RandomGenerator):
    """(reference: RandomText.scala - words / picklists / emails / urls...)"""

    def __init__(self, kind: str = "words", domain: Sequence[str] = (),
                 seed: int = 42, probability_of_empty: float = 0.0,
                 n_words: int = 3, word_len: int = 8) -> None:
        super().__init__(seed, probability_of_empty)
        self.kind = kind
        self.domain = list(domain)
        self.n_words = n_words
        self.word_len = word_len

    @staticmethod
    def words(seed=42, n_words=3) -> "RandomText":
        return RandomText("words", seed=seed, n_words=n_words)

    @staticmethod
    def picklists(domain: Sequence[str], seed=42) -> "RandomText":
        return RandomText("pick", domain=domain, seed=seed)

    @staticmethod
    def emails(domain: str = "example.com", seed=42) -> "RandomText":
        return RandomText("email", domain=[domain], seed=seed)

    @staticmethod
    def urls(seed=42) -> "RandomText":
        return RandomText("url", seed=seed)

    @staticmethod
    def phones(seed=42) -> "RandomText":
        return RandomText("phone", seed=seed)

    @staticmethod
    def ids(seed=42) -> "RandomText":
        return RandomText("id", seed=seed)

    def _word(self) -> str:
        letters = string.ascii_lowercase
        n = self.rng.randint(3, self.word_len + 1)
        return "".join(letters[self.rng.randint(26)] for _ in range(n))

    def _value(self) -> str:
        if self.kind == "pick":
            return self.domain[self.rng.randint(len(self.domain))]
        if self.kind == "email":
            return f"{self._word()}@{self.domain[0]}"
        if self.kind == "url":
            return f"https://{self._word()}.com/{self._word()}"
        if self.kind == "phone":
            return f"{self.rng.randint(200,999)}-{self.rng.randint(200,999)}-{self.rng.randint(1000,9999)}"
        if self.kind == "id":
            return f"id_{self.rng.randint(10**8):08d}"
        return " ".join(self._word() for _ in range(self.n_words))


class RandomList(RandomGenerator):
    def __init__(self, element: RandomGenerator, min_len=0, max_len=5,
                 seed: int = 42, probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.element = element
        self.min_len, self.max_len = min_len, max_len

    def _value(self) -> list:
        n = self.rng.randint(self.min_len, self.max_len + 1)
        return [v for v in (self.element.next() for _ in range(n)) if v is not None]


class RandomSet(RandomList):
    def _value(self) -> frozenset:
        return frozenset(super()._value())


class RandomMap(RandomGenerator):
    def __init__(self, value_gen: RandomGenerator, keys: Sequence[str],
                 seed: int = 42, probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.value_gen = value_gen
        self.keys = list(keys)

    def _value(self) -> dict:
        out = {}
        for k in self.keys:
            if self.rng.rand() < 0.7:
                v = self.value_gen.next()
                if v is not None:
                    out[k] = v
        return out


class RandomVector(RandomGenerator):
    def __init__(self, dim: int, seed: int = 42) -> None:
        super().__init__(seed, 0.0)
        self.dim = dim

    def _value(self) -> list:
        return self.rng.randn(self.dim).tolist()


def random_dataset(
    generators: dict[str, tuple[RandomGenerator, Type[ft.FeatureType]]],
    n: int,
) -> Dataset:
    """RandomData joiner analog (reference: RandomData.scala): draw n rows
    from each named generator into one columnar Dataset."""
    return Dataset(
        {
            name: column_from_list(gen.limit(n), t)
            for name, (gen, t) in generators.items()
        }
    )
