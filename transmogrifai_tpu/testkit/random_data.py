"""Seeded random feature-data generators.

Counterpart of the reference testkit (reference: testkit/src/main/scala/
com/salesforce/op/testkit/ - RandomReal.scala:45-110 uniform/normal/
poisson, RandomText, RandomBinary, RandomIntegral, RandomList/Map/Set/
Vector, ProbabilityOfEmpty mixin, RandomData joiner, InfiniteStream):
deterministic generators of typed feature columns for tests and synthetic
benchmarks.
"""
from __future__ import annotations

import itertools
import string
from typing import Any, Iterator, Optional, Sequence, Type

import numpy as np

from ..types import feature_types as ft
from ..types.columns import column_from_list
from ..types.dataset import Dataset


class RandomGenerator:
    """Infinite seeded stream of optional values (ProbabilityOfEmpty
    semantics: each draw is None with probability_of_empty)."""

    def __init__(self, seed: int = 42, probability_of_empty: float = 0.0):
        self.rng = np.random.RandomState(seed)
        self.probability_of_empty = probability_of_empty

    def with_probability_of_empty(self, p: float) -> "RandomGenerator":
        self.probability_of_empty = p
        return self

    def _value(self) -> Any:  # pragma: no cover - abstract
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        while True:
            yield self.next()

    def next(self) -> Any:
        if self.probability_of_empty and self.rng.rand() < self.probability_of_empty:
            return None
        return self._value()

    def limit(self, n: int) -> list:
        return [self.next() for _ in range(n)]


class RandomReal(RandomGenerator):
    """(reference: RandomReal.scala:45-110)"""

    def __init__(self, dist: str = "normal", a: float = 0.0, b: float = 1.0,
                 seed: int = 42, probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.dist, self.a, self.b = dist, a, b

    @staticmethod
    def uniform(low=0.0, high=1.0, seed=42) -> "RandomReal":
        return RandomReal("uniform", low, high, seed)

    @staticmethod
    def normal(mean=0.0, sigma=1.0, seed=42) -> "RandomReal":
        return RandomReal("normal", mean, sigma, seed)

    @staticmethod
    def poisson(mean=1.0, seed=42) -> "RandomReal":
        return RandomReal("poisson", mean, 0.0, seed)

    def _value(self) -> float:
        if self.dist == "uniform":
            return float(self.rng.uniform(self.a, self.b))
        if self.dist == "poisson":
            return float(self.rng.poisson(self.a))
        return float(self.rng.normal(self.a, self.b))


class RandomIntegral(RandomGenerator):
    def __init__(self, low: int = 0, high: int = 100, seed: int = 42,
                 probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.low, self.high = low, high

    def _value(self) -> int:
        return int(self.rng.randint(self.low, self.high))


class RandomBinary(RandomGenerator):
    def __init__(self, probability_of_true: float = 0.5, seed: int = 42,
                 probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.p = probability_of_true

    def _value(self) -> bool:
        return bool(self.rng.rand() < self.p)


class RandomText(RandomGenerator):
    """(reference: RandomText.scala - words / picklists / emails / urls...)"""

    def __init__(self, kind: str = "words", domain: Sequence[str] = (),
                 seed: int = 42, probability_of_empty: float = 0.0,
                 n_words: int = 3, word_len: int = 8) -> None:
        super().__init__(seed, probability_of_empty)
        self.kind = kind
        self.domain = list(domain)
        self.n_words = n_words
        self.word_len = word_len

    @staticmethod
    def words(seed=42, n_words=3) -> "RandomText":
        return RandomText("words", seed=seed, n_words=n_words)

    @staticmethod
    def picklists(domain: Sequence[str], seed=42) -> "RandomText":
        return RandomText("pick", domain=domain, seed=seed)

    @staticmethod
    def emails(domain: str = "example.com", seed=42) -> "RandomText":
        return RandomText("email", domain=[domain], seed=seed)

    @staticmethod
    def urls(seed=42) -> "RandomText":
        return RandomText("url", seed=seed)

    @staticmethod
    def phones(seed=42) -> "RandomText":
        return RandomText("phone", seed=seed)

    @staticmethod
    def ids(seed=42) -> "RandomText":
        return RandomText("id", seed=seed)

    def _word(self) -> str:
        letters = string.ascii_lowercase
        n = self.rng.randint(3, self.word_len + 1)
        return "".join(letters[self.rng.randint(26)] for _ in range(n))

    def _value(self) -> str:
        if self.kind == "pick":
            return self.domain[self.rng.randint(len(self.domain))]
        if self.kind == "email":
            return f"{self._word()}@{self.domain[0]}"
        if self.kind == "url":
            return f"https://{self._word()}.com/{self._word()}"
        if self.kind == "phone":
            return f"{self.rng.randint(200,999)}-{self.rng.randint(200,999)}-{self.rng.randint(1000,9999)}"
        if self.kind == "id":
            return f"id_{self.rng.randint(10**8):08d}"
        return " ".join(self._word() for _ in range(self.n_words))


class RandomList(RandomGenerator):
    def __init__(self, element: RandomGenerator, min_len=0, max_len=5,
                 seed: int = 42, probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.element = element
        self.min_len, self.max_len = min_len, max_len

    def _value(self) -> list:
        n = self.rng.randint(self.min_len, self.max_len + 1)
        return [v for v in (self.element.next() for _ in range(n)) if v is not None]


class RandomSet(RandomList):
    def _value(self) -> frozenset:
        return frozenset(super()._value())


class RandomMap(RandomGenerator):
    def __init__(self, value_gen: RandomGenerator, keys: Sequence[str],
                 seed: int = 42, probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.value_gen = value_gen
        self.keys = list(keys)

    def _value(self) -> dict:
        out = {}
        for k in self.keys:
            if self.rng.rand() < 0.7:
                v = self.value_gen.next()
                if v is not None:
                    out[k] = v
        return out


class RandomVector(RandomGenerator):
    def __init__(self, dim: int, seed: int = 42) -> None:
        super().__init__(seed, 0.0)
        self.dim = dim

    def _value(self) -> list:
        return self.rng.randn(self.dim).tolist()


class RandomDate(RandomGenerator):
    """Epoch-millis dates (reference: RandomIntegral.dates)."""

    def __init__(self, start_ms: int = 1_400_000_000_000,
                 span_ms: int = 100_000_000_000, seed: int = 42,
                 probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.start_ms, self.span_ms = start_ms, span_ms

    def _value(self) -> int:
        # explicit int64: the default randint dtype is np.int_ which is
        # 32-bit on some platforms and cannot hold a 1e11 span
        return self.start_ms + int(
            self.rng.randint(0, self.span_ms, dtype=np.int64)
        )


class RandomGeolocation(RandomGenerator):
    def _value(self) -> tuple:
        return (
            float(self.rng.uniform(-60, 60)),
            float(self.rng.uniform(-180, 180)),
            float(self.rng.randint(1, 10)),
        )


class RandomMultiPickList(RandomGenerator):
    def __init__(self, domain: Sequence[str], min_len=0, max_len=3,
                 seed: int = 42, probability_of_empty: float = 0.0) -> None:
        super().__init__(seed, probability_of_empty)
        self.domain = list(domain)
        self.min_len, self.max_len = min_len, max_len

    def _value(self) -> frozenset:
        k = self.rng.randint(self.min_len, self.max_len + 1)
        return frozenset(
            self.domain[self.rng.randint(len(self.domain))] for _ in range(k)
        )


def default_generator(
    t: Type[ft.FeatureType], seed: int = 42, probability_of_empty: float = 0.0
) -> RandomGenerator:
    """A sensible generator for any feature type - the glue that lets
    stress tests sweep the whole type lattice (reference: the testkit's
    per-type Random* companions)."""
    p = probability_of_empty
    if issubclass(t, ft.OPMap):
        vt = t.value_type or ft.Text
        return RandomMap(default_generator(vt, seed + 1), ["k1", "k2", "k3"],
                         seed=seed, probability_of_empty=p)
    if issubclass(t, ft.Binary):
        return RandomBinary(seed=seed, probability_of_empty=p)
    if issubclass(t, ft.Date):
        return RandomDate(seed=seed, probability_of_empty=p)
    if issubclass(t, ft.Integral):
        return RandomIntegral(seed=seed, probability_of_empty=p)
    if issubclass(t, ft.Real):
        return RandomReal(seed=seed,
                          probability_of_empty=0.0 if t.non_nullable else p)
    if issubclass(t, ft.PickList) or issubclass(t, ft.ComboBox):
        return RandomText.picklists(
            ["red", "green", "blue"], seed=seed
        ).with_probability_of_empty(p)
    if issubclass(t, ft.Email):
        return RandomText.emails(seed=seed).with_probability_of_empty(p)
    if issubclass(t, ft.Phone):
        return RandomText.phones(seed=seed).with_probability_of_empty(p)
    if issubclass(t, ft.URL):
        return RandomText.urls(seed=seed).with_probability_of_empty(p)
    if issubclass(t, ft.ID):
        return RandomText.ids(seed=seed).with_probability_of_empty(p)
    if issubclass(t, ft.Text):
        return RandomText.words(seed=seed).with_probability_of_empty(p)
    if issubclass(t, ft.MultiPickList):
        return RandomMultiPickList(["a", "b", "c", "d"], seed=seed,
                                   probability_of_empty=p)
    if issubclass(t, ft.Geolocation):
        return RandomGeolocation(seed=seed, probability_of_empty=p)
    if issubclass(t, ft.TextList):
        return RandomList(RandomText.words(seed=seed + 1), seed=seed,
                          probability_of_empty=p)
    if issubclass(t, ft.DateList):
        return RandomList(RandomDate(seed=seed + 1), max_len=3, seed=seed,
                          probability_of_empty=p)
    if issubclass(t, ft.OPVector):
        return RandomVector(4, seed=seed)
    raise TypeError(f"no default generator for {t.__name__}")


def random_dataset(
    generators: dict[str, tuple[RandomGenerator, Type[ft.FeatureType]]],
    n: int,
) -> Dataset:
    """RandomData joiner analog (reference: RandomData.scala): draw n rows
    from each named generator into one columnar Dataset."""
    return Dataset(
        {
            name: column_from_list(gen.limit(n), t)
            for name, (gen, t) in generators.items()
        }
    )


def write_corrupted_csv(
    path: str,
    n_rows: int = 500,
    n_type_flips: int = 5,
    n_truncated: int = 3,
    seed: int = 7,
) -> dict:
    """Deterministic corrupted-CSV generator for the data-plane drills
    (shared by tests/test_data_plane.py and ``bench.py --data-faults``).

    Writes a mixed numeric/text file (columns ``y``, ``a``, ``c``) with
    ``n_type_flips`` rows whose numeric cell ``a`` holds junk text and
    ``n_truncated`` rows missing their trailing fields.  Returns the
    ground truth a quarantine ingest must reproduce EXACTLY::

        {"n_rows", "columns", "type_flip_rows", "truncated_rows",
         "bad_rows", "good_rows"}
    """
    rng = np.random.RandomState(seed)
    n_bad = n_type_flips + n_truncated
    if n_bad > n_rows:
        raise ValueError("more corrupted rows than rows")
    bad = rng.choice(n_rows, size=n_bad, replace=False)
    flip_rows = sorted(int(i) for i in bad[:n_type_flips])
    trunc_rows = sorted(int(i) for i in bad[n_type_flips:])
    flips, truncs = set(flip_rows), set(trunc_rows)
    cats = ("u", "v", "w")
    with open(path, "w", newline="") as f:
        f.write("y,a,c\n")
        for i in range(n_rows):
            y = i % 2
            a = rng.randn()
            c = cats[i % 3]
            if i in flips:
                f.write(f"{y},not-a-number-{i},{c}\n")
            elif i in truncs:
                f.write(f"{y}\n")
            else:
                f.write(f"{y},{a:.6f},{c}\n")
    return {
        "n_rows": n_rows,
        "columns": ["y", "a", "c"],
        "type_flip_rows": flip_rows,
        "truncated_rows": trunc_rows,
        "bad_rows": sorted(flips | truncs),
        "good_rows": n_rows - n_bad,
    }


def shift_records(records, feature: str, delta: float = 0.0,
                  scale: float = 1.0) -> list[dict]:
    """Distribution-shifted copies of serve records (drift-guard
    drills): numeric ``feature`` becomes ``value * scale + delta``,
    missing values stay missing, everything else is untouched - the
    batch stays schema-VALID, only its distribution moves."""
    out = []
    for r in records:
        r = dict(r)
        v = r.get(feature)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            r[feature] = v * scale + delta
        out.append(r)
    return out


class InfiniteStream:
    """Endless Dataset batches from named generators (reference:
    testkit InfiniteStream): drives streaming-score paths and soak tests.
    Deterministic: each batch continues the generators' seeded streams."""

    def __init__(
        self,
        generators: dict[str, tuple[RandomGenerator, Type[ft.FeatureType]]],
        batch_size: int = 100,
    ) -> None:
        self.generators = generators
        self.batch_size = batch_size

    def __iter__(self) -> Iterator[Dataset]:
        while True:
            yield self.next_batch()

    def next_batch(self) -> Dataset:
        return random_dataset(self.generators, self.batch_size)

    def take(self, n_batches: int) -> list[Dataset]:
        return [self.next_batch() for _ in range(n_batches)]
