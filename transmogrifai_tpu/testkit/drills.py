"""Shared fixtures for the fault/robustness drills.

One tiny-but-real pipeline (FeatureBuilder -> transmogrify -> LR through
the full stage stack) used by tests/test_faults.py,
tests/test_model_io_corruption.py and ``bench.py --faults`` so the drill
surface cannot drift between them, plus the crash-saver child-script
template the kill-during-save drills run (the kill must land in a child
process: faults.inject_kill calls ``os._exit``).
"""
from __future__ import annotations


def tiny_drill_pipeline(n: int = 120, seed: int = 0):
    """-> (workflow, data, records, prediction_name): a seconds-to-train
    mixed-type pipeline whose numbers still come from the real stage
    stack."""
    import numpy as np

    import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
    from .. import FeatureBuilder, OpWorkflow
    from ..models.logistic_regression import OpLogisticRegression
    from ..ops.transmogrifier import transmogrify
    from ..types import feature_types as ft

    rng = np.random.RandomState(seed)
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
        "c": [("u", "v", "w")[i % 3] for i in range(n)],
    }
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    vec = transmogrify([a, c])
    pred = OpLogisticRegression(reg_param=0.01).set_input(y, vec).get_output()
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    records = [{"a": data["a"][i], "c": data["c"][i]} for i in range(n)]
    return wf, data, records, pred.name


def corrupted_csv_drill(dirpath: str, n_rows: int = 500,
                        n_type_flips: int = 5, n_truncated: int = 3,
                        seed: int = 7):
    """-> (csv_path, raw_features, truth): a corrupted CSV matching the
    tiny drill pipeline's schema (y response, a numeric, c picklist)
    plus the exact corruption ground truth (random_data.
    write_corrupted_csv) - ONE fixture shared by the quarantine tests,
    the chaos-composition drill, and ``bench.py --data-faults`` so
    their expected counts can never drift apart."""
    import os

    import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
    from .. import FeatureBuilder
    from ..types import feature_types as ft
    from .random_data import write_corrupted_csv

    path = os.path.join(dirpath, "corrupted.csv")
    truth = write_corrupted_csv(
        path, n_rows=n_rows, n_type_flips=n_type_flips,
        n_truncated=n_truncated, seed=seed,
    )
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    return path, [y, a, c], truth


def serving_fleet_workflow(n: int = 891, seed: int = 7):
    """-> (workflow, records): the serving-bench synthetic mixed-type
    pipeline (picklists + reals + integrals through transmogrify ->
    sanity check -> LR) - the fleet workload.  IMPORTABLE as
    ``transmogrifai_tpu.testkit.drills:serving_fleet_workflow`` so
    replica worker processes can rebuild the workflow a registry
    artifact was trained under (``bench.py --fleet`` + tests/
    test_fleet.py share it; deterministic for a fixed seed)."""
    import numpy as np

    import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
    from .. import FeatureBuilder, OpWorkflow
    from ..models.logistic_regression import OpLogisticRegression
    from ..ops.transmogrifier import transmogrify
    from ..types import feature_types as ft

    rng = np.random.RandomState(seed)
    cabins = ["A1", "B2", "C3", "D4", None]
    data = {
        "label": (rng.rand(n) > 0.6).astype(float).tolist(),
        "klass": [str(rng.randint(1, 4)) for _ in range(n)],
        "sex": [("male", "female")[rng.randint(2)] for _ in range(n)],
        "age": [float(a) if rng.rand() > 0.2 else None
                for a in rng.uniform(1, 80, n)],
        "fare": rng.uniform(5, 500, n).round(2).tolist(),
        "sibs": rng.randint(0, 5, n).astype(float).tolist(),
        "cabin": [cabins[rng.randint(len(cabins))] for _ in range(n)],
    }
    label = FeatureBuilder(ft.RealNN, "label").as_response()
    klass = FeatureBuilder(ft.PickList, "klass").as_predictor()
    sex = FeatureBuilder(ft.PickList, "sex").as_predictor()
    age = FeatureBuilder(ft.Real, "age").as_predictor()
    fare = FeatureBuilder(ft.Real, "fare").as_predictor()
    sibs = FeatureBuilder(ft.Integral, "sibs").as_predictor()
    cabin = FeatureBuilder(ft.PickList, "cabin").as_predictor()
    vec = transmogrify(
        [klass, sex, age.fill_missing_with_mean().z_normalize(), fare,
         sibs, cabin]
    )
    checked = label.sanity_check(vec, remove_bad_features=True)
    pred = OpLogisticRegression(reg_param=0.01).set_input(
        label, checked).get_output()
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    feature_names = ("klass", "sex", "age", "fare", "sibs", "cabin")
    records = [{k: data[k][i] for k in feature_names} for i in range(n)]
    return wf, records


def drill_env() -> dict:
    """Child-process env for supervision/crash drills: CPU backend, no
    inherited fault plan (TX_FAULTS would re-arm in the child), no axon
    pool tunnel.  The ambient trace context rides along (obs.fleet.
    child_env): a drill child's spans join the test's trace, exactly
    like a production child's join its dispatching run's (ISSUE 11)."""
    import os

    from ..obs.fleet import child_env

    env = child_env(dict(os.environ, JAX_PLATFORMS="cpu"))
    env.pop("TX_FAULTS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


# -- continuous-training drill surface (ISSUE 16) ---------------------------
def continuous_shard_rows(n: int = 64, seed: int = 0,
                          shift: float = 0.0) -> list:
    """-> n row dicts (y, a, c) matching the tiny drill schema.  ``a``
    is N(shift, 1) and ``y`` thresholds on the CENTERED value, so the
    label balance (and therefore trainability) survives any shift while
    the marginal of ``a`` - what the drift monitor watches - moves with
    it.  Deterministic per (n, seed, shift) so the continuous e2e test,
    the chaos drill and ``bench.py --continuous`` stream byte-identical
    data."""
    import numpy as np

    rng = np.random.RandomState(seed)
    a = rng.randn(n) + float(shift)
    y = ((a - float(shift) + 0.3 * rng.randn(n)) > 0).astype(float)
    return [
        {"y": float(y[i]), "a": float(a[i]),
         "c": ("u", "v", "w")[i % 3]}
        for i in range(n)
    ]


def write_shard_csv(path: str, rows: list) -> str:
    """Atomically publish one y,a,c shard CSV (tmp + os.replace): the
    producer contract :class:`~..readers.pipeline.ShardDirectoryFollower`
    documents - the follower must never see a half-written file."""
    import csv
    import os
    import tempfile

    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    with os.fdopen(fd, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=["y", "a", "c"])
        w.writeheader()
        w.writerows(rows)
    os.replace(tmp, path)
    return path


def continuous_drill_workflow(n: int = 256, seed: int = 0):
    """-> a selector-backed workflow over the y/a/c drill schema, input
    dataset attached (``continuous_shard_rows(n, seed)``).  IMPORTABLE
    as ``transmogrifai_tpu.testkit.drills:continuous_drill_workflow``,
    the daemon/worker/seed-trainer factory convention.  The selector
    (2 folds x 2-point LR grid) is what makes refits exercise the PR-15
    fused-train cache; the shape bucket is exact, so a refit hits the
    seed's cached executable ONLY when it trains on the same
    (rows, width, folds, grid) - stream exactly ``n`` rows before
    triggering."""
    import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
    from .. import FeatureBuilder, OpWorkflow
    from ..models.logistic_regression import OpLogisticRegression
    from ..ops.transmogrifier import transmogrify
    from ..selector.factories import BinaryClassificationModelSelector
    from ..types import feature_types as ft

    rows = continuous_shard_rows(n, seed)
    data = {k: [r[k] for r in rows] for k in ("y", "a", "c")}
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    vec = transmogrify([a, c])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2,
        models_and_parameters=[
            (OpLogisticRegression(max_iter=6),
             [{"reg_param": r, "elastic_net_param": 0.1}
              for r in (0.01, 0.1)]),
        ],
        splitter=None,
    )
    pred = selector.set_input(y, vec).get_output()
    return OpWorkflow().set_result_features(pred).set_input_dataset(data)


def continuous_tiny_factory():
    """-> the plain-LR tiny drill workflow (no selector): the FAST
    factory for continuous drills that exercise crash/recovery paths
    rather than the fused-train cache."""
    return tiny_drill_pipeline()[0]


#: child for the continuous warm-refit drills (tests/test_continuous.py
#: + ``bench.py --continuous``): cold-train the selector drill workflow
#: of exactly ``n`` rows with the fused-train AOT cache at ``cache_dir``,
#: publish the model as stable v1 into the registry at ``root``.  Runs
#: in a CHILD so the parent's in-process program registry stays empty -
#: the daemon's first refit then proves disk REHYDRATION (cache "hit",
#: load_ms > 0, compile_ms == 0), not a same-process memory hit.
CONTINUOUS_SEED_TRAINER_TEMPLATE = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TX_PRODUCT_MESH", "0")
from transmogrifai_tpu.testkit.drills import continuous_drill_workflow
from transmogrifai_tpu.registry import ModelRegistry
from transmogrifai_tpu.workflow.dag import compute_dag
from transmogrifai_tpu.workflow.runner import train_fused_summary
wf = continuous_drill_workflow(n={n}, seed={seed})
validators = []
for layer in compute_dag(wf.result_features):
    for stage in layer:
        if getattr(stage, "is_model_selector", False):
            stage.validator.train_fused = True
            stage.validator.train_cache_dir = {cache_dir!r}
            validators.append(stage.validator)
model = wf.train()
trail = train_fused_summary(validators)
reg = ModelRegistry({root!r})
entry = reg.publish(model, stage="stable")
print("SEEDED", entry.version, json.dumps(trail), flush=True)
os._exit(0)
"""


#: child for the ``continuous.refit_crash`` drills: run one trainer
#: cycle over a pre-seeded registry + a pre-written drifted shard with
#: the kill armed - the refit completes, then the process dies in the
#: window BEFORE the registry publish (exit DEFAULT_KILL_EXIT).  The
#: parent asserts the registry still points at the old stable and a
#: fresh (unarmed) trainer's next cycle recovers end-to-end.  Tiny
#: factory + consecutive=1/cooldown=0 + train_fused off: the drill pins
#: crash containment, not cache warmth.
CONTINUOUS_REFIT_CRASH_TEMPLATE = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from transmogrifai_tpu.continuous import ContinuousTrainer
from transmogrifai_tpu.faults import injection
trainer = ContinuousTrainer(
    {watch!r}, {root!r},
    "transmogrifai_tpu.testkit.drills:continuous_tiny_factory",
    drift_threshold=0.05, consecutive_windows=1, cooldown_windows=0,
    min_window_rows=8, refit_rows=256, train_fused=False,
)
injection.configure({fault!r})            # arm the crash
trainer.run_cycle()                       # dies at continuous.refit_crash
os._exit(0)                               # unreachable when armed
"""


#: child script for supervision drills: exits ``first_exit`` on the run
#: that creates ``marker``, ``then_exit`` on every run after (die-once
#: recovery when then_exit=0, differing-exit-codes when both non-zero).
DIE_ONCE_CHILD_TEMPLATE = """
import os, sys
p = {marker!r}
if not os.path.exists(p):
    open(p, 'w').close()
    sys.exit({first_exit})
sys.exit({then_exit})
"""


#: child for the mesh-peer drills (tests/test_mesh_resilience.py +
#: ``bench.py --mesh-faults``): beats its PeerHealth heartbeat ``beats``
#: times at ``interval`` seconds, then either dies (``mode='die'``, exit
#: ``exit_code``) or wedges alive-but-beatless (``mode='hang'``) - the
#: two stall classes a surviving mesh process must tell apart from
#: heartbeat files alone.  Deliberately jax-free: PeerHealth is
#: file-based exactly so liveness never rides the (possibly wedged)
#: collective channel.
MESH_PEER_CHILD_TEMPLATE = """
import os, sys, time
sys.path.insert(0, {repo!r})
from transmogrifai_tpu.parallel.resilience import PeerHealth
ph = PeerHealth({hb_dir!r}, process_id={peer_id})
for _ in range({beats}):
    ph.beat()
    time.sleep({interval})
if {mode!r} == "die":
    os._exit({exit_code})
time.sleep(600)  # hang: alive but no longer beating
"""


#: child for the bootstrap-deadline drills: initialize() against a
#: coordinator that never answers (armed via TX_FAULTS
#: ``mesh.init_no_coordinator`` in the child env, or a genuinely
#: unreachable ``addr``) must raise MeshBootstrapError within
#: TX_MESH_INIT_TIMEOUT_S - exit 42 proves the named error, any other
#: loud failure exits 43, and an indefinite hang fails the drill's
#: subprocess timeout.
MESH_BOOTSTRAP_CHILD_TEMPLATE = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
sys.path.insert(0, {repo!r})
from transmogrifai_tpu.parallel.distributed import (
    MeshBootstrapError, initialize)
try:
    initialize(coordinator_address={addr!r}, num_processes=2, process_id=0)
except MeshBootstrapError as e:
    print("MESH_BOOTSTRAP_ERROR:", str(e)[:160], flush=True)
    os._exit(42)  # _exit: a half-dialed grpc runtime must not block exit
except Exception as e:
    print("OTHER_ERROR:", type(e).__name__, str(e)[:160], flush=True)
    os._exit(43)
print("NO_ERROR", flush=True)
os._exit(0)
"""


#: child for the registry publish-crash drills (tests/test_registry.py,
#: tests/test_chaos_composition.py, ``bench.py --registry``): train the
#: tiny pipeline, publish + promote a clean v1 into the registry at
#: ``root``, arm ``fault`` (e.g. "registry.publish_crash:on=1"), publish
#: again and die in the window between the artifact save and the index
#: commit.  Exits 0 only if the kill failed to fire; the parent asserts
#: the registry is still loadable at v1.
REGISTRY_CRASH_PUBLISHER_TEMPLATE = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from transmogrifai_tpu.testkit.drills import tiny_drill_pipeline
from transmogrifai_tpu.registry import ModelRegistry
wf, _data, _records, _name = tiny_drill_pipeline()
model = wf.train()
reg = ModelRegistry({root!r})
v1 = reg.publish(model, metrics={{"auroc": 0.9}})
reg.promote(v1.version, to="stable")
from transmogrifai_tpu.faults import injection
injection.configure({fault!r})            # arm the crash
reg.publish(model)                        # dies at the injected point
os._exit(0)                               # unreachable when armed
"""


#: child script for the kill-during-save drills: train the tiny pipeline,
#: save a clean v1, arm ``fault`` (e.g. "io.save_model.crash_window:on=1"),
#: save again and die at the injected point.  Format with repo / path /
#: fault; exits 0 only if the kill failed to fire.
CRASH_SAVER_TEMPLATE = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from transmogrifai_tpu.testkit.drills import tiny_drill_pipeline
wf, _data, _records, _name = tiny_drill_pipeline()
model = wf.train()
model.save({path!r})                      # clean v1
from transmogrifai_tpu.faults import injection
injection.configure({fault!r})            # arm the crash
model.save({path!r})                      # dies at the injected point
os._exit(0)                               # unreachable when armed
"""


#: child for the fleet-aggregation drills (tests/test_obs_fleet.py +
#: ``bench.py --obs-fleet``): beats metrics + spans into its own obs
#: shard every ``interval`` seconds for ``duration`` seconds, then
#: exits 0.  Adopts the parent's trace context from the env seam
#: automatically (Tracer reads TX_OBS_TRACE_CONTEXT at construction),
#: so its spans merge into the dispatching test's trace; SIGKILLing it
#: mid-loop is the torn-write/staleness drill - the atomic-rename
#: shipping discipline must leave the aggregation dir readable.
FLEET_SHIPPER_CHILD_TEMPLATE = """
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from transmogrifai_tpu.obs import metrics_registry, ship_now, span
ticks = metrics_registry().counter("drill.ticks")
print("SHIPPER_READY", os.getpid(), flush=True)
deadline = time.monotonic() + {duration}
while time.monotonic() < deadline:
    with span("shipper.tick", pid=os.getpid()):
        ticks.inc()
    ship_now({agg_dir!r})
    time.sleep({interval})
os._exit(0)
"""


#: grandchild for the supervised-fleet e2e drill: the "deploy child" -
#: joins the trace via the env seam, records a span, ships its shard,
#: exits.  Spawned BY :data:`FLEET_DRILL_CHILD_TEMPLATE` through
#: ``obs.fleet.child_env()``, two process hops below the test.
FLEET_DEPLOY_CHILD_TEMPLATE = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from transmogrifai_tpu.obs import ship_now, span
with span("deploy.child", pid=os.getpid()):
    pass
ship_now({agg_dir!r})
os._exit(0)
"""


#: supervised child for the e2e fleet drill (ISSUE 11 acceptance): the
#: child adopts the supervisor's exported trace context, beats the
#: supervision heartbeat, records spans, spawns the deploy grandchild
#: (``grand`` is the already-formatted FLEET_DEPLOY_CHILD source) with
#: the context re-exported, ships its own shard, then die-once exits
#: ``first_exit`` on the run that creates ``marker`` and 0 after - so
#: one supervise() call produces spans from at least three pids
#: (attempt 1, attempt 2, grandchild) under ONE trace id.
FLEET_DRILL_CHILD_TEMPLATE = """
import os, subprocess, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from transmogrifai_tpu.obs import fleet, ship_now, span
from transmogrifai_tpu.workflow.supervisor import beat
beat({heartbeat!r})
with span("child.work", pid=os.getpid()):
    rc = subprocess.run(
        [sys.executable, "-c", {grand!r}],
        env=fleet.child_env(), timeout=120,
    ).returncode
beat({heartbeat!r})
ship_now({agg_dir!r})
if rc != 0:
    sys.exit(99)
marker = {marker!r}
if not os.path.exists(marker):
    open(marker, "w").close()
    sys.exit({first_exit})
sys.exit(0)
"""


#: child for the exactly-once bulk-scoring kill drills (tests/
#: test_bulk.py, the chaos composition's bulk phase, and
#: ``bench.py --bulk``): arms one ``bulk.*`` fault, trains the tiny
#: drill pipeline deterministically (the resuming parent trains the
#: SAME weights from the same seed, so post-resume output bytes are
#: comparable), then runs a BulkScoringJob that the armed fault must
#: SIGKILL mid-flight - ``os._exit(3)`` is unreachable when armed.
BULK_KILL_CHILD_TEMPLATE = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from transmogrifai_tpu.faults import injection
injection.configure({fault!r})
from transmogrifai_tpu.testkit.drills import tiny_drill_pipeline
from transmogrifai_tpu.bulk import BulkScoringJob
wf, _data, _records, _pred = tiny_drill_pipeline(n={n}, seed=0)
model = wf.train()
BulkScoringJob(model, {job_dir!r}, {shards!r}, chunk_rows={chunk}).run()
os._exit(3)  # unreachable: the armed fault must kill first
"""
