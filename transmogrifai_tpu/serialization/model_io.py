"""Workflow model persistence.

Counterpart of OpWorkflowModelWriter / OpWorkflowModelReader (reference:
core/.../OpWorkflowModelWriter.scala:52-140, OpWorkflowModelReader.scala):
the whole fitted workflow saves as one JSON document (stage classes, params,
metadata, result-feature names) plus an .npz of every array-valued piece of
fitted state.  Loading mirrors the reference's contract: the model is
restored INTO the same code-defined workflow (OpWorkflow.loadModel,
OpWorkflow.scala:468) - stages are re-paired with the freshly built DAG in
deterministic order, so feature wiring never needs serializing.

Persistence is crash-consistent (the user-level-checkpointing recovery
primitive, TensorFlow §4.2): the artifact writes into a temp directory,
every file is fsynced, a ``manifest.json`` records per-file SHA-256 +
sizes, and the finished directory swaps into place by rename - the
previous artifact survives as ``<path>.last-good``.  A crash at ANY
instant therefore leaves a loadable artifact: either the old one (crash
before the swap) or the new one (crash after), and ``load_model``
verifies checksums before trusting anything, falling back to the
last-good copy when the primary is truncated, bit-flipped, or missing.
Injection points ``io.save_model.crash`` / ``io.save_model.crash_window``
(faults/injection.py) drill both crash windows in tests/test_faults.py.
"""
from __future__ import annotations

import glob
import hashlib
import importlib
import json
import logging
import os
import shutil
import zipfile
import zlib
from typing import Any, Optional

import numpy as np

from ..faults import injection as _faults

log = logging.getLogger("transmogrifai_tpu.serialization")

MODEL_JSON = "model.json"
ARRAYS_NPZ = "arrays.npz"
MANIFEST_JSON = "manifest.json"
SCHEMA_JSON = "schema.json"
#: AOT-compiled XLA executables (local/fused_xla.py): meta + payload
#: blobs, persisted inside the same crash-consistent artifact so a
#: replica cold-starts by deserializing binaries instead of re-tracing
XLA_CACHE_JSON = "xla_cache.json"
XLA_CACHE_NPZ = "xla_cache.npz"
LAST_GOOD_SUFFIX = ".last-good"


class ModelLoadError(RuntimeError):
    """A model artifact cannot be restored; the message names the
    artifact file and (where applicable) the stage path inside it."""


class ModelIntegrityError(ModelLoadError):
    """Checksum/manifest verification failed and no last-good artifact
    could recover the load (truncation, bit-flips, missing files)."""


class _ArrayStore:
    """arrays.npz accessor that turns a missing/mismatched key into a
    ModelLoadError naming the stage path and the artifact file instead
    of a raw KeyError deep inside ``_decode``."""

    def __init__(self, npz, artifact: str) -> None:
        self._npz = npz
        self._artifact = artifact

    def __getitem__(self, key: str):
        try:
            return self._npz[key]
        except KeyError:
            raise ModelLoadError(
                f"model artifact {self._artifact} has no array for stage "
                f"path '{key}': {os.path.basename(self._artifact)} is "
                "truncated or belongs to a different model.json"
            ) from None
        except (zipfile.BadZipFile, zlib.error, OSError, ValueError) as e:
            # npz members decompress lazily: a corrupt legacy (manifest-
            # less) artifact surfaces HERE, not at np.load - still a
            # ModelLoadError, never a raw zlib traceback
            raise ModelLoadError(
                f"model artifact {self._artifact} is corrupt at stage "
                f"path '{key}': {type(e).__name__}: {e}"
            ) from e


def _encode(value: Any, arrays: dict[str, np.ndarray], path: str) -> Any:
    if isinstance(value, np.ndarray):
        arrays[path] = value
        return {"__npz__": path}
    if isinstance(value, (np.floating, np.integer)):
        return value.item()
    if isinstance(value, dict):
        return {
            "__dict__": {
                k: _encode(v, arrays, f"{path}.{k}") for k, v in value.items()
            }
        }
    if isinstance(value, (list, tuple)):
        enc = [_encode(v, arrays, f"{path}[{i}]") for i, v in enumerate(value)]
        return {"__list__": enc, "__tuple__": isinstance(value, tuple)}
    if isinstance(value, (set, frozenset)):
        try:
            items = sorted(value)
        except TypeError:
            items = list(value)
        enc = [_encode(v, arrays, f"{path}{{{i}}}") for i, v in enumerate(items)]
        return {"__set__": enc, "__frozen__": isinstance(value, frozenset)}
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    raise TypeError(
        f"cannot serialize {type(value).__name__} at {path}; stages must keep "
        "fitted state as arrays/scalars/dicts/lists"
    )


def _decode(value: Any, arrays) -> Any:
    if isinstance(value, dict):
        if "__npz__" in value:
            return arrays[value["__npz__"]]
        if "__dict__" in value:
            return {k: _decode(v, arrays) for k, v in value["__dict__"].items()}
        if "__list__" in value:
            items = [_decode(v, arrays) for v in value["__list__"]]
            return tuple(items) if value.get("__tuple__") else items
        if "__set__" in value:
            items = [_decode(v, arrays) for v in value["__set__"]]
            return frozenset(items) if value.get("__frozen__") else set(items)
    return value


# attributes owned by the stage machinery, not fitted state
_SKIP_ATTRS = {
    "input_features", "_output", "uid", "operation_name", "params",
    "metadata", "estimator_ref", "selector", "validator", "models",
    "splitter", "evaluators", "validation_result", "fn", "predicate",
    "model", "output_type", "input_types", "prefer_numpy",
    # per-process transform memoizations (vectorizer_base/combiner/
    # sanity_checker): identity-keyed, must never persist
    "_meta_cache", "_combine_cache", "_select_cache",
    "_metas_memo", "_pivot_helpers",
}


def stage_state(stage) -> dict[str, Any]:
    out = {}
    for k, v in vars(stage).items():
        if k in _SKIP_ATTRS:
            continue
        out[k] = v
    return out


def _write_fsync(path: str, data: bytes) -> None:
    """Write + flush + fsync: the bytes are durable before any rename
    can publish a directory that references them."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def write_bytes_atomic(path: str, data: bytes) -> None:
    """Crash-consistent single-file byte write: fsync'd temp file in the
    target directory, atomic ``os.replace``, directory fsync.  The
    sidecar artifacts that ride NEXT TO the model artifact - the
    ISSUE-15 ``train_xla_cache/`` executable entries - reuse this
    instead of re-inventing the discipline; a reader never observes a
    torn file, only the old bytes or the new ones."""
    parent = os.path.dirname(path) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    _write_fsync(tmp, data)
    os.replace(tmp, path)
    _fsync_dir(parent)


def _fsync_dir(path: str) -> None:
    """fsync a directory so renames within it are durable (best-effort:
    some filesystems refuse directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        log.debug("directory fsync unsupported for %s", path)
    finally:
        os.close(fd)


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _writer_alive(tmp_dir: str) -> bool:
    """True when the pid encoded in a ``<path>.tmp-<pid>`` save tempdir
    still belongs to a live process on THIS host (liveness is the reap
    guard; unparseable names count as live = never reaped)."""
    suffix = tmp_dir.rpartition(".tmp-")[2]
    try:
        pid = int(suffix)
    except ValueError:
        return True
    if pid == os.getpid():
        return False  # our own leftover from a failed earlier save
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True  # EPERM etc: the pid exists, leave it alone


_HASH_CHUNK = 1 << 20


def _sha256_file(path: str) -> tuple[str, int]:
    """Chunked (bounded-memory) file hash -> (hexdigest, byte size)."""
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_HASH_CHUNK)
            if not chunk:
                break
            h.update(chunk)
            size += len(chunk)
    return h.hexdigest(), size


def save_model(model, path: str) -> None:
    """Crash-consistent save: tempdir write -> fsync -> manifest ->
    atomic rename swap (the previous artifact survives as
    ``<path>.last-good``)."""
    path = os.path.abspath(path).rstrip(os.sep)
    arrays: dict[str, np.ndarray] = {}
    stages_doc = []
    for i, stage in enumerate(model.stages):
        cls = type(stage)
        doc: dict[str, Any] = {
            "index": i,
            "class": f"{cls.__module__}.{cls.__qualname__}",
            "uid": stage.uid,
            "operation_name": stage.operation_name,
            "output_name": stage.output_name,
            "params": _encode(stage.params, arrays, f"s{i}.params"),
            "metadata": _encode(stage.metadata, arrays, f"s{i}.metadata"),
            "state": _encode(stage_state(stage), arrays, f"s{i}.state"),
        }
        if hasattr(stage, "estimator_ref"):
            est = stage.estimator_ref
            doc["estimator"] = {
                "class": f"{type(est).__module__}.{type(est).__qualname__}",
                "params": _encode(est.params, arrays, f"s{i}.est_params"),
            }
        stages_doc.append(doc)
    doc = {
        "format_version": 1,
        "result_features": [f.name for f in model.result_features],
        "raw_features": [
            {"name": f.name, "type": f.ftype.__name__, "is_response": f.is_response}
            for f in model.raw_features
        ],
        # the RAW blacklist re-derives the whole DAG surgery at load
        # (cascaded drops are a deterministic function of it); without it
        # a fresh workflow still carries the pre-surgery stage count and
        # load cannot pair stages (reference: OpWorkflowModelWriter saves
        # blacklistedFeatures, reader reapplies setBlacklist)
        "blacklisted_raw": [
            f.name for f in model.blacklisted_features if f.is_raw()
        ],
        "parameters": _encode(model.parameters, arrays, "wf.params"),
        "train_time_s": model.train_time_s,
        "stages": stages_doc,
    }
    json_bytes = json.dumps(doc, indent=1, default=str).encode("utf-8")
    # the schema contract (schema/contract.py) rides INSIDE the same
    # crash-consistent artifact: serve-time drift enforcement must load
    # the exact data shape this model trained on, checksummed and
    # last-good-recoverable like every other artifact file
    contract = getattr(model, "schema_contract", None)
    schema_bytes = None
    if contract is not None:
        schema_bytes = json.dumps(
            contract.to_json(), indent=1, default=str
        ).encode("utf-8")

    # AOT-compiled XLA executables (local/fused_xla.py attaches the
    # cache to the model once an XLA-backed scorer compiles): persisted
    # as meta json + uint8-array npz, both in the manifest, so replica
    # warm-up deserializes binaries instead of re-tracing every bucket
    xla_cache = getattr(model, "xla_executable_cache", None)
    xla_meta_bytes = None
    xla_arrays = None
    if xla_cache is not None and getattr(xla_cache, "entries", None):
        xla_meta, xla_arrays = xla_cache.to_artifact()
        xla_meta_bytes = json.dumps(
            xla_meta, indent=1, sort_keys=True
        ).encode("utf-8")

    parent = os.path.dirname(path) or "."
    os.makedirs(parent, exist_ok=True)
    # reap tempdirs leaked by CRASHED saves: each holds a full artifact
    # copy.  Only dead writers' dirs are removed - a concurrent save by
    # a live process (retried fleet jobs sharing a path) must not have
    # its tempdir clobbered mid-write
    for stale in glob.glob(glob.escape(path) + ".tmp-*"):
        if os.path.isdir(stale) and not _writer_alive(stale):
            shutil.rmtree(stale, ignore_errors=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.isdir(tmp):  # same-pid leftover (pid reuse / prior error)
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    _write_fsync(os.path.join(tmp, MODEL_JSON), json_bytes)
    # crash drill: death here must leave the PREVIOUS artifact untouched
    # (the half-written tempdir is invisible to load_model)
    _faults.inject_kill("io.save_model.crash")
    npz_tmp = os.path.join(tmp, ARRAYS_NPZ)
    # stream the npz straight to disk (no whole-archive BytesIO), then
    # fsync it and checksum it back in bounded-memory chunks
    np.savez_compressed(npz_tmp, **arrays)
    with open(npz_tmp, "rb") as f:
        os.fsync(f.fileno())
    npz_sha, npz_size = _sha256_file(npz_tmp)
    manifest = {
        "format_version": 1,
        "files": {
            MODEL_JSON: {"sha256": _sha256(json_bytes),
                         "bytes": len(json_bytes)},
            ARRAYS_NPZ: {"sha256": npz_sha, "bytes": npz_size},
        },
    }
    if schema_bytes is not None:
        _write_fsync(os.path.join(tmp, SCHEMA_JSON), schema_bytes)
        manifest["files"][SCHEMA_JSON] = {
            "sha256": _sha256(schema_bytes), "bytes": len(schema_bytes),
        }
    if xla_meta_bytes is not None:
        _write_fsync(os.path.join(tmp, XLA_CACHE_JSON), xla_meta_bytes)
        manifest["files"][XLA_CACHE_JSON] = {
            "sha256": _sha256(xla_meta_bytes),
            "bytes": len(xla_meta_bytes),
        }
        xla_npz_tmp = os.path.join(tmp, XLA_CACHE_NPZ)
        np.savez_compressed(xla_npz_tmp, **xla_arrays)
        with open(xla_npz_tmp, "rb") as f:
            os.fsync(f.fileno())
        xla_sha, xla_size = _sha256_file(xla_npz_tmp)
        manifest["files"][XLA_CACHE_NPZ] = {
            "sha256": xla_sha, "bytes": xla_size,
        }
    _write_fsync(
        os.path.join(tmp, MANIFEST_JSON),
        json.dumps(manifest, indent=1, sort_keys=True).encode("utf-8"),
    )
    _fsync_dir(tmp)

    last_good = path + LAST_GOOD_SUFFIX
    try:
        if os.path.isdir(path):
            if os.path.isdir(last_good):
                shutil.rmtree(last_good)
            os.rename(path, last_good)
        # crash drill: death between the two renames leaves NO primary
        # artifact - load_model must recover from <path>.last-good
        _faults.inject_kill("io.save_model.crash_window")
        os.rename(tmp, path)
    except OSError as e:
        # rename(2) refuses to move a mount point (EBUSY) - e.g. a k8s/
        # docker volume mounted directly at the artifact path.  Publish
        # by copy instead: payload files first, manifest LAST, each via
        # a file-level atomic replace - a crash mid-publish leaves a
        # manifest that mismatches the new payload, which verification
        # detects and recovers from last-good
        _publish_by_copy(tmp, path, last_good, reason=str(e))
    else:
        # the swap moved the WHOLE old directory aside; co-located
        # non-artifact files (the runner's summary.json, user-kept eval
        # reports) must survive the re-save, not vanish into last-good
        _carry_extras(last_good, path)
    _fsync_dir(parent)


_ARTIFACT_FILES = frozenset(
    (MODEL_JSON, ARRAYS_NPZ, MANIFEST_JSON, SCHEMA_JSON,
     XLA_CACHE_JSON, XLA_CACHE_NPZ)
)

#: artifact files that are OPTIONAL per model: absent from the new save,
#: a stale copy from the replaced artifact must not survive a
#: publish-by-copy to masquerade as this model's
_OPTIONAL_ARTIFACT_FILES = (SCHEMA_JSON, XLA_CACHE_JSON, XLA_CACHE_NPZ)


def _carry_extras(old_dir: str, new_dir: str) -> None:
    """Copy non-artifact entries the previous save directory carried
    into the freshly published one (best-effort: extras must never fail
    a completed save)."""
    if not os.path.isdir(old_dir):
        return
    for name in os.listdir(old_dir):
        if name in _ARTIFACT_FILES:
            continue
        src = os.path.join(old_dir, name)
        dst = os.path.join(new_dir, name)
        if os.path.exists(dst):
            continue
        try:
            if os.path.isdir(src):
                shutil.copytree(src, dst)
            else:
                shutil.copy2(src, dst)
        except OSError as e:
            log.warning("could not carry %s into the new artifact: %s",
                        src, e)


def _publish_by_copy(tmp: str, path: str, last_good: str,
                     reason: str) -> None:
    log.warning(
        "atomic artifact swap unavailable for %s (%s); publishing by "
        "file copy - still crash-detectable via the manifest", path, reason,
    )
    if os.path.isdir(path) and verify_artifact(path) is None:
        if os.path.isdir(last_good):
            shutil.rmtree(last_good)
        try:
            shutil.copytree(path, last_good)
        except OSError:
            log.warning("could not snapshot %s to %s; continuing without "
                        "a last-good copy", path, last_good)
    os.makedirs(path, exist_ok=True)
    # payload before manifest: until the manifest flips, verification
    # sees old-manifest-vs-new-payload and rejects the half-published dir
    for name in (MODEL_JSON, ARRAYS_NPZ, SCHEMA_JSON, XLA_CACHE_JSON,
                 XLA_CACHE_NPZ, MANIFEST_JSON):
        src = os.path.join(tmp, name)
        if name in _OPTIONAL_ARTIFACT_FILES and not os.path.exists(src):
            # contract-less / cache-less model: a STALE optional file
            # from the replaced artifact must not survive to masquerade
            # as this model's
            stale = os.path.join(path, name)
            if os.path.exists(stale):
                os.remove(stale)
            continue
        part = os.path.join(path, name + ".part")
        with open(src, "rb") as fsrc, open(part, "wb") as fdst:
            shutil.copyfileobj(fsrc, fdst, _HASH_CHUNK)
            fdst.flush()
            os.fsync(fdst.fileno())
        os.replace(part, os.path.join(path, name))
    _fsync_dir(path)
    shutil.rmtree(tmp, ignore_errors=True)


def verify_artifact(path: str) -> Optional[str]:
    """Checksum-verify a saved artifact against its manifest; returns
    None when intact, else a human-readable description of the damage.
    A manifest-less directory with both payload files is accepted as a
    legacy (pre-manifest) artifact."""
    if not os.path.isdir(path):
        return f"artifact directory {path} missing"
    manifest_path = os.path.join(path, MANIFEST_JSON)
    if not os.path.exists(manifest_path):
        missing = [
            f for f in (MODEL_JSON, ARRAYS_NPZ)
            if not os.path.exists(os.path.join(path, f))
        ]
        if missing:
            return f"artifact {path} incomplete: missing {missing}"
        log.warning(
            "model artifact %s has no %s (legacy save): loading without "
            "checksum verification", path, MANIFEST_JSON,
        )
        return None
    try:
        with open(manifest_path, "rb") as f:
            manifest = json.loads(f.read().decode("utf-8"))
        files = manifest["files"]
    except (OSError, ValueError, KeyError, UnicodeDecodeError) as e:
        return f"manifest {manifest_path} unreadable: {e}"
    for name, meta in files.items():
        fpath = os.path.join(path, name)
        try:
            sha, size = _sha256_file(fpath)
        except OSError as e:
            return f"artifact file {fpath} unreadable: {e}"
        if size != meta.get("bytes"):
            return (
                f"artifact file {fpath} truncated: {size} bytes, "
                f"manifest records {meta.get('bytes')}"
            )
        if sha != meta.get("sha256"):
            return (
                f"artifact file {fpath} failed its SHA-256 checksum "
                "(bit-flip or partial overwrite)"
            )
    return None


def resolve_artifact(path: str) -> str:
    """Return a checksum-verified artifact directory for ``path``:
    the primary when intact, else the ``.last-good`` predecessor (a
    crash mid-save, see save_model).  Raises ModelIntegrityError when
    neither verifies."""
    path = os.path.abspath(path).rstrip(os.sep)
    err = verify_artifact(path)
    if err is None:
        return path
    last_good = path + LAST_GOOD_SUFFIX
    lg_err = verify_artifact(last_good)
    if lg_err is None:
        log.warning(
            "model artifact failed verification (%s); recovering from "
            "last-good artifact %s", err, last_good,
        )
        return last_good
    raise ModelIntegrityError(
        f"{err}; last-good recovery also failed ({lg_err})"
    )


def _load_class(qualname: str):
    module, _, name = qualname.rpartition(".")
    mod = importlib.import_module(module)
    obj = mod
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def load_model(path: str, workflow):
    """Restore into the code-defined workflow (reference contract:
    OpWorkflow.loadModel)."""
    from ..workflow.dag import compute_dag, flatten
    from ..workflow.workflow import OpWorkflowModel

    path = resolve_artifact(path)
    json_path = os.path.join(path, MODEL_JSON)
    npz_path = os.path.join(path, ARRAYS_NPZ)
    try:
        with open(json_path) as f:
            doc = json.load(f)
    except ValueError as e:
        raise ModelLoadError(f"model artifact {json_path} is not valid "
                             f"JSON: {e}") from e
    try:
        arrays = _ArrayStore(np.load(npz_path, allow_pickle=False), npz_path)
    except (OSError, ValueError, zipfile.BadZipFile, zlib.error) as e:
        raise ModelLoadError(
            f"model artifact {npz_path} is not a readable npz: {e}"
        ) from e

    # reapply the saved blacklist surgery to the fresh workflow so its
    # DAG matches the trained one (cascades re-derive deterministically).
    # A workflow whose stage graph was ALREADY surgered differently
    # cannot be reconciled - re-running surgery on mutated stages would
    # produce a DAG matching neither side - so mismatches reject loudly.
    bl_names = set(doc.get("blacklisted_raw", ()))
    already = {f.name for f in workflow.blacklisted_features if f.is_raw()}
    if bl_names != already:
        if already:
            raise ValueError(
                "target workflow already carries a different blacklist "
                f"({sorted(already)}) than the saved model "
                f"({sorted(bl_names)}); load needs a freshly built "
                "workflow"
            )
        by_name = {f.name: f for f in workflow.raw_features}
        missing = bl_names - set(by_name)
        if missing:
            raise ValueError(
                f"saved model blacklists raw features {sorted(missing)} "
                "absent from the target workflow"
            )
        workflow.blacklisted_features = [by_name[n] for n in sorted(bl_names)]
        workflow._apply_blacklist()

    dag = compute_dag(workflow.result_features)
    dag_stages = flatten(dag)
    if len(dag_stages) != len(doc["stages"]):
        raise ValueError(
            f"workflow has {len(dag_stages)} stages but saved model has "
            f"{len(doc['stages'])}; load requires the same code-defined workflow"
        )

    fitted = []
    for stage_def, saved in zip(dag_stages, doc["stages"]):
        cls = _load_class(saved["class"])
        # stages pair positionally with the code-defined workflow; estimators
        # save their fitted-model class, so accept either an exact class match
        # or estimator->model pairs (both carry the estimator's operation_name)
        if (
            type(stage_def).__name__ != cls.__name__
            and stage_def.operation_name != saved["operation_name"]
        ):
            raise ValueError(
                f"saved stage {saved['class']} does not match workflow stage "
                f"{type(stage_def).__name__} at the same DAG position; load "
                "requires the same code-defined workflow"
            )
        inst = cls.__new__(cls)
        # baseline attrs from the (unfitted) DAG stage, then saved state
        inst.__dict__.update(
            {
                k: v
                for k, v in vars(stage_def).items()
                if k not in ("params", "metadata")
            }
        )
        # adopt the TARGET workflow's uid so DAG substitution by uid works
        # regardless of where the fresh build's uid counters start
        inst.uid = stage_def.uid
        inst.operation_name = saved["operation_name"]
        inst.params = _decode(saved["params"], arrays)
        inst.metadata = _decode(saved["metadata"], arrays)
        for k, v in _decode(saved["state"], arrays).items():
            setattr(inst, k, v)
        if "estimator" in saved:
            est_cls = _load_class(saved["estimator"]["class"])
            est = est_cls()
            est.params = _decode(saved["estimator"]["params"], arrays)
            inst.estimator_ref = est
        inst.input_features = stage_def.input_features
        inst._output = stage_def._output if stage_def._output else None
        # fitted stage replaces the estimator: same output feature
        stage_def._output = stage_def.get_output()
        inst._output = stage_def._output
        fitted.append(inst)

    model = OpWorkflowModel(
        result_features=workflow.result_features,
        raw_features=workflow.raw_features,
        stages=fitted,
        parameters=_decode(doc["parameters"], arrays),
        train_time_s=doc.get("train_time_s", 0.0),
        blacklisted_features=workflow.blacklisted_features,
    )
    # schema contract (optional: pre-contract artifacts have none) - the
    # serve tier's drift guards need the trained data shape; checksummed
    # via the manifest, so corruption was already caught above
    schema_path = os.path.join(path, SCHEMA_JSON)
    if os.path.exists(schema_path):
        from ..schema.contract import SchemaContract

        try:
            with open(schema_path) as f:
                model.schema_contract = SchemaContract.from_json(
                    json.load(f)
                )
        except (ValueError, KeyError, TypeError) as e:
            raise ModelLoadError(
                f"model artifact {schema_path} is not a valid schema "
                f"contract: {e}"
            ) from e
    # AOT-compiled XLA executable cache (optional; local/fused_xla.py):
    # re-attached so an XLA-backed endpoint warm-up deserializes the
    # per-bucket binaries instead of re-tracing.  Best-effort: a cache
    # that cannot be read never fails the model load - the scorer just
    # retraces (and recaches) as if the artifact carried none.
    xla_meta_path = os.path.join(path, XLA_CACHE_JSON)
    xla_npz_path = os.path.join(path, XLA_CACHE_NPZ)
    if os.path.exists(xla_meta_path) and os.path.exists(xla_npz_path):
        # deferred import: model_io loads during workflow import, and
        # local/ imports workflow back - module scope would be circular
        from ..local.fused_xla import XlaExecutableCache

        try:
            with open(xla_meta_path) as f:
                xla_meta = json.load(f)
            with np.load(xla_npz_path, allow_pickle=False) as blobs:
                model.xla_executable_cache = (
                    XlaExecutableCache.from_artifact(xla_meta, blobs)
                )
        except (OSError, ValueError, KeyError, TypeError,
                zipfile.BadZipFile, zlib.error) as e:
            log.warning(
                "model artifact %s has an unreadable xla executable "
                "cache (%s); serving will re-trace", xla_meta_path, e,
            )
    return model
