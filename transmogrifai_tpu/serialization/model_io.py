"""Workflow model persistence.

Counterpart of OpWorkflowModelWriter / OpWorkflowModelReader (reference:
core/.../OpWorkflowModelWriter.scala:52-140, OpWorkflowModelReader.scala):
the whole fitted workflow saves as one JSON document (stage classes, params,
metadata, result-feature names) plus an .npz of every array-valued piece of
fitted state.  Loading mirrors the reference's contract: the model is
restored INTO the same code-defined workflow (OpWorkflow.loadModel,
OpWorkflow.scala:468) - stages are re-paired with the freshly built DAG in
deterministic order, so feature wiring never needs serializing.
"""
from __future__ import annotations

import importlib
import json
import os
from typing import Any

import numpy as np

MODEL_JSON = "model.json"
ARRAYS_NPZ = "arrays.npz"


def _encode(value: Any, arrays: dict[str, np.ndarray], path: str) -> Any:
    if isinstance(value, np.ndarray):
        arrays[path] = value
        return {"__npz__": path}
    if isinstance(value, (np.floating, np.integer)):
        return value.item()
    if isinstance(value, dict):
        return {
            "__dict__": {
                k: _encode(v, arrays, f"{path}.{k}") for k, v in value.items()
            }
        }
    if isinstance(value, (list, tuple)):
        enc = [_encode(v, arrays, f"{path}[{i}]") for i, v in enumerate(value)]
        return {"__list__": enc, "__tuple__": isinstance(value, tuple)}
    if isinstance(value, (set, frozenset)):
        try:
            items = sorted(value)
        except TypeError:
            items = list(value)
        enc = [_encode(v, arrays, f"{path}{{{i}}}") for i, v in enumerate(items)]
        return {"__set__": enc, "__frozen__": isinstance(value, frozenset)}
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    raise TypeError(
        f"cannot serialize {type(value).__name__} at {path}; stages must keep "
        "fitted state as arrays/scalars/dicts/lists"
    )


def _decode(value: Any, arrays) -> Any:
    if isinstance(value, dict):
        if "__npz__" in value:
            return arrays[value["__npz__"]]
        if "__dict__" in value:
            return {k: _decode(v, arrays) for k, v in value["__dict__"].items()}
        if "__list__" in value:
            items = [_decode(v, arrays) for v in value["__list__"]]
            return tuple(items) if value.get("__tuple__") else items
        if "__set__" in value:
            items = [_decode(v, arrays) for v in value["__set__"]]
            return frozenset(items) if value.get("__frozen__") else set(items)
    return value


# attributes owned by the stage machinery, not fitted state
_SKIP_ATTRS = {
    "input_features", "_output", "uid", "operation_name", "params",
    "metadata", "estimator_ref", "selector", "validator", "models",
    "splitter", "evaluators", "validation_result", "fn", "predicate",
    "model", "output_type", "input_types", "prefer_numpy",
    # per-process transform memoizations (vectorizer_base/combiner/
    # sanity_checker): identity-keyed, must never persist
    "_meta_cache", "_combine_cache", "_select_cache",
    "_metas_memo", "_pivot_helpers",
}


def stage_state(stage) -> dict[str, Any]:
    out = {}
    for k, v in vars(stage).items():
        if k in _SKIP_ATTRS:
            continue
        out[k] = v
    return out


def save_model(model, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    stages_doc = []
    for i, stage in enumerate(model.stages):
        cls = type(stage)
        doc: dict[str, Any] = {
            "index": i,
            "class": f"{cls.__module__}.{cls.__qualname__}",
            "uid": stage.uid,
            "operation_name": stage.operation_name,
            "output_name": stage.output_name,
            "params": _encode(stage.params, arrays, f"s{i}.params"),
            "metadata": _encode(stage.metadata, arrays, f"s{i}.metadata"),
            "state": _encode(stage_state(stage), arrays, f"s{i}.state"),
        }
        if hasattr(stage, "estimator_ref"):
            est = stage.estimator_ref
            doc["estimator"] = {
                "class": f"{type(est).__module__}.{type(est).__qualname__}",
                "params": _encode(est.params, arrays, f"s{i}.est_params"),
            }
        stages_doc.append(doc)
    doc = {
        "format_version": 1,
        "result_features": [f.name for f in model.result_features],
        "raw_features": [
            {"name": f.name, "type": f.ftype.__name__, "is_response": f.is_response}
            for f in model.raw_features
        ],
        # the RAW blacklist re-derives the whole DAG surgery at load
        # (cascaded drops are a deterministic function of it); without it
        # a fresh workflow still carries the pre-surgery stage count and
        # load cannot pair stages (reference: OpWorkflowModelWriter saves
        # blacklistedFeatures, reader reapplies setBlacklist)
        "blacklisted_raw": [
            f.name for f in model.blacklisted_features if f.is_raw()
        ],
        "parameters": _encode(model.parameters, arrays, "wf.params"),
        "train_time_s": model.train_time_s,
        "stages": stages_doc,
    }
    with open(os.path.join(path, MODEL_JSON), "w") as f:
        json.dump(doc, f, indent=1, default=str)
    np.savez_compressed(os.path.join(path, ARRAYS_NPZ), **arrays)


def _load_class(qualname: str):
    module, _, name = qualname.rpartition(".")
    mod = importlib.import_module(module)
    obj = mod
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def load_model(path: str, workflow):
    """Restore into the code-defined workflow (reference contract:
    OpWorkflow.loadModel)."""
    from ..workflow.dag import compute_dag, flatten
    from ..workflow.workflow import OpWorkflowModel

    with open(os.path.join(path, MODEL_JSON)) as f:
        doc = json.load(f)
    arrays = np.load(os.path.join(path, ARRAYS_NPZ), allow_pickle=False)

    # reapply the saved blacklist surgery to the fresh workflow so its
    # DAG matches the trained one (cascades re-derive deterministically).
    # A workflow whose stage graph was ALREADY surgered differently
    # cannot be reconciled - re-running surgery on mutated stages would
    # produce a DAG matching neither side - so mismatches reject loudly.
    bl_names = set(doc.get("blacklisted_raw", ()))
    already = {f.name for f in workflow.blacklisted_features if f.is_raw()}
    if bl_names != already:
        if already:
            raise ValueError(
                "target workflow already carries a different blacklist "
                f"({sorted(already)}) than the saved model "
                f"({sorted(bl_names)}); load needs a freshly built "
                "workflow"
            )
        by_name = {f.name: f for f in workflow.raw_features}
        missing = bl_names - set(by_name)
        if missing:
            raise ValueError(
                f"saved model blacklists raw features {sorted(missing)} "
                "absent from the target workflow"
            )
        workflow.blacklisted_features = [by_name[n] for n in sorted(bl_names)]
        workflow._apply_blacklist()

    dag = compute_dag(workflow.result_features)
    dag_stages = flatten(dag)
    if len(dag_stages) != len(doc["stages"]):
        raise ValueError(
            f"workflow has {len(dag_stages)} stages but saved model has "
            f"{len(doc['stages'])}; load requires the same code-defined workflow"
        )

    fitted = []
    for stage_def, saved in zip(dag_stages, doc["stages"]):
        cls = _load_class(saved["class"])
        # stages pair positionally with the code-defined workflow; estimators
        # save their fitted-model class, so accept either an exact class match
        # or estimator->model pairs (both carry the estimator's operation_name)
        if (
            type(stage_def).__name__ != cls.__name__
            and stage_def.operation_name != saved["operation_name"]
        ):
            raise ValueError(
                f"saved stage {saved['class']} does not match workflow stage "
                f"{type(stage_def).__name__} at the same DAG position; load "
                "requires the same code-defined workflow"
            )
        inst = cls.__new__(cls)
        # baseline attrs from the (unfitted) DAG stage, then saved state
        inst.__dict__.update(
            {
                k: v
                for k, v in vars(stage_def).items()
                if k not in ("params", "metadata")
            }
        )
        # adopt the TARGET workflow's uid so DAG substitution by uid works
        # regardless of where the fresh build's uid counters start
        inst.uid = stage_def.uid
        inst.operation_name = saved["operation_name"]
        inst.params = _decode(saved["params"], arrays)
        inst.metadata = _decode(saved["metadata"], arrays)
        for k, v in _decode(saved["state"], arrays).items():
            setattr(inst, k, v)
        if "estimator" in saved:
            est_cls = _load_class(saved["estimator"]["class"])
            est = est_cls()
            est.params = _decode(saved["estimator"]["params"], arrays)
            inst.estimator_ref = est
        inst.input_features = stage_def.input_features
        inst._output = stage_def._output if stage_def._output else None
        # fitted stage replaces the estimator: same output feature
        stage_def._output = stage_def.get_output()
        inst._output = stage_def._output
        fitted.append(inst)

    model = OpWorkflowModel(
        result_features=workflow.result_features,
        raw_features=workflow.raw_features,
        stages=fitted,
        parameters=_decode(doc["parameters"], arrays),
        train_time_s=doc.get("train_time_s", 0.0),
        blacklisted_features=workflow.blacklisted_features,
    )
    return model
