"""Model persistence (crash-consistent; see model_io.py)."""
from .model_io import (
    ARRAYS_NPZ,
    LAST_GOOD_SUFFIX,
    MANIFEST_JSON,
    MODEL_JSON,
    ModelIntegrityError,
    ModelLoadError,
    load_model,
    resolve_artifact,
    save_model,
    verify_artifact,
)

__all__ = [
    "ARRAYS_NPZ",
    "LAST_GOOD_SUFFIX",
    "MANIFEST_JSON",
    "MODEL_JSON",
    "ModelIntegrityError",
    "ModelLoadError",
    "load_model",
    "resolve_artifact",
    "save_model",
    "verify_artifact",
]
