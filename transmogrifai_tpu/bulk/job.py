"""Exactly-once bulk scoring: pipelined reader -> fused programs ->
journaled output shards.

The "score a billion rows overnight" run type (ROADMAP item 4): stream
sharded input files through :class:`readers.pipeline.InputPipeline`
STRAIGHT into the PR-12 fused programs - the decoded columnar chunks
feed :meth:`score_env` directly, skipping per-record dict building,
admission control and the micro-batcher (serving machinery is pure
overhead under a throughput-bound load) - and write one exactly-ordered
output shard per input shard under the :class:`~.journal.BulkJournal`.
Each shard commits ``assigned -> scored -> committed`` durably, with the
output shard fsynced and checksummed BEFORE the ``scored`` record, so a
SIGKILL at any instant costs at most the shards in flight: resume rolls
committed/verified work forward and re-scores only what the checksums
reject.  Quarantined rows are double-entry accounted per shard
(``rows_in == rows_out + rows_quarantined`` exactly) and globally.

Fleet mode (``router=``) fans chunk batches across replicas over the
PR-17 TCP channels: the router's at-least-once failover plus the
``ReplicaHealth`` detector reassign work when a replica dies mid-shard
(``bulk.replica_die_midshard`` drill), while the journal's
commit-after-durable-write discipline keeps the OUTPUT exactly-once.
``router=`` accepts a :class:`~..fleet.FleetRouter`, a
:class:`~..fleet.FleetController`, or a zero-arg callable returning
either; the job RE-RESOLVES it at every shard boundary so an elastic
fleet (ISSUE 19) growing or shrinking mid-job fans the next shard out
to the CURRENT membership, never a stale snapshot.  On a multi-model
fleet (ISSUE 20) ``model_id=`` pins every batch to ONE hosted model:
the router dispatches only to replicas hosting it and raises
``UnhostedModelError`` loudly - at job start and again mid-job if
hosting vanishes - rather than silently scoring with whatever model a
replica has; the exactly-once ledger discipline is unchanged.

Fault points: ``bulk.output_crash`` kills the job between the durable
output-shard write and its journal commit - the canonical "did the
work, lost the receipt" window a resume must re-score.
"""
from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from functools import lru_cache
from typing import Any, Optional, Sequence

import numpy as np

from ..faults import injection as _faults
from ..obs import trace as _obs_trace
from ..obs.metrics import metrics_registry
from ..readers.pipeline import (
    DEFAULT_CHUNK_ROWS,
    DEFAULT_WORKERS,
    InputPipeline,
    ShardSpec,
    shard as plan_shards,
)
from ..stages.base import MASK_SUFFIX
from .journal import (
    STATE_ASSIGNED,
    STATE_COMMITTED,
    STATE_PENDING,
    STATE_SCORED,
    BulkJournal,
)

#: raw-feature kinds a pipelined CsvChunk can carry columnar
_CHUNK_KINDS = ("numeric", "text")


def _env_from_chunk(chunk, features) -> dict[str, Any]:
    """Build the fused decode env STRAIGHT from a decoded columnar
    chunk - the assemble_columns missing-value rule (present NaN is
    missing) so the direct feed is bit-identical to scoring the
    assembled Dataset's records through ``decode_env``."""
    env: dict[str, Any] = {}
    for f in features:
        if f.ftype.kind == "numeric":
            vals, mask = chunk.numeric[f.name]
            vals = np.asarray(vals, dtype=np.float64)
            mask = np.asarray(mask, dtype=bool)
            nan = np.isnan(vals)
            if nan.any():
                vals = np.where(nan, 0.0, vals)
                mask = mask & ~nan
            env[f.name] = vals
            env[f.name + MASK_SUFFIX] = mask
        else:
            env[f.name] = np.asarray(chunk.text[f.name], dtype=object)
    return env


def _records_from_chunk(chunk, features) -> list[dict[str, Any]]:
    """Chunk columns -> per-row record dicts (the fleet wire format and
    the interpreted-scorer fallback)."""
    cols = []
    for f in features:
        if f.ftype.kind == "numeric":
            vals, mask = chunk.numeric[f.name]
            vals = np.asarray(vals, dtype=np.float64)
            mask = np.asarray(mask, dtype=bool) & ~np.isnan(vals)
            cols.append((f.name, [
                float(v) if m else None
                for v, m in zip(vals.tolist(), mask.tolist())
            ]))
        else:
            cols.append((f.name, list(chunk.text[f.name])))
    names = [n for n, _ in cols]
    return [dict(zip(names, row)) for row in zip(*(c for _, c in cols))]


def _result_lines(rows: Sequence[Any]) -> list[bytes]:
    """Deterministic one-line-per-row JSON encoding of scored rows."""
    out = []
    for r in rows:
        if not isinstance(r, dict):
            r = {"error": getattr(r, "error", str(r))}
        out.append(json.dumps(r, sort_keys=True,
                              separators=(",", ":"),
                              default=str).encode("utf-8") + b"\n")
    return out


@lru_cache(maxsize=64)
def _prediction_fmt(name: str, keys: tuple) -> tuple:
    """(%-format template for ONE output line, sorted column order) of
    the single-Prediction result shape: the template emits the SAME
    bytes json.dumps(sort_keys, separators) produces for the assembled
    row dict (%r of a finite float IS its json spelling)."""
    order = tuple(sorted(range(len(keys)), key=lambda i: keys[i]))
    esc = lambda s: s.replace("%", "%%")  # noqa: E731
    fmt = (
        esc("{%s:{" % json.dumps(name))
        + "".join(
            esc(("" if i == 0 else ",") + json.dumps(keys[j]) + ":") + "%r"
            for i, j in enumerate(order))
        + "}}\n"
    )
    return fmt, order


def _result_lines_from_prediction(name: str, keys: Sequence[str],
                                  stacked, bad_rows: Sequence[int],
                                  ) -> list[bytes]:
    """Vectorized line encoding of the single-Prediction result shape:
    one %-format pass per row over the stacked [n, k] array.
    Non-finite rows - whose floats json spells NaN/Infinity, not
    nan/inf - are patched through json.dumps afterwards."""
    fmt, order = _prediction_fmt(name, tuple(keys))
    cols = [stacked[:, j].tolist() for j in order]
    out = [(fmt % row).encode("utf-8") for row in zip(*cols)]
    for i in bad_rows:
        row = {name: dict(zip(keys, stacked[i].tolist()))}
        out[i] = json.dumps(row, sort_keys=True, separators=(",", ":"),
                            default=str).encode("utf-8") + b"\n"
    return out


#: rows per %-format call in the blob encoder: big enough to amortise
#: the format-call overhead, small enough that the flattened value
#: tuple stays cache-friendly
_ENC_BATCH = 256


def _result_blob_from_prediction(name: str, keys: Sequence[str],
                                 stacked, bad_rows: Sequence[int],
                                 ) -> bytes:
    """The whole chunk's output bytes in ONE pass: `_ENC_BATCH` rows
    per %-format call over the row-major flattened value list, joined
    and utf-8-encoded once.  Chunks with non-finite rows (rare: the
    fallback spelling differs per row) take the per-row path."""
    if bad_rows:
        return b"".join(
            _result_lines_from_prediction(name, keys, stacked, bad_rows))
    fmt, order = _prediction_fmt(name, tuple(keys))
    k = len(order)
    n = stacked.shape[0]
    flat = stacked[:, order].ravel().tolist()
    pieces = []
    nb = (n // _ENC_BATCH) * _ENC_BATCH
    if nb:
        fmt_b = fmt * _ENC_BATCH
        step = _ENC_BATCH * k
        for i in range(0, nb * k, step):
            pieces.append(fmt_b % tuple(flat[i:i + step]))
    for i in range(nb * k, n * k, k):
        pieces.append(fmt % tuple(flat[i:i + k]))
    return "".join(pieces).encode("utf-8")


class _ShardWriter:
    """ONE background thread executing the job's journal transitions
    and durable output writes in EXACT submission order - a write-
    ahead queue.  Scoring never stalls on an fsync, while the on-disk
    journal/output sequence (and therefore the fault-point walk the
    kill drills pin) stays byte-for-byte the serial one.  Tasks run
    under a copy of the submitter's context so commit spans parent to
    the ambient ``bulk.run`` span.  Bulky (output-data) submissions
    are bounded to ``max_queued_writes`` in flight so a slow disk
    backpressures scoring instead of buffering every shard in RAM."""

    def __init__(self, max_queued_writes: int = 2) -> None:
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="bulk-writer")
        self._futures: list[Any] = []
        self._sem = threading.Semaphore(max_queued_writes)

    def submit(self, fn, *args) -> None:
        ctx = contextvars.copy_context()
        self._futures.append(self._pool.submit(ctx.run, fn, *args))

    def submit_bulky(self, fn, *args) -> None:
        self._sem.acquire()
        ctx = contextvars.copy_context()

        def run() -> None:
            try:
                ctx.run(fn, *args)
            finally:
                self._sem.release()

        self._futures.append(self._pool.submit(run))

    def check(self) -> None:
        """Re-raise the first failure of any finished task (so a dead
        disk aborts the run instead of scoring every remaining
        shard)."""
        for f in self._futures:
            if f.done():
                f.result()

    def close(self) -> None:
        """Drain the queue, then re-raise the first task failure."""
        self._pool.shutdown(wait=True)
        for f in self._futures:
            f.result()


class BulkScoringJob:
    """One checkpointed, kill-survivable batch-inference job.

    ``run()`` either plans a fresh job (journal created from
    ``inputs``) or resumes the journal already in ``job_dir``:
    committed shards whose output passes its checksum are skipped
    entirely, ``scored`` shards with a verified output roll forward to
    ``committed`` without re-scoring, and everything else (including a
    partially written or checksum-rejected output) is re-scored.
    """

    def __init__(
        self,
        model,
        job_dir: str,
        inputs: Optional[Sequence[str]] = None,
        *,
        fmt: Optional[str] = None,
        errors: str = "quarantine",
        chunk_rows: int = DEFAULT_CHUNK_ROWS,
        workers: int = DEFAULT_WORKERS,
        buffer_chunks: int = 8,
        fused_backend: Optional[str] = None,
        use_native: bool = True,
        router=None,
        model_id: Optional[str] = None,
        batch_timeout_s: float = 120.0,
        max_in_flight: int = 8,
        instance: Optional[str] = None,
    ) -> None:
        self.model = model
        self.job_dir = str(job_dir)
        self.inputs = [str(p) for p in inputs] if inputs else None
        self.fmt = fmt
        self.errors = errors
        self.chunk_rows = int(chunk_rows)
        self.workers = int(workers)
        self.buffer_chunks = int(buffer_chunks)
        self.fused_backend = fused_backend
        self.use_native = use_native
        #: what the caller handed us (router / controller / callable);
        #: ``self.router`` is the CURRENT resolution, refreshed at
        #: every shard boundary (elastic fleets change membership
        #: mid-job)
        self._router_source = router
        self.router = self._resolve_router()
        self.model_id = str(model_id) if model_id else None
        if self.model_id and self.router is None:
            raise ValueError(
                "model_id= selects a hosted model on a multi-model "
                "fleet; it requires router= (local scoring has exactly "
                "one model: the one passed in)")
        self.batch_timeout_s = float(batch_timeout_s)
        self.max_in_flight = max(int(max_in_flight), 1)
        self.instance = str(instance) if instance else (
            f"bulk-{os.getpid()}")
        self.journal: Optional[BulkJournal] = None
        #: live telemetry the ``bulk`` metrics view snapshots
        self._rows_out = 0
        self._rows_quarantined = 0
        self._rows_per_s = 0.0
        self._shards_committed_this_run = 0
        self._view_idx = metrics_registry().register_view("bulk", self)
        # build the direct scoring path once per job: fused numpy/XLA
        # via the scorer's own backend-degradation chain
        from ..local.scorer import LocalScorer

        self.scorer = LocalScorer(
            model, fused=True,
            **({"fused_backend": fused_backend} if fused_backend else {}),
        )
        self._features = [f for f in self.scorer.raw_features
                          if not f.is_response]
        bad = [f.name for f in self._features
               if f.ftype.kind not in _CHUNK_KINDS]
        if bad:
            raise ValueError(
                f"bulk scoring reads columnar shards (numeric/text "
                f"features); {bad} cannot ride the pipelined chunk path"
            )
        self._schema = {f.name: f.ftype for f in self._features}
        self._wanted = [f.name for f in self._features]

    # -- metrics view --------------------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        """The ``tx_bulk_*`` gauge surface riding the obs scrape."""
        j = self.journal
        states = j.states() if j is not None else {}
        resumes = j.doc.get("resumes", []) if j is not None else []
        return {
            "shards_total": j.doc.get("n_shards", 0) if j else 0,
            "shards_committed": states.get(STATE_COMMITTED, 0),
            "shards_pending": states.get(STATE_PENDING, 0),
            "rows_out": self._rows_out,
            "rows_quarantined": self._rows_quarantined,
            "rows_per_s": round(self._rows_per_s, 1),
            "resume_count": len(resumes),
            "rescored_shards": sum(
                len(r.get("rescored_shards", [])) for r in resumes),
        }

    # -- planning / recovery -------------------------------------------------
    def _check_inputs(self, j: BulkJournal) -> None:
        if self.inputs:
            recorded = [j.shard(s)["path"] for s in j.shard_ids()]
            if recorded != self.inputs:
                raise ValueError(
                    f"{self.job_dir} already journals a different "
                    f"input set ({len(recorded)} shards); refusing "
                    f"to mix jobs in one directory"
                )

    def _create_journal(self) -> BulkJournal:
        if not self.inputs:
            raise ValueError(
                f"no journal under {self.job_dir} and no inputs given")
        specs = plan_shards(self.inputs, fmt=self.fmt)
        return BulkJournal.create(
            self.job_dir,
            [(s.path, s.fmt) for s in specs],
            trace_context=_obs_trace.current_context(),
            params={
                "errors": self.errors,
                "chunk_rows": self.chunk_rows,
                "workers": self.workers,
                "mode": "fleet" if self.router is not None else "local",
                "model_id": self.model_id,
            },
        )

    def _recover(self, j: BulkJournal) -> tuple[dict[str, str], list[int]]:
        """Resume triage: roll verified work forward, reset the rest.
        Mutations are in-memory; the caller's ``record_resume`` makes
        them durable in ONE commit."""
        recovered: dict[str, str] = {}
        rescored: list[int] = []
        for sid in j.shard_ids():
            rec = j.shard(sid)
            state = rec["state"]
            if state == STATE_COMMITTED:
                if not j.verify_output(sid):
                    # committed but the bytes on disk are not the bytes
                    # the journal checksummed - re-score, loudly
                    recovered[str(sid)] = state
                    rescored.append(sid)
                    j.reset_shard(sid)
            elif state == STATE_SCORED:
                recovered[str(sid)] = state
                if j.verify_output(sid):
                    # output durable + verified: the kill landed between
                    # the scored and committed records - roll forward
                    rec["state"] = STATE_COMMITTED
                else:
                    rescored.append(sid)
                    j.reset_shard(sid)
            elif state == STATE_ASSIGNED:
                recovered[str(sid)] = state
                # scoring was in flight; any bytes on disk (a complete
                # write whose receipt never landed, or a torn partial)
                # are untrusted and re-scored
                if os.path.exists(j.output_path(sid)):
                    rescored.append(sid)
                j.reset_shard(sid)
        return recovered, rescored

    # -- the run -------------------------------------------------------------
    def run(self) -> dict[str, Any]:
        """Plan or resume, score every uncommitted shard, return the
        job summary (ledger, resume history, throughput)."""
        t0 = time.perf_counter()
        resuming = BulkJournal.exists(self.job_dir)
        j = BulkJournal.load(self.job_dir) if resuming else None
        if j is not None:
            self._check_inputs(j)
            # adopt the planning process's trace id BEFORE the run span
            # opens: plan -> score -> commit -> resume is ONE trace
            _obs_trace.tracer().adopt_context(j.doc.get("trace_context"))
        with _obs_trace.span("bulk.run", job_dir=self.job_dir,
                             resume=resuming,
                             mode="fleet" if self.router else "local"):
            if j is None:
                j = self._create_journal()
            self.journal = j
            if resuming:
                with _obs_trace.span("bulk.resume"):
                    recovered, rescored = self._recover(j)
                    j.record_resume(os.getpid(), self.instance,
                                    recovered, rescored)
            todo = j.uncommitted()
            if todo:
                self._check_model_hosted()
                self._score_shards(j, todo)
            wall = time.perf_counter() - t0
            led = j.ledger()
            self._rows_per_s = (led["rows_out"] / wall) if wall > 0 else 0.0
            return {
                "job_dir": self.job_dir,
                "resumed": resuming,
                "shards": j.doc["n_shards"],
                "shards_scored_this_run": len(todo),
                "ledger": led,
                "resumes": list(j.doc.get("resumes", [])),
                "wall_s": round(wall, 3),
                "rows_per_s": round(self._rows_per_s, 1),
                "scorer_backend": self.scorer.fused_backend,
            }

    def _score_shards(self, j: BulkJournal, todo: list[int]) -> None:
        """Stream the uncommitted shards through ONE InputPipeline.

        Shards are renumbered positionally for the pipeline (its
        ordered cursor walks 0..k-1) and mapped back to journal ids.
        ``ordered=True`` guarantees a chunk of pipeline-shard k+1 only
        arrives after shard k fully parsed (stats + quarantine final),
        so each shard finalizes - durable output write, ``scored``,
        ``committed`` - the moment its last chunk is scored, while
        later shards are still parsing on the worker threads.
        """
        sid_of = {i: sid for i, sid in enumerate(todo)}
        specs = [
            ShardSpec(i, j.shard(sid)["path"], j.shard(sid)["fmt"])
            for i, sid in sid_of.items()
        ]
        pipe = InputPipeline(
            specs, self._schema, wanted=self._wanted,
            workers=self.workers, buffer_chunks=self.buffer_chunks,
            chunk_rows=self.chunk_rows, errors=self.errors,
            ordered=True, use_native=self.use_native,
        )
        # ONE write-ahead thread executes every journal transition and
        # durable output write in EXACT submission order, so the
        # on-disk sequence - and the fault-point walk the kill drills
        # pin - is byte-for-byte the serial one, while scoring never
        # stalls on an fsync.
        writer = _ShardWriter()
        assigned: set[int] = set()
        try:
            current: Optional[int] = None
            parts: list[tuple[bytes, int]] = []
            pending_results: list[Any] = []  # fleet in-flight requests
            for pc in pipe.chunks():
                if current is not None and pc.shard_id != current:
                    for k in range(current, pc.shard_id):
                        self._seal_shard(j, pipe, k, sid_of[k], parts,
                                         pending_results, writer,
                                         assigned)
                        parts, pending_results = [], []
                if current is None and pc.shard_id > 0:
                    for k in range(0, pc.shard_id):
                        self._seal_shard(j, pipe, k, sid_of[k], [], [],
                                         writer, assigned)
                if current != pc.shard_id:
                    # shard boundary: re-resolve the replica set so an
                    # elastic fleet's grow/shrink lands on this shard
                    self.router = self._resolve_router()
                    assigned.add(sid_of[pc.shard_id])
                    writer.submit(j.mark_assigned, sid_of[pc.shard_id],
                                  self.instance)
                current = pc.shard_id
                if self.router is not None:
                    self._submit_chunk(pc.payload, parts, pending_results)
                else:
                    parts.append(self._score_chunk_local(pc.payload))
            start = 0 if current is None else current
            for k in range(start, len(specs)):
                self._seal_shard(j, pipe, k, sid_of[k], parts,
                                 pending_results, writer, assigned)
                parts, pending_results = [], []
        finally:
            writer.close()

    def _score_chunk_local(self, chunk) -> tuple[bytes, int]:
        """Direct columnar feed: chunk columns -> fused env -> device
        program -> one ``(output bytes, n_rows)`` blob, no per-record
        decode and no per-row dict building on the single-Prediction
        plan.  Falls back to the assembled-row path when fusion
        degraded to the interpreted scorer or the result shape is not
        a lone Prediction."""
        fused = self.scorer.fused
        if fused is None:
            lines = _result_lines(self.scorer.score_batch(
                _records_from_chunk(chunk, self._features)))
            return b"".join(lines), len(lines)
        with _obs_trace.span("bulk.score_chunk", n=chunk.n_rows):
            env = _env_from_chunk(chunk, self._features)
            fast = getattr(fused, "score_env_prediction", None)
            res = fast(env, chunk.n_rows) if fast is not None else None
            if res is not None:
                name, keys, stacked = res
                blob = _result_blob_from_prediction(
                    name, keys, stacked, fused.last_nonfinite_rows)
                return blob, chunk.n_rows
            lines = _result_lines(fused.score_env(env, chunk.n_rows))
            return b"".join(lines), len(lines)

    # -- fleet fan-out -------------------------------------------------------
    def _resolve_router(self):
        """The CURRENT router behind ``router=``: a FleetRouter is
        itself, a FleetController yields its live router, a zero-arg
        callable is invoked.  Re-run at shard boundaries so a fleet
        that grew or shrank mid-job fans the next shard out to current
        members instead of a snapshot taken at job start."""
        src = self._router_source
        if src is None:
            return None
        if hasattr(src, "submit"):
            return src  # a router directly
        if hasattr(src, "router"):
            return src.router  # a FleetController
        if callable(src):
            return src()
        raise TypeError(
            f"router= must be a FleetRouter, FleetController, or "
            f"callable, got {type(src).__name__}")

    def _check_model_hosted(self) -> None:
        """Fail LOUDLY before scoring starts when ``model_id=`` names
        a model no live replica hosts - a billion-row job must not
        discover an unhosted model one chunk at a time."""
        if not self.model_id or self.router is None:
            return
        if not any(h.alive and h.hosts(self.model_id)
                   for h in self.router.replicas()):
            from ..fleet.multimodel import UnhostedModelError

            raise UnhostedModelError(
                f"bulk job {self.job_dir}: model {self.model_id!r} is "
                f"not hosted by any live replica; host it "
                f"(FleetController.host_model) before scoring")

    def _submit_chunk(self, chunk, parts: list[bytes],
                      pending: list[Any]) -> None:
        """Dispatch one chunk's records to the fleet; drain the oldest
        in-flight requests (IN ORDER - the output shard is
        exactly-ordered) once the window is full."""
        records = _records_from_chunk(chunk, self._features)
        while len(pending) >= self.max_in_flight:
            parts.append(self._drain_result(pending.pop(0)))
        pending.append(self.router.submit(records=records,
                                          model_id=self.model_id))

    def _drain_result(self, req) -> tuple[bytes, int]:
        res = req.wait(timeout=self.batch_timeout_s)
        lines = _result_lines(res.results)
        return b"".join(lines), len(lines)

    def _seal_shard(self, j: BulkJournal, pipe: InputPipeline,
                    pipe_sid: int, sid: int,
                    parts: list[tuple[bytes, int]],
                    pending: list[Any], writer: "_ShardWriter",
                    assigned: set[int]) -> None:
        """One shard's chunks are all scored (or it produced none):
        drain the fleet window, merge the per-shard quarantine into
        the ledger tally, and enqueue the durable write + journal
        commits on the write-ahead thread.  Nothing is promised until
        the write is durable - the transitions run strictly after it,
        in the same task."""
        if sid not in assigned:
            # zero-chunk shard (empty, or every row quarantined): it
            # never produced a chunk, so assignment happens here
            assigned.add(sid)
            writer.submit(j.mark_assigned, sid, self.instance)
        for req in pending:
            parts.append(self._drain_result(req))
        info = pipe.stats.shards.get(pipe_sid, {})
        buf = pipe.shard_quarantines.get(pipe_sid)
        rows_q = buf.total if buf is not None else 0
        rows_in = int(info.get("rows_kept", 0)) + rows_q
        rows_out = sum(n for _, n in parts)
        data = b"".join(b for b, _ in parts)
        writer.check()
        writer.submit_bulky(self._commit_shard, j, sid, data,
                            rows_in, rows_out, rows_q)

    def _commit_shard(self, j: BulkJournal, sid: int, data: bytes,
                      rows_in: int, rows_out: int, rows_q: int) -> None:
        """Durably write one output shard, then commit
        ``scored`` -> ``committed`` (write-ahead thread)."""
        with _obs_trace.span("bulk.commit_shard", shard=sid,
                             rows=rows_out):
            sha, n_bytes = j.write_output_shard(sid, data)
            # the exactly-once window under drill: output is durable,
            # the journal still says "assigned"
            _faults.inject_kill("bulk.output_crash")
            j.mark_scored(sid, sha, n_bytes, rows_in, rows_out, rows_q)
            j.mark_committed(sid)
        self._rows_out += rows_out
        self._rows_quarantined += rows_q
        self._shards_committed_this_run += 1


def concatenated_output(job_dir: str) -> bytes:
    """Every committed output shard's bytes, in shard order - the
    byte-identity surface the resume tests and the bench drill pin."""
    j = BulkJournal.load(job_dir)
    blobs = []
    for sid in j.shard_ids():
        if j.shard(sid)["state"] == STATE_COMMITTED:
            with open(j.output_path(sid), "rb") as f:
                blobs.append(f.read())
    return b"".join(blobs)
