"""Exactly-once bulk scoring (ISSUE 18; ROADMAP item 4).

A checkpointed, kill-survivable batch-inference job joining the PR-8
pipelined reader to the PR-12 fused programs (and, in fleet mode, the
PR-17 TCP fleet): sharded inputs stream through
:class:`readers.pipeline.InputPipeline` straight into
``score_env`` - no admission controller, no micro-batcher - while an
atomic, checksummed :class:`BulkJournal` walks every shard through
``pending -> assigned -> scored -> committed`` so a SIGKILL at any
instant resumes with zero duplicated and zero lost rows, and the
double-entry ledger accounts every quarantined row exactly.
"""
from .job import BulkScoringJob, concatenated_output
from .journal import (
    JOURNAL_FILENAME,
    OUTPUT_DIR,
    STATE_ASSIGNED,
    STATE_COMMITTED,
    STATE_PENDING,
    STATE_SCORED,
    STATES,
    BulkJournal,
    TornJournalError,
)

__all__ = [
    "BulkJournal",
    "BulkScoringJob",
    "JOURNAL_FILENAME",
    "OUTPUT_DIR",
    "STATES",
    "STATE_ASSIGNED",
    "STATE_COMMITTED",
    "STATE_PENDING",
    "STATE_SCORED",
    "TornJournalError",
    "concatenated_output",
]
