"""Atomic, checksummed job journal for exactly-once bulk scoring.

The journal is the ONLY durable truth a :class:`~.job.BulkScoringJob`
trusts: one ``journal.json`` document per job directory, written with
the serialization/model_io discipline (tempfile + fsync + rename, the
previous good document kept as ``journal.json.last-good``) and carrying
its own SHA-256 so a torn write can never be mistaken for state.  Every
shard moves through ``pending -> assigned -> scored -> committed``; the
``scored`` record pins the output shard's SHA-256 + byte size, so a
resume can tell a durable, complete output from a partial one without
trusting anything but the checksum.  The double-entry ledger
(``rows_in == rows_out + rows_quarantined``, per shard and globally) is
computed from the same records.

Fault points (drilled by tests/test_bulk.py and the chaos schedule):

* ``bulk.journal_torn``   - the primary journal reads back torn on
  :meth:`BulkJournal.load`; recovery must come from ``.last-good``.
* ``bulk.commit_crash``   - SIGKILL-equivalent exit immediately after
  the Nth journal commit lands (``on=N`` walks the kill across every
  state boundary).
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Optional, Sequence

from ..faults import injection as _faults
from ..serialization.model_io import LAST_GOOD_SUFFIX, write_bytes_atomic

#: the one journal document per job directory
JOURNAL_FILENAME = "journal.json"
#: output shards live under <job_dir>/shards/
OUTPUT_DIR = "shards"

STATE_PENDING = "pending"
STATE_ASSIGNED = "assigned"
STATE_SCORED = "scored"
STATE_COMMITTED = "committed"
#: the per-shard state machine, in order
STATES = (STATE_PENDING, STATE_ASSIGNED, STATE_SCORED, STATE_COMMITTED)

_CHECKSUM_KEY = "sha256"
_HASH_CHUNK = 1 << 20


class TornJournalError(RuntimeError):
    """``journal.json`` AND its ``.last-good`` fallback are both
    missing or fail their embedded checksum."""


def sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def sha256_file(path: str) -> tuple[Optional[str], int]:
    """(hexdigest, size) of ``path``, chunked; ``(None, 0)`` when the
    file does not exist."""
    if not os.path.exists(path):
        return None, 0
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(_HASH_CHUNK)
            if not block:
                break
            h.update(block)
            size += len(block)
    return h.hexdigest(), size


def _canonical(doc: dict) -> bytes:
    return json.dumps(doc, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8")


def _verify_raw(raw: Optional[bytes]) -> Optional[dict]:
    """Parse + checksum-verify one serialized journal; None on ANY
    torn/foreign state (missing, unparseable, wrong shape, bad sum)."""
    if raw is None:
        return None
    try:
        doc = json.loads(raw)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(doc, dict) or not isinstance(doc.get("shards"), dict):
        return None
    want = doc.get(_CHECKSUM_KEY)
    body = {k: v for k, v in doc.items() if k != _CHECKSUM_KEY}
    if want != sha256_bytes(_canonical(body)):
        return None
    return doc


def _read_bytes(path: str) -> Optional[bytes]:
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return f.read()


def output_name(shard_id: int) -> str:
    return f"part-{int(shard_id):05d}.jsonl"


class BulkJournal:
    """The per-job shard state machine + ledger, persisted atomically.

    Every mutation lands through :meth:`commit`: serialize with the
    embedded checksum, keep the previous GOOD document as
    ``.last-good``, then tempfile + fsync + rename the new one.  A kill
    at any instant leaves either the old good journal, the new good
    journal, or a torn primary with a good ``.last-good`` - never an
    unrecoverable state.
    """

    def __init__(self, job_dir: str, doc: dict,
                 recovered_from_last_good: bool = False) -> None:
        self.job_dir = str(job_dir)
        self.doc = doc
        self.recovered_from_last_good = recovered_from_last_good

    # -- paths ---------------------------------------------------------------
    @property
    def path(self) -> str:
        return os.path.join(self.job_dir, JOURNAL_FILENAME)

    def output_path(self, shard_id: int) -> str:
        return os.path.join(self.job_dir, OUTPUT_DIR,
                            self.shard(shard_id)["output"])

    # -- construction --------------------------------------------------------
    @classmethod
    def create(cls, job_dir: str, inputs: Sequence[tuple[str, Optional[str]]],
               trace_context: Optional[str] = None,
               params: Optional[dict] = None) -> "BulkJournal":
        """Plan a fresh job: one journal record per input shard, all
        ``pending``, committed durably before any scoring starts."""
        shards: dict[str, dict] = {}
        for i, (path, fmt) in enumerate(inputs):
            shards[str(i)] = {
                "shard_id": i,
                "path": str(path),
                "fmt": fmt,
                "input_bytes": (os.path.getsize(path)
                                if os.path.exists(path) else None),
                "state": STATE_PENDING,
                "output": output_name(i),
                "output_sha256": None,
                "output_bytes": None,
                "rows_in": None,
                "rows_out": None,
                "rows_quarantined": None,
                "assigned_to": None,
                "attempts": 0,
            }
        doc = {
            "version": 1,
            "created_unix": time.time(),
            "trace_context": trace_context,
            "params": dict(params or {}),
            "n_shards": len(shards),
            "shards": shards,
            "resumes": [],
        }
        j = cls(job_dir, doc)
        j.commit()
        return j

    @classmethod
    def load(cls, job_dir: str) -> "BulkJournal":
        """Checksum-verified load: primary first, ``.last-good`` on any
        torn primary, :class:`TornJournalError` when both fail."""
        path = os.path.join(str(job_dir), JOURNAL_FILENAME)
        raw = _read_bytes(path)
        if raw is not None and _faults.fires("bulk.journal_torn") is not None:
            # drill: the primary reads back half-written
            raw = raw[: max(len(raw) // 2, 1)]
        doc = _verify_raw(raw)
        if doc is not None:
            return cls(str(job_dir), doc)
        lg = _verify_raw(_read_bytes(path + LAST_GOOD_SUFFIX))
        if lg is not None:
            return cls(str(job_dir), lg, recovered_from_last_good=True)
        raise TornJournalError(
            f"{path}: journal and its {LAST_GOOD_SUFFIX} fallback are "
            f"both missing or fail their checksum"
        )

    @staticmethod
    def exists(job_dir: str) -> bool:
        path = os.path.join(str(job_dir), JOURNAL_FILENAME)
        return os.path.exists(path) or os.path.exists(
            path + LAST_GOOD_SUFFIX)

    # -- persistence ---------------------------------------------------------
    def commit(self) -> None:
        """Serialize + checksum + atomically replace, preserving the
        previous good journal as ``.last-good`` first."""
        body = {k: v for k, v in self.doc.items() if k != _CHECKSUM_KEY}
        body[_CHECKSUM_KEY] = sha256_bytes(_canonical(
            {k: v for k, v in body.items() if k != _CHECKSUM_KEY}))
        self.doc = body
        prev = _read_bytes(self.path)
        if prev is not None and _verify_raw(prev) is not None:
            write_bytes_atomic(self.path + LAST_GOOD_SUFFIX, prev)
        write_bytes_atomic(
            self.path, json.dumps(body, indent=1, sort_keys=True,
                                  default=str).encode("utf-8") + b"\n")
        # drill seam: die IMMEDIATELY after the Nth commit lands - with
        # on=N this walks a SIGKILL across every state boundary
        _faults.inject_kill("bulk.commit_crash")

    # -- shard accessors -----------------------------------------------------
    def shard(self, shard_id: int) -> dict:
        return self.doc["shards"][str(int(shard_id))]

    def shard_ids(self) -> list[int]:
        return sorted(int(k) for k in self.doc["shards"])

    def states(self) -> dict[str, int]:
        hist = {s: 0 for s in STATES}
        for sid in self.shard_ids():
            hist[self.shard(sid)["state"]] += 1
        return hist

    def uncommitted(self) -> list[int]:
        return [sid for sid in self.shard_ids()
                if self.shard(sid)["state"] != STATE_COMMITTED]

    # -- state transitions (each one durable) --------------------------------
    def mark_assigned(self, shard_id: int, instance: str) -> None:
        rec = self.shard(shard_id)
        rec["state"] = STATE_ASSIGNED
        rec["assigned_to"] = str(instance)
        rec["attempts"] = int(rec["attempts"]) + 1
        self.commit()

    def mark_scored(self, shard_id: int, sha256: str, n_bytes: int,
                    rows_in: int, rows_out: int,
                    rows_quarantined: int) -> None:
        rec = self.shard(shard_id)
        rec["state"] = STATE_SCORED
        rec["output_sha256"] = sha256
        rec["output_bytes"] = int(n_bytes)
        rec["rows_in"] = int(rows_in)
        rec["rows_out"] = int(rows_out)
        rec["rows_quarantined"] = int(rows_quarantined)
        self.commit()

    def mark_committed(self, shard_id: int) -> None:
        self.shard(shard_id)["state"] = STATE_COMMITTED
        self.commit()

    def reset_shard(self, shard_id: int) -> None:
        """Roll one shard's record back to ``pending`` (in memory; the
        caller batches the durable commit via :meth:`record_resume`)."""
        rec = self.shard(shard_id)
        rec["state"] = STATE_PENDING
        rec["output_sha256"] = None
        rec["output_bytes"] = None
        rec["rows_in"] = None
        rec["rows_out"] = None
        rec["rows_quarantined"] = None
        rec["assigned_to"] = None

    def record_resume(self, pid: int, instance: str,
                      recovered: dict[str, str],
                      rescored: Sequence[int]) -> None:
        self.doc["resumes"].append({
            "unix": time.time(),
            "pid": int(pid),
            "instance": str(instance),
            "recovered_states": dict(recovered),
            "rescored_shards": sorted(int(s) for s in rescored),
            "from_last_good": self.recovered_from_last_good,
        })
        self.commit()

    # -- output shards -------------------------------------------------------
    def write_output_shard(self, shard_id: int,
                           data: bytes) -> tuple[str, int]:
        """Durably write one output shard (tempfile + fsync + rename)
        and return its ``(sha256, byte size)`` for the journal record."""
        write_bytes_atomic(self.output_path(shard_id), data)
        return sha256_bytes(data), len(data)

    def verify_output(self, shard_id: int) -> bool:
        """Does the output shard on disk match its journal checksum?
        False on a missing/partial/foreign file or an unrecorded one."""
        rec = self.shard(shard_id)
        if rec["output_sha256"] is None:
            return False
        sha, size = sha256_file(self.output_path(shard_id))
        return sha == rec["output_sha256"] and size == rec["output_bytes"]

    # -- the double-entry ledger ---------------------------------------------
    def ledger(self) -> dict[str, Any]:
        """``rows_in == rows_out + rows_quarantined``, per shard and
        globally.  ``balanced`` is None for shards not yet scored;
        the global verdict requires every shard scored AND balanced."""
        per: dict[str, dict] = {}
        tot_in = tot_out = tot_q = 0
        complete = True
        all_balanced = True
        for sid in self.shard_ids():
            rec = self.shard(sid)
            if rec["rows_in"] is None:
                balanced = None
                complete = False
            else:
                balanced = (rec["rows_in"]
                            == rec["rows_out"] + rec["rows_quarantined"])
                tot_in += rec["rows_in"]
                tot_out += rec["rows_out"]
                tot_q += rec["rows_quarantined"]
                all_balanced = all_balanced and balanced
            per[str(sid)] = {
                "state": rec["state"],
                "rows_in": rec["rows_in"],
                "rows_out": rec["rows_out"],
                "rows_quarantined": rec["rows_quarantined"],
                "balanced": balanced,
            }
        return {
            "shards": per,
            "rows_in": tot_in,
            "rows_out": tot_out,
            "rows_quarantined": tot_q,
            "complete": complete,
            "balanced": complete and all_balanced
            and tot_in == tot_out + tot_q,
        }

    # -- operator surface ----------------------------------------------------
    def status_doc(self) -> dict[str, Any]:
        """The one-document job status ``tx bulk status`` prints."""
        resumes = self.doc.get("resumes", [])
        return {
            "job_dir": self.job_dir,
            "n_shards": self.doc.get("n_shards"),
            "states": self.states(),
            "shards": {str(sid): dict(self.shard(sid))
                       for sid in self.shard_ids()},
            "ledger": self.ledger(),
            "resumes": list(resumes),
            "resume_count": len(resumes),
            "rescored_shards": sorted(
                {s for r in resumes for s in r.get("rescored_shards", [])}),
            "trace_context": self.doc.get("trace_context"),
            "recovered_from_last_good": self.recovered_from_last_good,
            "params": dict(self.doc.get("params", {})),
        }
