"""ModelInsights: aggregate post-train knowledge into one report.

Counterpart of the reference ModelInsights (reference: core/.../
ModelInsights.scala:72-99,435-525 + prettyPrint): walks the fitted stages
for the last SanityChecker and ModelSelector, joins their summary metadata
with vector-column provenance, and renders the README-style tables
(selected model params, metrics, top positive/negative correlations).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np


def _fmt_table(rows: list[tuple], headers: tuple) -> str:
    """ASCII table in the reference's summaryPretty style (reference:
    utils/.../text/Table.scala)."""
    cols = [headers] + [tuple(str(c) for c in r) for r in rows]
    widths = [max(len(r[i]) for r in cols) for i in range(len(headers))]

    def line() -> str:
        return "|" + "|".join("-" * (w + 2) for w in widths) + "|"

    def row(r) -> str:
        return "| " + " | ".join(str(c).rjust(w) for c, w in zip(r, widths)) + " |"

    out = [line(), row(headers), line()]
    out += [row(r) for r in rows]
    out.append(line())
    return "\n".join(out)


@dataclass
class FeatureInsight:
    name: str
    pretty_name: str
    parent: str
    corr_label: Optional[float]
    cramers_v: Optional[float]
    variance: Optional[float]
    mean: Optional[float]
    contribution: Optional[float]
    dropped_reasons: list = field(default_factory=list)

    def to_json(self) -> dict:
        return dict(self.__dict__)


@dataclass
class ModelInsights:
    selected_model_type: Optional[str]
    best_params: dict
    validation_metric: dict
    validation_results: list
    train_metrics: dict
    holdout_metrics: dict
    feature_insights: list[FeatureInsight]
    splitter_summary: dict
    n_rows: int
    # reference parity (ModelInsights.scala:72-79): the label's own
    # summary + every stage's settings keyed by uid
    label_summary: dict = field(default_factory=dict)
    stage_info: dict = field(default_factory=dict)

    @staticmethod
    def _label_summary(model) -> dict:
        """Label name, lineage, sample size and distribution (reference
        LabelSummary + Continuous/Discrete, ModelInsights.scala:291-323).
        Distribution is computed from the model's stored input dataset by
        replaying the fitted DAG up to the prediction - a one-off cost at
        insights time, not retained training state."""
        import numpy as np

        label_f = None
        for f in getattr(model, "result_features", ()):
            st = f.origin_stage
            ins = getattr(st, "input_features", ()) if st else ()
            if len(ins) >= 2 and ins[0].is_response:
                label_f = ins[0]
                break
        if label_f is None:
            return {}
        hist = label_f.history()
        out = {
            "label_name": label_f.name,
            "raw_feature_names": hist["originFeatures"],
            "stages_applied": hist["stages"],
        }
        # the training cache holds the fully-transformed columns - the
        # label included.  A model restored via load_model has no cache
        # (and no data to replay), so the distribution is honestly
        # unavailable there rather than recomputed from nothing.
        ds = getattr(model, "_train_data_cache", None)
        if ds is None:
            out["distribution_unavailable"] = (
                "no training cache (loaded model): label stats are "
                "computed from the fit-time data"
            )
            return out
        try:
            col = ds.columns().get(label_f.name)
            vals = np.asarray(
                [v for v in col.to_list() if v is not None], dtype=float
            )
            out["sample_size"] = int(len(vals))
            uniq, cnts = np.unique(vals, return_counts=True)
            if len(uniq) <= 30:
                out["distribution"] = {
                    "type": "discrete",
                    "domain": [str(u) for u in uniq],
                    "prob": (cnts / max(len(vals), 1)).tolist(),
                }
            else:
                out["distribution"] = {
                    "type": "continuous",
                    "min": float(vals.min()),
                    "max": float(vals.max()),
                    "mean": float(vals.mean()),
                    "variance": float(vals.var(ddof=1)),
                }
        except Exception as e:
            out["distribution_error"] = f"{type(e).__name__}: {e}"
        return out

    @staticmethod
    def _stage_info(model) -> dict:
        """Every fitted stage's settings keyed by uid (reference
        ModelInsights stageInfo map); params scrub to JSON-safe strings
        so exotic values never break the report."""
        def safe(v):
            if isinstance(v, (bool, int, float, str, type(None))):
                return v
            if isinstance(v, (list, tuple)) and len(v) <= 32:
                return [safe(x) for x in v]
            if (
                isinstance(v, dict)
                and len(v) <= 32
                and all(isinstance(k, str) for k in v)
            ):
                return {k: safe(x) for k, x in v.items()}
            return str(v)[:200]

        info = {}
        for s in getattr(model, "stages", ()):
            # fitted predictor wrappers report their estimator's type
            # (PredictorModel alone says nothing about WHICH model)
            cls = (
                getattr(s, "model_type", None)
                or getattr(
                    getattr(s, "estimator_ref", None), "model_type", None
                )
                or type(s).__name__
            )
            params = getattr(s, "params", None) or getattr(
                getattr(s, "estimator_ref", None), "params", None
            ) or {}
            info[s.uid] = {
                "class": cls,
                "inputs": [f.name for f in getattr(s, "input_features", ())],
                "params": {k: safe(v) for k, v in params.items()},
            }
        return info

    @staticmethod
    def from_model(model, feature=None) -> "ModelInsights":
        """Walk fitted stages (reference: ModelInsights.scala:435-525)."""
        sc_summary = None
        ms_summary = None
        contributions = None
        for s in model.stages:
            if "sanity_checker_summary" in s.metadata:
                sc_summary = s.metadata["sanity_checker_summary"]
            if "model_selector_summary" in s.metadata:
                ms_summary = s.metadata["model_selector_summary"]
                if hasattr(s, "feature_contributions"):
                    contributions = s.feature_contributions()
            elif hasattr(s, "feature_contributions") and contributions is None:
                contributions = s.feature_contributions()

        insights: list[FeatureInsight] = []
        if sc_summary is not None:
            kept_i = 0
            for c in sc_summary["column_stats"]:
                contrib = None
                if contributions is not None and not c["dropped_reasons"]:
                    if kept_i < len(contributions):
                        contrib = float(contributions[kept_i])
                    kept_i += 1
                insights.append(
                    FeatureInsight(
                        name=c["name"],
                        pretty_name=c["pretty_name"],
                        parent=c["parent"],
                        corr_label=c["corr_label"],
                        cramers_v=c["cramers_v"],
                        variance=c["variance"],
                        mean=c["mean"],
                        contribution=contrib,
                        dropped_reasons=c["dropped_reasons"],
                    )
                )

        ms = ms_summary or {}
        return ModelInsights(
            selected_model_type=ms.get("best_model_type"),
            best_params=ms.get("best_params", {}),
            validation_metric=ms.get("validation_metric", {}),
            validation_results=ms.get("validation_results", []),
            train_metrics=ms.get("train_metrics", {}),
            holdout_metrics=ms.get("holdout_metrics", {}),
            feature_insights=insights,
            splitter_summary=ms.get("splitter_summary", {}),
            n_rows=ms.get("n_rows", 0),
            label_summary=ModelInsights._label_summary(model),
            stage_info=ModelInsights._stage_info(model),
        )

    def to_json(self) -> dict:
        return {
            "selected_model_type": self.selected_model_type,
            "best_params": self.best_params,
            "validation_metric": self.validation_metric,
            "validation_results": self.validation_results,
            "train_metrics": self.train_metrics,
            "holdout_metrics": self.holdout_metrics,
            "feature_insights": [f.to_json() for f in self.feature_insights],
            "splitter_summary": self.splitter_summary,
            "n_rows": self.n_rows,
            "label_summary": self.label_summary,
            "stage_info": self.stage_info,
        }

    def json(self) -> str:
        return json.dumps(self.to_json(), indent=2, default=str)

    def pretty(self, top_k: int = 15) -> str:
        """README-style summary (reference: ModelInsights.prettyPrint +
        README.md:59-107)."""
        out = []
        if self.validation_results:
            by_type: dict[str, list[float]] = {}
            for r in self.validation_results:
                by_type.setdefault(r["model_type"], []).append(r["metric"])
            name = self.validation_metric.get("name", "metric")
            counts = ", ".join(f"{len(v)} {k}" for k, v in by_type.items())
            out.append(f"Evaluated {counts} models with {name} metric.")
            for k, v in by_type.items():
                out.append(
                    f"Evaluated {len(v)} {k} models with {name} between "
                    f"[{min(v):.6g}, {max(v):.6g}]"
                )
            out.append("")
        if self.selected_model_type:
            rows = [("modelType", self.selected_model_type)] + sorted(
                (k, v) for k, v in self.best_params.items()
            )
            out.append(f"Selected model {self.selected_model_type} with parameters:")
            out.append(_fmt_table(rows, ("Model Param", "Value")))
            out.append("")
        if self.train_metrics or self.holdout_metrics:
            tm = next(iter(self.train_metrics.values()), {})
            hm = next(iter(self.holdout_metrics.values()), {})
            keys = [k for k in tm if isinstance(tm.get(k), (int, float))]
            rows = [
                (k, f"{hm.get(k, float('nan')):.6g}" if k in hm else "-",
                 f"{tm[k]:.6g}")
                for k in keys
            ]
            out.append("Model evaluation metrics:")
            out.append(
                _fmt_table(
                    rows, ("Metric Name", "Hold Out Set Value", "Training Set Value")
                )
            )
            out.append("")
        corr_feats = [
            f for f in self.feature_insights
            if f.corr_label is not None and not f.dropped_reasons
            and np.isfinite(f.corr_label)
        ]
        if corr_feats:
            pos = sorted(corr_feats, key=lambda f: -f.corr_label)[:3]
            neg = sorted(corr_feats, key=lambda f: f.corr_label)[:3]
            out.append("Top model insights computed using correlation:")
            out.append(
                _fmt_table(
                    [(f.pretty_name, f"{f.corr_label:.6g}") for f in pos],
                    ("Top Positive Insights", "Correlation"),
                )
            )
            out.append(
                _fmt_table(
                    [(f.pretty_name, f"{f.corr_label:.6g}") for f in neg],
                    ("Top Negative Insights", "Correlation"),
                )
            )
        return "\n".join(out)
