"""RecordInsightsLOCO: per-row leave-one-column-out explanations.

Counterpart of the reference RecordInsightsLOCO (reference: core/.../impl/
insights/RecordInsightsLOCO.scala:55-105): score each row with each feature
column zeroed out and report the top-K score deltas.  Where the reference
re-scores per row per column with a bounded priority queue, the TPU version
batches ALL (row, column) zero-outs as one [d+1, n]-shaped vmapped rescore -
cheap on device because the model's predict is a couple of matmuls.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..models.base import PredictorModel
from ..stages.base import Transformer
from ..types.columns import Column, MapColumn, VectorColumn
from ..types.dataset import Dataset
from ..types.feature_types import OPVector, TextMap


class RecordInsightsCorr(Transformer):
    """Correlation-based record insights (reference: core/.../impl/insights/
    RecordInsightsCorr.scala): per-row contribution of column j approximated
    as corr(feature_j, score) * standardized deviation of x_ij - one pass
    of columnar moments, no rescoring."""

    input_types = [OPVector]
    output_type = TextMap

    def __init__(self, model: PredictorModel, top_k: int = 20, **kw) -> None:
        super().__init__(**kw)
        self.model = model
        self.top_k = top_k

    def transform_columns(self, cols: Sequence[Column], ds: Dataset) -> Column:
        (vec,) = cols
        assert isinstance(vec, VectorColumn)
        X = np.asarray(vec.values, dtype=np.float64)
        n, d = X.shape
        est, params = self.model.estimator_ref, self.model.model_params
        pred, raw, prob = est.predict_arrays(params, X)
        score = (
            prob[:, 1]
            if prob is not None and prob.shape[1] == 2
            else pred
        )
        mu = X.mean(axis=0)
        sd = X.std(axis=0) + 1e-12
        s_mu, s_sd = score.mean(), score.std() + 1e-12
        corr = ((X - mu) * (score - s_mu)[:, None]).mean(axis=0) / (sd * s_sd)
        contrib = corr[None, :] * (X - mu) / sd  # [n, d]
        names = vec.metadata.column_names() if vec.metadata.size == d else [
            str(j) for j in range(d)
        ]
        k = min(self.top_k, d)
        top_idx = np.argsort(-np.abs(contrib), axis=1)[:, :k]
        return MapColumn(
            [
                {names[j]: float(contrib[i, j]) for j in top_idx[i]}
                for i in range(n)
            ],
            TextMap,
        )


class RecordInsightsLOCO(Transformer):
    """Input: the feature vector; carries a fitted predictor model.  Output:
    per-row {column_name: delta} map of the top-K largest prediction moves.
    With ``detailed=True`` the map uses the reference's serialized format
    instead: {column-history-json: [[prediction_index, delta]] json}
    (RecordInsightsLOCO.scala + RecordInsightsParser.scala), parseable
    back to structure with :func:`parse_insights`."""

    input_types = [OPVector]
    output_type = TextMap

    def __init__(self, model: PredictorModel, top_k: int = 20,
                 detailed: bool = False, **kw) -> None:
        super().__init__(**kw)
        self.model = model
        self.top_k = top_k
        self.detailed = detailed

    def transform_columns(self, cols: Sequence[Column], ds: Dataset) -> Column:
        (vec,) = cols
        assert isinstance(vec, VectorColumn)
        X = np.asarray(vec.values, dtype=np.float64)
        n, d = X.shape
        est, params = self.model.estimator_ref, self.model.model_params

        def score_all(Xm: np.ndarray) -> np.ndarray:
            """Full score vector per row [n, C] (class probabilities, or
            the prediction itself for regressors) - the reference's LOCO
            diffs EVERY prediction index (RecordInsightsLOCO.scala:94)."""
            pred, raw, prob = est.predict_arrays(params, Xm)
            if prob is not None and prob.shape[1] > 1:
                return np.asarray(prob)
            return np.asarray(pred)[:, None]

        base = score_all(X)                      # [n, C]
        C = base.shape[1]
        deltas = np.zeros((n, d, C))
        for j in range(d):  # d zero-out passes, each a full batched rescore
            Xj = X.copy()
            Xj[:, j] = 0.0
            deltas[:, j, :] = base - score_all(Xj)

        # scalar ranking value per (row, column): binary keeps the positive
        # class' delta (prob sums to 1, so |delta| matches class 0);
        # multiclass/regression takes the largest-|.| class diff
        if C == 2:
            scalar = deltas[:, :, 1]
        elif C == 1:
            scalar = deltas[:, :, 0]
        else:
            amax = np.argmax(np.abs(deltas), axis=2)  # [n, d]
            scalar = np.take_along_axis(
                deltas, amax[:, :, None], axis=2
            )[:, :, 0]

        names = vec.metadata.column_names() if vec.metadata.size == d else [
            str(j) for j in range(d)
        ]
        k = min(self.top_k, d)
        out = []
        # top-k by |delta| per row (the reference's bounded priority queue)
        top_idx = np.argsort(-np.abs(scalar), axis=1)[:, :k]
        if self.detailed:
            import json

            histories = (
                vec.metadata.column_history()
                if vec.metadata.size == d
                else [{"columnName": nm} for nm in names]
            )
            # serialize each column's history ONCE, not once per (row, k)
            keys = [json.dumps(h, sort_keys=True) for h in histories]
            for i in range(n):
                out.append({
                    keys[j]: json.dumps(
                        [[c, float(deltas[i, j, c])] for c in range(C)]
                    )
                    for j in top_idx[i]
                })
            return MapColumn(out, TextMap)
        for i in range(n):
            out.append(
                {names[j]: float(scalar[i, j]) for j in top_idx[i]}
            )
        return MapColumn(out, TextMap)


# -- RecordInsightsParser -----------------------------------------------------
# (reference: core/.../impl/insights/RecordInsightsParser.scala - converts
# the record-insight TextMap {column-history-json: [[idx, score]...]} to and
# from structured form so downstream consumers can parse per-column
# provenance together with the score deltas)
def insights_to_text_map(
    insights: Sequence[tuple[dict, Sequence[tuple[int, float]]]],
) -> dict:
    """[(column_history, [(prediction_index, delta), ...]), ...] -> the
    serialized {history_json: scores_json} map of one record's insights."""
    import json

    out = {}
    for history, scores in insights:
        key = json.dumps(history, sort_keys=True)
        out[key] = json.dumps([[int(i), float(s)] for i, s in scores])
    return out


def parse_insights(
    text_map: dict,
) -> list[tuple[dict, list[tuple[int, float]]]]:
    """Inverse of insights_to_text_map: the record-insight TextMap back to
    [(column_history, [(prediction_index, delta), ...])]."""
    import json

    out = []
    for key, val in text_map.items():
        history = json.loads(key)
        scores = [(int(i), float(s)) for i, s in json.loads(val)]
        out.append((history, scores))
    return out
