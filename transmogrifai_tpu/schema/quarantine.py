"""Poison-row quarantine + data-plane telemetry.

tf.data's stance (PAPERS.md) applied to this engine's readers: a
production input pipeline owns an error POLICY — one malformed row in a
million must not abort the ingest, and it must not silently coerce into
a plausible value either.  Every reader takes ``errors=``:

* ``"coerce"``     — legacy behavior (unparseable numeric cells become
                     missing values); the default, bit-identical to the
                     pre-quarantine readers.
* ``"strict"``     — the first malformed row raises
                     :class:`MalformedRowError` naming the row index,
                     column, and reason.
* ``"quarantine"`` — malformed rows are dropped from the output and
                     recorded (row index, payload excerpt, reason) in a
                     bounded :class:`QuarantineBuffer`; exact counts land
                     in :class:`DataTelemetry`.

``DataTelemetry`` mirrors the ServingTelemetry snapshot/export contract
(serving/telemetry.py) for the ingest tier, and the module-level
:func:`data_telemetry` accumulator lets readers record without plumbing
when the caller does not pass one.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..obs.metrics import metrics_registry, write_json_artifact

log = logging.getLogger("transmogrifai_tpu.schema")

LOG_PREFIX = "op_data_metrics"

ERROR_MODES = ("coerce", "strict", "quarantine")

#: default QuarantineBuffer capacity: counts stay exact past it, only
#: the per-row detail stops accumulating (ingest memory must be bounded
#: no matter how poisoned the file is)
DEFAULT_MAX_ROWS = 1024

_EXCERPT_LEN = 80


def check_errors_mode(errors: str) -> str:
    """Validate a reader ``errors=`` mode (misconfigured policies must
    be loud at construction, not at the first bad row)."""
    if errors not in ERROR_MODES:
        raise ValueError(
            f"errors must be one of {ERROR_MODES}, got {errors!r}"
        )
    return errors


class MalformedRowError(ValueError):
    """Strict-mode ingest error naming the offending row.

    ``row_index`` is 0-based over the file's data rows (header
    excluded), matching the QuarantinedRow indices quarantine mode
    records for the same file.
    """

    def __init__(self, source: str, row_index: int, reason: str,
                 column: Optional[str] = None,
                 excerpt: Optional[str] = None) -> None:
        self.source = source
        self.row_index = row_index
        self.reason = reason
        self.column = column
        self.excerpt = excerpt
        at = f" column {column!r}" if column else ""
        ex = f" (cell: {excerpt!r})" if excerpt else ""
        super().__init__(
            f"{source}: malformed row {row_index}{at}: {reason}{ex}; "
            "use errors='quarantine' to isolate bad rows instead"
        )


def coerce_numeric(value) -> Optional[float]:
    """THE junk-vs-number decision every reader shares: the value a
    coerce-mode read would silently null is exactly what checked modes
    call a type flip, so strict/quarantine/coerce can never disagree
    about which cells are junk.  Bytes decode as UTF-8 first (the
    native CSV scanner hands raw cell bytes); None = does not parse."""
    if isinstance(value, (bytes, bytearray)):
        try:
            value = value.decode("utf-8")
        except UnicodeDecodeError:
            return None
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


def excerpt_of(raw) -> str:
    """Bounded, printable excerpt of a bad cell/row payload."""
    if isinstance(raw, bytes):
        raw = raw.decode("utf-8", errors="replace")
    s = str(raw)
    return s if len(s) <= _EXCERPT_LEN else s[: _EXCERPT_LEN - 1] + "…"


@dataclass
class QuarantinedRow:
    """One isolated row: where it was, why, and what it looked like."""

    row_index: int
    reason: str
    column: Optional[str] = None
    excerpt: str = ""

    def to_json(self) -> dict:
        return {
            "row_index": self.row_index,
            "reason": self.reason,
            "column": self.column,
            "excerpt": self.excerpt,
        }


class QuarantineBuffer:
    """Bounded, thread-safe poison-row sink.

    ``total``/``by_reason`` counts stay EXACT past ``max_rows``; only
    per-row detail stops accumulating (``truncated`` reports how many
    details were dropped).  Thread-safe because DeviceCSVIngest's parse
    worker records from a background thread.
    """

    def __init__(self, max_rows: int = DEFAULT_MAX_ROWS,
                 source: str = "") -> None:
        if max_rows < 1:
            raise ValueError("max_rows must be >= 1")
        self.max_rows = int(max_rows)
        self.source = source
        self.rows: list[QuarantinedRow] = []
        self.total = 0
        self.by_reason: dict[str, int] = {}
        self._lock = threading.Lock()

    def add(self, row_index: int, reason: str,
            column: Optional[str] = None, excerpt: str = "") -> None:
        with self._lock:
            self.total += 1
            self.by_reason[reason] = self.by_reason.get(reason, 0) + 1
            if len(self.rows) < self.max_rows:
                self.rows.append(
                    QuarantinedRow(row_index, reason, column, excerpt)
                )

    @property
    def truncated(self) -> int:
        """Quarantined rows whose detail was dropped at the cap."""
        with self._lock:
            return self.total - len(self.rows)

    def __len__(self) -> int:
        return self.total

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "source": self.source,
                "total": self.total,
                "by_reason": dict(self.by_reason),
                "detail_capacity": self.max_rows,
                "detail_dropped": self.total - len(self.rows),
                "rows": [r.to_json() for r in self.rows],
            }


class DataTelemetry:
    """Ingest-tier accumulator (the ServingTelemetry sibling): exact
    read/kept/quarantined row counts per source plus reason totals,
    snapshot()-able any time and export()-able as a JSON artifact."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.started_at = time.time()  # epoch stamp (correlation only)
        self._pc_start = time.perf_counter()  # durations never use the
        # epoch clock (the tests/test_style.py timing gate)
        # unified metrics plane (obs/): snapshot registered as a view
        metrics_registry().register_view("data", self)
        # model-version attribution (registry/): the ServingTelemetry-
        # shared pair, so data-plane metrics in bench JSON and
        # summary_json() name the model version they fed
        self.model_version: Optional[str] = None
        self.generation: Optional[int] = None
        self.rows_read = 0
        self.rows_kept = 0
        self.rows_quarantined = 0
        self.strict_errors = 0
        self.reads = 0
        self.quarantined_by_reason: dict[str, int] = {}
        self.per_source: dict[str, dict] = {}

    # -- recording ----------------------------------------------------------
    def record_read(self, source: str, rows_read: int, rows_kept: int,
                    quarantine: Optional[QuarantineBuffer] = None) -> None:
        """One completed ingest: exact totals; ``quarantine`` folds the
        buffer's reason counts in."""
        with self._lock:
            self.reads += 1
            self.rows_read += int(rows_read)
            self.rows_kept += int(rows_kept)
            n_quar = int(rows_read) - int(rows_kept)
            self.rows_quarantined += n_quar
            if quarantine is not None:
                for reason, n in quarantine.by_reason.items():
                    self.quarantined_by_reason[reason] = (
                        self.quarantined_by_reason.get(reason, 0) + n
                    )
            src = self.per_source.setdefault(
                source, {"reads": 0, "rows_read": 0, "rows_kept": 0,
                         "rows_quarantined": 0},
            )
            src["reads"] += 1
            src["rows_read"] += int(rows_read)
            src["rows_kept"] += int(rows_kept)
            src["rows_quarantined"] += n_quar
        if n_quar:
            log.warning(
                "%s source=%s quarantined=%d of %d rows", LOG_PREFIX,
                source, n_quar, rows_read,
            )

    def record_strict_error(self, source: str) -> None:
        with self._lock:
            self.strict_errors += 1

    def set_model_version(self, version: Optional[str],
                          generation: Optional[int] = None) -> None:
        """Attribute subsequent ingest metrics to one model version /
        deployment generation (the ServingTelemetry contract)."""
        with self._lock:
            self.model_version = version
            self.generation = generation

    # -- reporting ----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            wall = max(time.perf_counter() - self._pc_start, 1e-9)
            return {
                "wall_s": round(wall, 3),
                "model_version": self.model_version,
                "generation": self.generation,
                "reads": self.reads,
                "rows_read": self.rows_read,
                "rows_kept": self.rows_kept,
                "rows_quarantined": self.rows_quarantined,
                "strict_errors": self.strict_errors,
                "quarantined_by_reason": dict(self.quarantined_by_reason),
                "per_source": {k: dict(v)
                               for k, v in self.per_source.items()},
            }

    def log_line(self) -> str:
        snap = self.snapshot()
        kv = {
            "reads": snap["reads"],
            "rows_read": snap["rows_read"],
            "rows_quarantined": snap["rows_quarantined"],
            "strict_errors": snap["strict_errors"],
        }
        return LOG_PREFIX + " " + " ".join(f"{k}={v}" for k, v in kv.items())

    def export(self, path: str, extra: Optional[dict] = None) -> dict:
        snap = self.snapshot()
        if extra:
            snap.update(extra)
        write_json_artifact(path, snap)
        log.info(self.log_line())
        return snap


_telemetry = DataTelemetry()


def data_telemetry() -> DataTelemetry:
    """Process-wide default accumulator readers record into when the
    caller passes none (the mesh_telemetry() pattern)."""
    return _telemetry


def reset_data_telemetry() -> DataTelemetry:
    """Fresh accumulator (test/bench isolation)."""
    global _telemetry
    _telemetry = DataTelemetry()
    return _telemetry
