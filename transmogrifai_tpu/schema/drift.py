"""Serve-time distribution-drift scoring against the training contract.

The score-vs-train half of the reference's RawFeatureFilter (reference:
core/.../filters/RawFeatureFilter.scala jsDivergence check between
training and scoring FeatureDistributions) relocated to where this
engine actually sees scoring traffic: the serving endpoint.  A
:class:`DriftMonitor` accumulates a running FeatureDistribution per
contracted feature from every scored batch (distributions are monoid-
mergeable — the same reduce the reference runs over Spark partitions,
here over serve batches) with numeric bin edges PINNED to the training
value_range, so the JS divergence against the fit-time histogram is
meaningful from the first batch.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Mapping, Optional, Sequence

from ..filters.feature_distribution import (
    FeatureDistribution,
    compute_distribution,
)
# columnar extraction shared with the fused-serving decoder (one
# single-pass comprehension per feature instead of the per-element
# column_from_list loop - drift observation was the top line of the
# fused-endpoint profile at ~46us/row); the helpers live in
# types/columns.py so this import stays within the base layer
from ..types.columns import (
    NumericColumn,
    TextColumn,
    column_from_list,
    decode_numeric,
    decode_text,
)
from .contract import SchemaContract

log = logging.getLogger("transmogrifai_tpu.schema")

#: JS divergence above this logs a drift WARNING (once per feature per
#: monitor); scores are always surfaced in telemetry regardless
DEFAULT_WARN_THRESHOLD = 0.1

#: the WARNING (not the score) waits for this many observed rows: a
#: 4-row batch legitimately has JS ~0.6 against a 32-bin training
#: histogram from pure sampling noise, and a latched false alarm is
#: worse than a slightly later true one
DEFAULT_MIN_WARN_ROWS = 256


class DriftMonitor:
    """Running serve-side distributions + JS drift scores per feature."""

    def __init__(
        self,
        contract: SchemaContract,
        warn_threshold: float = DEFAULT_WARN_THRESHOLD,
        min_warn_rows: int = DEFAULT_MIN_WARN_ROWS,
    ) -> None:
        self.contract = contract
        self.warn_threshold = float(warn_threshold)
        self.min_warn_rows = int(min_warn_rows)
        self._accum: dict[str, FeatureDistribution] = {}
        self._warned: set[str] = set()
        self._lock = threading.Lock()
        self.batches_observed = 0
        # only features with a captured training distribution can drift-
        # score; numeric bins reuse the training value_range so the two
        # histograms share edges (Summary.scala's train->score hand-off)
        self._watch: list[tuple[str, Any, Optional[tuple], int]] = []
        for name, train_dist in contract.distributions.items():
            spec = contract.feature(name)
            if spec is None or spec.is_response:
                continue
            if spec.kind not in ("numeric", "text"):
                continue
            ftype = contract.ftype_of(name)
            n_bins = (
                max(len(train_dist.histogram) - 2, 1)
                if spec.kind == "numeric" else 0
            )
            self._watch.append(
                (name, ftype, train_dist.value_range, n_bins)
            )

    def observe(self, records: Sequence[Mapping[str, Any]]) -> None:
        """Fold one serve batch into the running distributions.  Never
        raises: drift monitoring must not be able to take serving down
        (a mis-typed batch is the schema validator's job, not ours)."""
        if not records:
            return
        for name, ftype, value_range, n_bins in self._watch:
            try:
                if ftype.kind == "numeric":
                    vals, mask = decode_numeric(records, name)
                    col = NumericColumn(vals, mask, ftype)
                elif ftype.kind == "text":
                    col = TextColumn(decode_text(records, name), ftype)
                else:  # pragma: no cover - _watch filters to these kinds
                    col = column_from_list(
                        [r.get(name) for r in records], ftype
                    )
                dist = compute_distribution(
                    name, col,
                    n_bins=n_bins or 100,
                    value_range=value_range,
                )
            except Exception as e:  # noqa: BLE001 - monitoring only
                log.debug("drift observe skipped for %s: %s", name, e)
                continue
            with self._lock:
                prev = self._accum.get(name)
                self._accum[name] = (
                    dist if prev is None else prev.merge(dist)
                )
        with self._lock:
            self.batches_observed += 1

    def scores(self) -> dict[str, float]:
        """Per-feature JS divergence of the accumulated serve
        distribution vs the training one (0 = identical, log2 base so
        1.0 = disjoint support)."""
        out: dict[str, float] = {}
        with self._lock:
            accum = dict(self._accum)
        for name, serve_dist in accum.items():
            train = self.contract.distributions.get(name)
            if train is None:
                continue
            if len(train.histogram) != len(serve_dist.histogram):
                log.warning(
                    "drift score skipped for %s: train/serve histogram "
                    "widths differ (%d vs %d)", name,
                    len(train.histogram), len(serve_dist.histogram),
                )
                continue
            score = train.js_divergence(serve_dist)
            out[name] = round(float(score), 6)
            if (score > self.warn_threshold
                    and serve_dist.count >= self.min_warn_rows
                    and name not in self._warned):
                self._warned.add(name)
                log.warning(
                    "op_data_metrics feature %r drifted: JS divergence "
                    "%.4f vs training distribution (threshold %.2f)",
                    name, score, self.warn_threshold,
                )
        return out

    def rows_observed(self, name: str) -> int:
        with self._lock:
            d = self._accum.get(name)
            return 0 if d is None else d.count

    def reset(self) -> "DriftMonitor":
        """Drop the accumulated serve distributions: the windowed-merge
        seam (ISSUE 16).  The cumulative monoid merge above is the right
        default for a serving endpoint (one long-lived score-vs-train
        comparison), but it DILUTES late shifts: after a million
        baseline rows, a thousand drifted rows move the accumulated
        histogram - and therefore the JS score - almost nothing, so a
        continuous trainer watching the cumulative score would detect a
        mid-stream distribution change hours late or never
        (tests/test_continuous.py pins the bias).  A drift-triggered
        refit loop instead calls ``reset()`` at each window boundary and
        scores every window against the training contract on its own
        rows.  The warned-once latch clears too: a fresh window is a
        fresh alarm."""
        with self._lock:
            self._accum.clear()
            self._warned.clear()
            self.batches_observed = 0
        return self
