"""Schema contracts: the fit-time data shape a model is entitled to.

Counterpart of the reference's feature-validation contract (reference:
core/.../filters/RawFeatureFilter.scala compares score-time feature
distributions against the training Summary; OpWorkflowModelWriter
persists the trained feature metadata): at fit time the workflow
captures every raw feature's name, dtype, nullability and a
:class:`~..filters.feature_distribution.FeatureDistribution` summary,
and the contract travels INSIDE the crash-consistent model artifact
(``schema.json``, checksummed by the manifest — serialization/
model_io.py).  At serve time the endpoint and the local scorer validate
incoming batches against it: a renamed / re-typed / missing column is a
named :class:`SchemaDriftError`, and distribution drift is scored by JS
divergence against the training histograms (schema/drift.py).
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Mapping, Optional, Sequence

import numpy as np

from ..filters.feature_distribution import (
    FeatureDistribution,
    compute_distribution,
)
from ..types.feature_types import feature_type_by_name

log = logging.getLogger("transmogrifai_tpu.schema")

CONTRACT_FORMAT_VERSION = 1

#: rows examined per batch for value-level type checks: enough to catch
#: a re-typed column immediately, bounded so validation stays O(1)-ish
#: per batch no matter the batch size
TYPE_CHECK_SAMPLE_ROWS = 64

#: fit-time distribution capture is capped: histograms stabilize long
#: before this, and text bucketing is per-value python work
CAPTURE_MAX_ROWS = 100_000

_NUMERIC_OK = (bool, int, float, np.integer, np.floating, np.bool_)


class SchemaDriftError(ValueError):
    """A serve batch violates the training schema contract; the message
    names every offending feature.  ``violations`` carries the
    structured list: dicts of kind ('missing_column' | 'extra_column' |
    'type_flip' | 'injected'), feature, detail.  A plain string builds
    a pre-rendered error (the scheduler's shed-marker relay)."""

    def __init__(self, violations) -> None:
        if isinstance(violations, str):
            self.violations: list[dict] = []
            super().__init__(violations)
            return
        self.violations = list(violations)
        parts = [
            f"{v['kind']}: {v['feature']}" + (
                f" ({v['detail']})" if v.get("detail") else ""
            )
            for v in self.violations
        ]
        super().__init__(
            "serve batch violates the training schema contract — "
            + "; ".join(parts)
        )


def log_violations_once(violations: Sequence[dict], warned: set,
                        logger, context: str) -> None:
    """policy='warn' logging shared by every enforcement site (serving
    endpoint, local scorer): each DISTINCT (kind, feature) violation
    logs once per ``warned`` set, so a drifting client cannot flood the
    logs batch after batch."""
    for v in violations:
        sig = (v["kind"], v["feature"])
        if sig in warned:
            continue
        warned.add(sig)
        logger.warning(
            "schema drift (policy=warn, %s): %s: %s — %s",
            context, v["kind"], v["feature"], v.get("detail", ""),
        )


def collect_violations(contract, records: Sequence[Mapping[str, Any]],
                       extra_violations: Sequence[dict] = ()) -> list[dict]:
    """THE batch-vs-contract check every serve surface shares (serving
    endpoint, local scorer, registry deployment controller): one
    implementation so registry-driven swaps can never diverge between
    surfaces.  ``extra_violations`` carries caller-injected entries
    (e.g. the ``serving.schema_drift`` fault point); a None contract or
    empty batch validates vacuously."""
    violations = list(extra_violations)
    if contract is not None and records:
        violations.extend(contract.validate_records(records))
    return violations


def apply_drift_policy(violations: Sequence[dict], policy: str,
                       warned: set, logger, context: str) -> bool:
    """The policy dispatch shared by the same surfaces: raises
    :class:`SchemaDriftError` under ``policy='raise'``, warns once per
    distinct violation under ``'warn'``, and returns True exactly when
    the caller must SHED the batch (``policy='shed'`` with violations).
    Telemetry accounting stays with the caller — it happens BEFORE this
    call so a raised error is still counted."""
    if not violations:
        return False
    if policy == "raise":
        raise SchemaDriftError(violations)
    if policy == "warn":
        log_violations_once(violations, warned, logger, context)
        return False
    return policy == "shed"


@dataclass
class FeatureSpec:
    """One raw feature's contracted shape."""

    name: str
    type_name: str
    kind: str
    nullable: bool
    is_response: bool = False

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "type": self.type_name,
            "kind": self.kind,
            "nullable": self.nullable,
            "is_response": self.is_response,
        }

    @staticmethod
    def from_json(doc: dict) -> "FeatureSpec":
        return FeatureSpec(
            name=doc["name"],
            type_name=doc["type"],
            kind=doc["kind"],
            nullable=bool(doc["nullable"]),
            is_response=bool(doc.get("is_response", False)),
        )


class SchemaContract:
    """Raw-feature schema + training distributions, captured at fit."""

    def __init__(
        self,
        features: Sequence[FeatureSpec],
        distributions: Optional[Mapping[str, FeatureDistribution]] = None,
        n_rows: int = 0,
        sampled_rows: int = 0,
        captured_at: Optional[float] = None,
    ) -> None:
        self.features = list(features)
        self.distributions = dict(distributions or {})
        self.n_rows = int(n_rows)
        self.sampled_rows = int(sampled_rows)
        self.captured_at = (
            time.time() if captured_at is None else float(captured_at)
        )
        self._by_name = {f.name: f for f in self.features}

    # -- capture ------------------------------------------------------------
    @classmethod
    def capture(
        cls,
        raw_features: Sequence,
        dataset,
        n_bins: int = 32,
        max_rows: int = CAPTURE_MAX_ROWS,
    ) -> "SchemaContract":
        """Fit-time capture from the (post-RawFeatureFilter) raw data.

        Distribution capture samples an even stride of at most
        ``max_rows`` rows; columns whose type has no distribution (maps,
        predictions) keep their FeatureSpec with no histogram.
        """
        specs = [
            FeatureSpec(
                name=f.name,
                type_name=f.ftype.__name__,
                kind=f.ftype.kind,
                nullable=not f.ftype.non_nullable,
                is_response=bool(f.is_response),
            )
            for f in raw_features
        ]
        dists: dict[str, FeatureDistribution] = {}
        n = len(dataset) if dataset is not None else 0
        sampled = 0
        if n:
            if n > max_rows:
                idx = np.linspace(0, n - 1, max_rows).astype(np.int64)
                sample = dataset.take(idx)
                sampled = max_rows
            else:
                sample = dataset
                sampled = n
            for spec in specs:
                if spec.name not in dataset:
                    continue
                try:
                    dists[spec.name] = compute_distribution(
                        spec.name, sample[spec.name], n_bins=n_bins
                    )
                except TypeError as e:
                    # no distribution for this column type (maps etc.):
                    # the FeatureSpec still validates structurally
                    log.debug("no distribution captured for %s: %s",
                              spec.name, e)
        return cls(specs, dists, n_rows=n, sampled_rows=sampled)

    # -- lookups ------------------------------------------------------------
    def feature(self, name: str) -> Optional[FeatureSpec]:
        return self._by_name.get(name)

    @property
    def predictor_names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.features if not f.is_response)

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.features)

    # -- serve-time validation ----------------------------------------------
    def validate_records(
        self,
        records: Sequence[Mapping[str, Any]],
        sample_rows: int = TYPE_CHECK_SAMPLE_ROWS,
    ) -> list[dict]:
        """Structural check of a serve batch against the contract;
        returns the violation list (empty = conformant), never raises —
        the POLICY (raise/warn/shed) belongs to the caller.

        * ``missing_column`` — a contracted predictor absent from every
          record of the batch (response features are exempt: scoring
          never requires the label);
        * ``extra_column``  — a key the contract has never heard of (a
          renamed column shows up as missing + extra);
        * ``type_flip``     — a value whose python type contradicts the
          contracted kind (string in a numeric feature, number in a
          text feature), checked over the first ``sample_rows`` rows.
        """
        if not records:
            return []
        violations: list[dict] = []
        # the key scan is deliberately O(batch): a key present in ANY
        # record counts as present (only the per-VALUE type check below
        # is sample-bounded)
        seen_keys: set = set().union(*(r.keys() for r in records))
        for spec in self.features:
            if spec.is_response:
                continue
            if spec.name not in seen_keys:
                violations.append({
                    "kind": "missing_column",
                    "feature": spec.name,
                    "detail": f"contracted {spec.type_name} column absent "
                              "from the batch",
                })
        for key in sorted(seen_keys):
            if key not in self._by_name:
                violations.append({
                    "kind": "extra_column",
                    "feature": key,
                    "detail": "column not in the training contract",
                })
        for spec in self.features:
            if spec.is_response or spec.name not in seen_keys:
                continue
            bad = self._first_type_flip(spec, records[:sample_rows])
            if bad is not None:
                violations.append(bad)
        return violations

    def _first_type_flip(
        self, spec: FeatureSpec, records: Sequence[Mapping[str, Any]]
    ) -> Optional[dict]:
        for i, r in enumerate(records):
            v = r.get(spec.name)
            if v is None:
                continue
            if spec.kind == "numeric" and not isinstance(v, _NUMERIC_OK):
                return {
                    "kind": "type_flip",
                    "feature": spec.name,
                    "detail": f"row {i}: expected {spec.type_name} "
                              f"(numeric), got {type(v).__name__} "
                              f"{str(v)[:40]!r}",
                }
            if spec.kind == "text" and not isinstance(v, str):
                return {
                    "kind": "type_flip",
                    "feature": spec.name,
                    "detail": f"row {i}: expected {spec.type_name} (text), "
                              f"got {type(v).__name__} {str(v)[:40]!r}",
                }
        return None

    def ftype_of(self, name: str):
        """The contracted FeatureType class (for rebuilding columns on
        the drift path)."""
        spec = self._by_name.get(name)
        return None if spec is None else feature_type_by_name(spec.type_name)

    # -- persistence --------------------------------------------------------
    def to_json(self) -> dict:
        return {
            "format_version": CONTRACT_FORMAT_VERSION,
            "captured_at": self.captured_at,
            "n_rows": self.n_rows,
            "sampled_rows": self.sampled_rows,
            "features": [f.to_json() for f in self.features],
            "distributions": {
                name: d.to_json() for name, d in self.distributions.items()
            },
        }

    @staticmethod
    def from_json(doc: dict) -> "SchemaContract":
        return SchemaContract(
            features=[FeatureSpec.from_json(f) for f in doc["features"]],
            distributions={
                name: FeatureDistribution.from_json(d)
                for name, d in doc.get("distributions", {}).items()
            },
            n_rows=int(doc.get("n_rows", 0)),
            sampled_rows=int(doc.get("sampled_rows", 0)),
            captured_at=doc.get("captured_at"),
        )
