"""Data-plane robustness: schema contracts, quarantine, drift guards.

The train/serve-skew layer the reference builds into RawFeatureFilter
(reference: core/.../filters/RawFeatureFilter.scala — score-vs-train
distribution comparison gating features before they reach a model) and
that tf.data treats as a first-class production concern (PAPERS.md:
input pipelines own their error policies and telemetry).  Three pieces:

* :class:`SchemaContract` — raw-feature names, dtypes, nullability and
  per-feature :class:`~..filters.feature_distribution.FeatureDistribution`
  summaries captured at fit time, persisted inside the crash-consistent
  model artifact (serialization/model_io.py ``schema.json``, checksummed
  by the manifest), and enforced against serve-time batches
  (``SchemaDriftError`` / ``drift_policy`` on the serving endpoint).
* Quarantine-mode ingestion — readers accept ``errors="quarantine"``:
  malformed / type-flipped / truncated rows land in a bounded
  :class:`QuarantineBuffer` (row index, payload excerpt, reason) with
  exact counts in :class:`DataTelemetry` instead of aborting the ingest
  (``errors="strict"``) or silently coercing (``errors="coerce"``, the
  legacy default).
* :class:`DriftMonitor` — serve-side running FeatureDistributions merged
  batch-by-batch (the monoid the reference reduces over partitions),
  scored against the training contract by JS divergence.
"""
from .contract import (
    FeatureSpec,
    SchemaContract,
    SchemaDriftError,
    apply_drift_policy,
    collect_violations,
)
from .drift import DriftMonitor
from .quarantine import (
    ERROR_MODES,
    DataTelemetry,
    MalformedRowError,
    QuarantineBuffer,
    QuarantinedRow,
    check_errors_mode,
    data_telemetry,
    reset_data_telemetry,
)

__all__ = [
    "ERROR_MODES",
    "DataTelemetry",
    "DriftMonitor",
    "FeatureSpec",
    "MalformedRowError",
    "QuarantineBuffer",
    "QuarantinedRow",
    "SchemaContract",
    "SchemaDriftError",
    "apply_drift_policy",
    "check_errors_mode",
    "collect_violations",
    "data_telemetry",
    "reset_data_telemetry",
]
