"""Bounded request/response transport between router and replicas.

The transport tier of the scale-out serving fleet (ISSUE 14/17;
reference frame: the TensorFlow system paper's position that throughput
scaling comes from many coordinated workers behind one dispatch layer,
arXiv 1605.08695 §3 - the dataflow workers there talk over explicit
Send/Recv edges, and this module is that edge for serving): one stream
socket per replica - AF_UNIX for on-host replicas (the fast path), TCP
for cross-host ones - carrying length-framed messages, with a wire
format deliberately split into a tiny header/meta part and an OPAQUE
payload:

* the router never (un)pickles record batches - it forwards the
  caller's encoded payload bytes verbatim and hands responses back with
  the result payload still encoded (decoded lazily by the caller), so
  the dispatch layer's per-row cost is framing + syscalls, not object
  graph serialization.  That is what keeps one router process able to
  feed 4+ replicas at aggregate rates a single GIL could never pickle;
* encode-once/retry-many: a batch is encoded at submission and the
  SAME bytes are re-sent when a dead or ejected replica's in-flight
  requests are retried on survivors (at-least-once delivery with
  idempotent scoring - the fleet may score a row twice, the caller
  sees it once);
* every blocking wait is bounded at ``QUANTUM_S`` (50 ms) quanta - the
  PR-8 pipeline discipline, style-gated for fleet/ in
  tests/test_style.py: sockets run under ``settimeout(QUANTUM_S)`` and
  every send/recv loop re-checks its stop flag/deadline per quantum, so
  a wedged or vanished peer can never block the router or a worker
  forever (a SIGKILLed peer closes the socket -> ``ChannelClosedError``
  immediately);
* every frame carries a CRC32 of its body.  A unix socket cannot
  corrupt bytes, but a TCP path crossing NICs/middleboxes can (and
  TCP's own 16-bit checksum provably lets corruption through at scale),
  so a mismatch raises :class:`ChannelProtocolError` - counted on the
  channel, surfaced in the router's view, and NEVER decoded into a
  garbage batch.  A corrupt stream is unsyncable, so the channel closes
  and the health machinery reconnects.

Addressing: ``host:port`` / ``tcp://host:port`` selects TCP (keepalive
tuned so a silently-dead cross-host peer is detected in seconds, Nagle
off so small frames are not delayed behind a timer); anything else is
an AF_UNIX socket path.  TCP connections complete an ``OP_HELLO``
handshake (magic + peer identity, bounded by its own timeout) before
the channel is handed to the router - a cross-wired port or a foreign
listener fails loudly at connect, not as garbage frames mid-serve.

Deterministic network-fault seams (driven by the TX_FAULTS framework,
see faults/injection.py; ``delay=`` is the impairment duration):

* ``fleet.partition``      - on a data send, the channel drops BOTH
  directions for ``delay`` seconds: outbound frames vanish, inbound
  bytes queue unread in the kernel until the window heals;
* ``fleet.half_open``      - outbound frames vanish for ``delay``
  seconds but the channel keeps reading: the peer that accepts work
  and never responds, the drill a unix socketpair cannot express;
* ``channel.corrupt_frame``- the frame goes out with a flipped CRC, so
  the receiver proves the integrity check end to end;
* ``fleet.reconnect_storm``- :func:`connect` drops the connection
  before the handshake, drilling rate-bounded reconnect probes.

Fault *consumption* happens only on data sends (and connects) - never
on recv polls - so ``on=N``/``every=N`` trigger counts are a
deterministic function of traffic, not of idle-poll timing.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
import zlib
from typing import Any, Optional, Sequence, Tuple

from ..faults import injection as _faults

#: the bounded-wait quantum every blocking socket operation runs under
QUANTUM_S = 0.05

#: message ops (u8 on the wire)
OP_SCORE = 1
OP_RESULT = 2
OP_ERROR = 3
OP_CONTROL = 4
OP_CONTROL_RESULT = 5
OP_HELLO = 6

#: frame = u64 body length + u32 CRC32(body); body = u8 op, u64 req_id,
#: u32 meta_len, meta bytes (pickled small dict), payload (the rest,
#: opaque)
_FRAME = struct.Struct("<QI")
_HEADER = struct.Struct("<BQI")

#: a frame larger than this is a protocol error, not a request (guards
#: the length-prefix read against garbage bytes from a foreign writer)
MAX_FRAME_BYTES = 1 << 31

#: handshake identity: both ends must present this or the connection is
#: cross-wired (wrong port, foreign service) and fails at connect
WIRE_MAGIC = "txfleet2"

#: default bound on the OP_HELLO round trip at connect
HANDSHAKE_TIMEOUT_S = 5.0

#: impairment window when an armed partition/half_open spec has no
#: ``delay=`` field
DEFAULT_IMPAIR_S = 1.0

#: TCP keepalive: first probe after 5 s idle, then every 2 s, dead
#: after 3 missed - a silently-vanished cross-host peer (power loss,
#: cable pull: no FIN, no RST) surfaces as ChannelClosedError in ~11 s
#: instead of the kernel default's ~2 h
_TCP_KEEPALIVE = (("TCP_KEEPIDLE", 5), ("TCP_KEEPINTVL", 2),
                  ("TCP_KEEPCNT", 3))


class ChannelClosedError(RuntimeError):
    """The peer closed (or was SIGKILLed out from under) the socket."""


class ChannelTimeoutError(TimeoutError):
    """A bounded channel operation ran past its deadline."""


class ChannelProtocolError(RuntimeError):
    """The stream carried bytes that are not a valid frame (CRC
    mismatch, oversized length prefix, undecodable meta, bad
    handshake).  The channel is unsyncable past this point and closes;
    the erroring frame is counted, never decoded into a batch."""


def parse_address(address: str) -> Tuple[str, Any]:
    """``address`` -> ``("tcp", (host, port))`` or ``("unix", path)``.

    ``tcp://host:port`` is explicit; a bare ``host:port`` whose port
    parses as an integer and which contains no path separator is
    inferred as TCP; everything else is an AF_UNIX socket path.
    """
    if address.startswith("tcp://"):
        host, _, port = address[len("tcp://"):].rpartition(":")
        return "tcp", (host or "127.0.0.1", int(port))
    host, sep, port = address.rpartition(":")
    if sep and host and os.sep not in address and port.isdigit():
        return "tcp", (host, int(port))
    return "unix", address


def _tune_tcp(sock: socket.socket) -> None:
    """Latency + liveness tuning for TCP channels: Nagle off (length-
    framed request/response must not wait on a coalescing timer) and
    aggressive keepalive (see :data:`_TCP_KEEPALIVE`)."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        for name, val in _TCP_KEEPALIVE:
            if hasattr(socket, name):
                sock.setsockopt(socket.IPPROTO_TCP,
                                getattr(socket, name), val)
    except OSError:
        pass  # tuning is best-effort; an untuned channel still works


def encode_records(records: Sequence[Any]) -> bytes:
    """Record batch -> opaque payload bytes (the caller-side half of
    the encode-once contract; a retried request reuses these bytes)."""
    return pickle.dumps(list(records), protocol=5)


def decode_records(payload: bytes) -> list:
    return pickle.loads(payload)


def encode_results(results: Sequence[Any]) -> bytes:
    """Score results -> opaque payload bytes (worker side; the router
    relays them undecoded and the caller decodes lazily)."""
    return pickle.dumps(list(results), protocol=5)


def decode_results(payload: bytes) -> list:
    return pickle.loads(payload)


class FleetChannel:
    """Length-framed, CRC-checked messages over one connected stream
    socket (AF_UNIX or TCP) with every blocking primitive bounded at
    :data:`QUANTUM_S` quanta.

    Thread contract: any number of threads may :meth:`send` (a lock
    serializes frames); exactly ONE thread may :meth:`recv` (the
    router's per-replica receiver thread / the worker's serve loop).
    """

    #: socket buffer request: large enough that a whole wire batch
    #: lands in one or two kernel chunks - the receiver then wakes
    #: once or twice per message instead of once per 64 KB default
    #: buffer (the wakeup churn, not the memcpy, dominates the
    #: router's per-row CPU; the kernel clamps this to wmem_max)
    SOCK_BUF_BYTES = 4 << 20

    def __init__(self, sock: socket.socket) -> None:
        sock.settimeout(QUANTUM_S)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                            self.SOCK_BUF_BYTES)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                            self.SOCK_BUF_BYTES)
        except OSError:
            pass  # clamped/refused: the default buffer still works
        if sock.family in (socket.AF_INET, socket.AF_INET6):
            _tune_tcp(sock)
        self._sock = sock
        self._send_lock = threading.Lock()
        self.closed = False
        #: handshake meta from the peer (set by connect(); workers
        #: leave it None - they learn the router exists by serving it)
        self.peer: Optional[dict] = None
        # -- injected-impairment window (fault drills) --
        self._impair_mode: Optional[str] = None
        self._impair_until = 0.0
        # -- integrity/fault counters (read by router + worker obs) --
        self.protocol_errors = 0   # CRC/length/meta violations seen
        self.frames_dropped = 0    # outbound frames eaten by a window
        self.partitions = 0        # partition windows opened
        self.half_opens = 0        # half-open windows opened
        self.corrupt_injected = 0  # frames sent with a flipped CRC

    def stats(self) -> dict:
        """Integrity/fault counters as one plain dict (obs plane)."""
        return {
            "protocol_errors": self.protocol_errors,
            "frames_dropped": self.frames_dropped,
            "partitions": self.partitions,
            "half_opens": self.half_opens,
            "corrupt_injected": self.corrupt_injected,
        }

    # -- injected impairment ------------------------------------------------
    def _impairment(self) -> Optional[str]:
        """The currently-open impairment window's mode, or None.  Never
        consumes fault-trigger calls (recv polls must not burn
        ``on=N`` counts)."""
        if self._impair_mode is not None:
            if time.monotonic() < self._impair_until:
                return self._impair_mode
            self._impair_mode = None
        return None

    def _maybe_open_impairment(self) -> Optional[str]:
        """Called once per DATA send: extend/open a partition or
        half-open window from the fault plan.  Returns the active
        mode, or None for a healthy channel."""
        mode = self._impairment()
        if mode is not None:
            return mode
        for point, mode in (("fleet.partition", "partition"),
                            ("fleet.half_open", "half_open")):
            spec = _faults.fires(point)
            if spec is not None:
                self._impair_mode = mode
                self._impair_until = (time.monotonic()
                                      + (spec.delay or DEFAULT_IMPAIR_S))
                if mode == "partition":
                    self.partitions += 1
                else:
                    self.half_opens += 1
                return mode
        return None

    # -- low-level bounded IO -----------------------------------------------
    def _send_all(self, data, deadline: Optional[float],
                  stop: Optional[threading.Event]) -> None:
        view = memoryview(data)
        off = 0
        while off < len(view):
            if stop is not None and stop.is_set():
                raise ChannelClosedError("channel stopping")
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError("send deadline exceeded")
            try:
                off += self._sock.send(view[off:])
            except socket.timeout:
                continue
            except OSError as e:
                self.closed = True
                raise ChannelClosedError(f"peer gone mid-send: {e}") from e

    def send(self, op: int, req_id: int, meta: dict,
             payload=b"", timeout_s: Optional[float] = None,
             stop: Optional[threading.Event] = None) -> None:
        """Send one framed message.  Head+meta and the payload go out
        in ONE ``sendmsg`` gather call when possible - no
        concatenation copy of a potentially-large batch and one fewer
        syscall per message (the router's per-row cost is syscalls +
        kernel copies; see the fleet CPU floor)."""
        meta_b = pickle.dumps(meta, protocol=5)
        body_len = _HEADER.size + len(meta_b) + len(payload)
        head_body = _HEADER.pack(op, req_id, len(meta_b)) + meta_b
        crc = zlib.crc32(head_body)
        if payload:
            crc = zlib.crc32(payload, crc)
        if op != OP_HELLO and _faults.active():
            # the network-fault seam: handshakes are connection
            # establishment, not the drill surface, so only data
            # frames open/extend impairment windows or get corrupted
            if self._maybe_open_impairment() is not None:
                self.frames_dropped += 1
                return  # the frame vanishes into the partition
            if _faults.fires("channel.corrupt_frame") is not None:
                crc ^= 0x5A5A5A5A
                self.corrupt_injected += 1
        head = _FRAME.pack(body_len, crc) + head_body
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._send_lock:
            if payload:
                try:
                    sent = self._sock.sendmsg([head, payload])
                except socket.timeout:
                    sent = 0
                except OSError as e:
                    self.closed = True
                    raise ChannelClosedError(
                        f"peer gone mid-send: {e}") from e
                if sent >= len(head) + len(payload):
                    return
                # partial gather write (full socket buffer): finish
                # byte-exactly with the bounded loop
                if sent < len(head):
                    self._send_all(memoryview(head)[sent:], deadline,
                                   stop)
                    self._send_all(payload, deadline, stop)
                else:
                    self._send_all(
                        memoryview(payload)[sent - len(head):],
                        deadline, stop)
            else:
                self._send_all(head, deadline, stop)

    def _recv_exact(self, n: int, stop: Optional[threading.Event],
                    idle_return: bool) -> Optional[bytearray]:
        """Read exactly ``n`` bytes into ONE preallocated buffer via
        ``recv_into`` - the payload never makes an extra userspace copy
        (the router's per-row cost is this loop; see the fleet CPU
        floor in tests/test_fleet.py).  Returns None when
        ``idle_return`` and a quantum passed with nothing read yet;
        once bytes have arrived it keeps reading - a live peer
        mid-frame finishes, a dead one raises."""
        buf = bytearray(n)
        view = memoryview(buf)
        off = 0
        while off < n:
            if stop is not None and stop.is_set():
                return None
            try:
                k = self._sock.recv_into(view[off:], n - off)
            except socket.timeout:
                if idle_return and off == 0:
                    return None
                continue
            except OSError as e:
                self.closed = True
                raise ChannelClosedError(f"peer gone mid-recv: {e}") from e
            if k == 0:
                self.closed = True
                raise ChannelClosedError("peer closed the channel")
            off += k
        return buf

    def recv(self, stop: Optional[threading.Event] = None,
             idle_return: bool = True) -> Optional[tuple]:
        """One message as ``(op, req_id, meta, payload)``, or ``None``
        when idle for a quantum (``idle_return``) or ``stop`` is set.
        The payload comes back as a memoryview over the single receive
        buffer (``decode_records``/``decode_results`` consume it
        directly; ``send`` re-sends it on failover without a copy).
        Raises :class:`ChannelClosedError` on peer death/EOF and
        :class:`ChannelProtocolError` on a corrupt frame (the stream
        is unsyncable past it; the channel is closed)."""
        if self._impairment() == "partition":
            # both directions dead: leave inbound bytes queued in the
            # kernel until the window heals (exactly what a network
            # partition does to data in flight)
            time.sleep(QUANTUM_S)
            return None
        head = self._recv_exact(_FRAME.size, stop, idle_return)
        if head is None:
            return None
        body_len, crc_expected = _FRAME.unpack_from(head)
        if body_len > MAX_FRAME_BYTES:
            self.protocol_errors += 1
            self.closed = True
            raise ChannelProtocolError(
                f"oversized frame ({body_len} bytes): protocol corruption"
            )
        body = self._recv_exact(body_len, stop, idle_return=False)
        if body is None:
            return None
        if zlib.crc32(body) != crc_expected:
            self.protocol_errors += 1
            self.closed = True
            raise ChannelProtocolError(
                f"frame CRC mismatch ({body_len}-byte body): corrupt "
                "stream, closing channel"
            )
        op, req_id, meta_len = _HEADER.unpack_from(body)
        meta_off = _HEADER.size
        try:
            meta = pickle.loads(
                memoryview(body)[meta_off:meta_off + meta_len])
        except Exception as e:
            self.protocol_errors += 1
            self.closed = True
            raise ChannelProtocolError(
                f"undecodable frame meta (op={op}): {e}") from e
        payload = memoryview(body)[meta_off + meta_len:body_len]
        return op, req_id, meta, payload

    # -- handshake ----------------------------------------------------------
    def handshake_client(self, timeout_s: float = HANDSHAKE_TIMEOUT_S,
                         stop: Optional[threading.Event] = None) -> dict:
        """Send OP_HELLO and wait (bounded) for the peer's OP_HELLO
        reply; returns the peer's meta ({"magic", "instance", "pid"}).
        A wrong-magic peer or silence past ``timeout_s`` fails loudly
        here instead of as garbage frames mid-serve."""
        self.send(OP_HELLO, 0, {"magic": WIRE_MAGIC, "pid": os.getpid()},
                  timeout_s=timeout_s, stop=stop)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() <= deadline:
            if stop is not None and stop.is_set():
                raise ChannelClosedError("stopping mid-handshake")
            msg = self.recv(stop=stop)
            if msg is None:
                continue
            op, _rid, meta, _payload = msg
            if op != OP_HELLO or meta.get("magic") != WIRE_MAGIC:
                self.protocol_errors += 1
                self.closed = True
                raise ChannelProtocolError(
                    f"bad handshake reply (op={op}, "
                    f"magic={meta.get('magic')!r}): cross-wired peer"
                )
            self.peer = dict(meta)
            return self.peer
        raise ChannelTimeoutError(
            f"no handshake reply within {timeout_s}s")

    def hello_reply_meta(self) -> dict:
        """The server-side half of the handshake (the worker attaches
        its identity so the router can verify it reached the replica
        it meant to)."""
        return {"magic": WIRE_MAGIC, "pid": os.getpid()}

    def close(self) -> None:
        self.closed = True
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# connection establishment (both bounded)
# ---------------------------------------------------------------------------
def listen(address: str) -> socket.socket:
    """Bind + listen a worker's socket - AF_UNIX path (stale file
    replaced) or ``host:port`` TCP; the returned listener runs under
    the bounded-accept quantum."""
    scheme, target = parse_address(address)
    if scheme == "tcp":
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind(target)
    else:
        try:
            os.unlink(target)
        except OSError:
            pass  # first bind: nothing stale to replace
        lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        lsock.bind(target)
    # backlog 2: the controller's restart reconnect and the router's
    # readmission probe may race to the same worker; neither should
    # see a refused connect
    lsock.listen(2)
    lsock.settimeout(QUANTUM_S)
    return lsock


def accept(lsock: socket.socket, timeout_s: float,
           stop: Optional[threading.Event] = None
           ) -> Optional[FleetChannel]:
    """Accept one peer within ``timeout_s`` (quantum-bounded); None on
    deadline/stop.  At least one accept attempt is always made, so
    ``timeout_s=0.0`` is a single bounded poll (the worker's
    newest-connection-wins idle check)."""
    deadline = time.monotonic() + timeout_s
    while True:
        if stop is not None and stop.is_set():
            return None
        try:
            sock, _ = lsock.accept()
        except socket.timeout:
            if time.monotonic() > deadline:
                return None
            continue
        except OSError as e:
            raise ChannelClosedError(f"listener closed: {e}") from e
        return FleetChannel(sock)


def connect(address: str, timeout_s: float = 30.0,
            handshake: bool = True,
            handshake_timeout_s: float = HANDSHAKE_TIMEOUT_S
            ) -> FleetChannel:
    """Connect to a worker's socket (AF_UNIX path or ``host:port``
    TCP), retrying per quantum until the worker has bound it (startup
    race) or the deadline passes, then complete the bounded OP_HELLO
    handshake (the worker replies from its serve loop, so a returned
    channel is one a live replica is actually serving)."""
    scheme, target = parse_address(address)
    family = socket.AF_INET if scheme == "tcp" else socket.AF_UNIX
    deadline = time.monotonic() + timeout_s
    while True:
        sock = socket.socket(family, socket.SOCK_STREAM)
        sock.settimeout(QUANTUM_S)
        try:
            sock.connect(target)
        except (FileNotFoundError, ConnectionRefusedError, socket.timeout,
                OSError):
            sock.close()
            if time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"no worker listening at {address} within "
                    f"{timeout_s}s"
                ) from None
            time.sleep(QUANTUM_S)
            continue
        if _faults.fires("fleet.reconnect_storm") is not None:
            sock.close()
            raise ChannelProtocolError(
                f"injected reconnect storm: connection to {address} "
                "dropped before handshake")
        chan = FleetChannel(sock)
        if not handshake:
            return chan
        try:
            chan.handshake_client(handshake_timeout_s)
            return chan
        except ChannelProtocolError:
            chan.close()
            raise  # wrong magic / bad frame: permanent, never retried
        except (ChannelClosedError, ChannelTimeoutError):
            # the worker accepted but is busy serving another channel
            # (its newest-connection-wins accept loop will pick us up
            # on its next idle poll - or a restart race closed us):
            # retry a FRESH connection until the overall deadline
            chan.close()
            if time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"worker at {address} accepted but did not complete "
                    f"the handshake within {timeout_s}s"
                ) from None
            time.sleep(QUANTUM_S)
