"""Bounded request/response IPC channel between router and replicas.

The transport tier of the scale-out serving fleet (ISSUE 14; reference
frame: the TensorFlow system paper's position that throughput scaling
comes from many coordinated workers behind one dispatch layer, arXiv
1605.08695 §3 - the dataflow workers there talk over explicit Send/Recv
edges, and this module is that edge for serving): one AF_UNIX stream
socket per replica carrying length-framed messages, with a wire format
deliberately split into a tiny header/meta part and an OPAQUE payload:

* the router never (un)pickles record batches - it forwards the
  caller's encoded payload bytes verbatim and hands responses back with
  the result payload still encoded (decoded lazily by the caller), so
  the dispatch layer's per-row cost is framing + syscalls, not object
  graph serialization.  That is what keeps one router process able to
  feed 4+ replicas at aggregate rates a single GIL could never pickle;
* encode-once/retry-many: a batch is encoded at submission and the
  SAME bytes are re-sent when a SIGKILLed replica's in-flight requests
  are retried on survivors (at-least-once delivery with idempotent
  scoring - the fleet may score a row twice, the caller sees it once);
* every blocking wait is bounded at ``QUANTUM_S`` (50 ms) quanta - the
  PR-8 pipeline discipline, style-gated for fleet/ in
  tests/test_style.py: sockets run under ``settimeout(QUANTUM_S)`` and
  every send/recv loop re-checks its stop flag/deadline per quantum, so
  a wedged or vanished peer can never block the router or a worker
  forever (a SIGKILLed peer closes the socket -> ``ChannelClosedError``
  immediately).
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Optional, Sequence

#: the bounded-wait quantum every blocking socket operation runs under
QUANTUM_S = 0.05

#: message ops (u8 on the wire)
OP_SCORE = 1
OP_RESULT = 2
OP_ERROR = 3
OP_CONTROL = 4
OP_CONTROL_RESULT = 5

#: frame = u64 body length; body = u8 op, u64 req_id, u32 meta_len,
#: meta bytes (pickled small dict), payload bytes (the rest, opaque)
_FRAME = struct.Struct("<Q")
_HEADER = struct.Struct("<BQI")

#: a frame larger than this is a protocol error, not a request (guards
#: the length-prefix read against garbage bytes from a foreign writer)
MAX_FRAME_BYTES = 1 << 31


class ChannelClosedError(RuntimeError):
    """The peer closed (or was SIGKILLed out from under) the socket."""


class ChannelTimeoutError(TimeoutError):
    """A bounded channel operation ran past its deadline."""


def encode_records(records: Sequence[Any]) -> bytes:
    """Record batch -> opaque payload bytes (the caller-side half of
    the encode-once contract; a retried request reuses these bytes)."""
    return pickle.dumps(list(records), protocol=5)


def decode_records(payload: bytes) -> list:
    return pickle.loads(payload)


def encode_results(results: Sequence[Any]) -> bytes:
    """Score results -> opaque payload bytes (worker side; the router
    relays them undecoded and the caller decodes lazily)."""
    return pickle.dumps(list(results), protocol=5)


def decode_results(payload: bytes) -> list:
    return pickle.loads(payload)


class FleetChannel:
    """Length-framed messages over one connected AF_UNIX socket with
    every blocking primitive bounded at :data:`QUANTUM_S` quanta.

    Thread contract: any number of threads may :meth:`send` (a lock
    serializes frames); exactly ONE thread may :meth:`recv` (the
    router's per-replica receiver thread / the worker's serve loop).
    """

    #: socket buffer request: large enough that a whole wire batch
    #: lands in one or two kernel chunks - the receiver then wakes
    #: once or twice per message instead of once per 64 KB default
    #: buffer (the wakeup churn, not the memcpy, dominates the
    #: router's per-row CPU; the kernel clamps this to wmem_max)
    SOCK_BUF_BYTES = 4 << 20

    def __init__(self, sock: socket.socket) -> None:
        sock.settimeout(QUANTUM_S)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                            self.SOCK_BUF_BYTES)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                            self.SOCK_BUF_BYTES)
        except OSError:
            pass  # clamped/refused: the default buffer still works
        self._sock = sock
        self._send_lock = threading.Lock()
        self.closed = False

    # -- low-level bounded IO -----------------------------------------------
    def _send_all(self, data, deadline: Optional[float],
                  stop: Optional[threading.Event]) -> None:
        view = memoryview(data)
        off = 0
        while off < len(view):
            if stop is not None and stop.is_set():
                raise ChannelClosedError("channel stopping")
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError("send deadline exceeded")
            try:
                off += self._sock.send(view[off:])
            except socket.timeout:
                continue
            except OSError as e:
                self.closed = True
                raise ChannelClosedError(f"peer gone mid-send: {e}") from e

    def send(self, op: int, req_id: int, meta: dict,
             payload=b"", timeout_s: Optional[float] = None,
             stop: Optional[threading.Event] = None) -> None:
        """Send one framed message.  Head+meta and the payload go out
        in ONE ``sendmsg`` gather call when possible - no
        concatenation copy of a potentially-large batch and one fewer
        syscall per message (the router's per-row cost is syscalls +
        kernel copies; see the fleet CPU floor)."""
        meta_b = pickle.dumps(meta, protocol=5)
        body_len = _HEADER.size + len(meta_b) + len(payload)
        head = (_FRAME.pack(body_len)
                + _HEADER.pack(op, req_id, len(meta_b)) + meta_b)
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._send_lock:
            if payload:
                try:
                    sent = self._sock.sendmsg([head, payload])
                except socket.timeout:
                    sent = 0
                except OSError as e:
                    self.closed = True
                    raise ChannelClosedError(
                        f"peer gone mid-send: {e}") from e
                if sent >= len(head) + len(payload):
                    return
                # partial gather write (full socket buffer): finish
                # byte-exactly with the bounded loop
                if sent < len(head):
                    self._send_all(memoryview(head)[sent:], deadline,
                                   stop)
                    self._send_all(payload, deadline, stop)
                else:
                    self._send_all(
                        memoryview(payload)[sent - len(head):],
                        deadline, stop)
            else:
                self._send_all(head, deadline, stop)

    def _recv_exact(self, n: int, stop: Optional[threading.Event],
                    idle_return: bool) -> Optional[bytearray]:
        """Read exactly ``n`` bytes into ONE preallocated buffer via
        ``recv_into`` - the payload never makes an extra userspace copy
        (the router's per-row cost is this loop; see the fleet CPU
        floor in tests/test_fleet.py).  Returns None when
        ``idle_return`` and a quantum passed with nothing read yet;
        once bytes have arrived it keeps reading - a live peer
        mid-frame finishes, a dead one raises."""
        buf = bytearray(n)
        view = memoryview(buf)
        off = 0
        while off < n:
            if stop is not None and stop.is_set():
                return None
            try:
                k = self._sock.recv_into(view[off:], n - off)
            except socket.timeout:
                if idle_return and off == 0:
                    return None
                continue
            except OSError as e:
                self.closed = True
                raise ChannelClosedError(f"peer gone mid-recv: {e}") from e
            if k == 0:
                self.closed = True
                raise ChannelClosedError("peer closed the channel")
            off += k
        return buf

    def recv(self, stop: Optional[threading.Event] = None,
             idle_return: bool = True) -> Optional[tuple]:
        """One message as ``(op, req_id, meta, payload)``, or ``None``
        when idle for a quantum (``idle_return``) or ``stop`` is set.
        The payload comes back as a memoryview over the single receive
        buffer (``decode_records``/``decode_results`` consume it
        directly; ``send`` re-sends it on failover without a copy).
        Raises :class:`ChannelClosedError` on peer death/EOF."""
        head = self._recv_exact(_FRAME.size, stop, idle_return)
        if head is None:
            return None
        (body_len,) = _FRAME.unpack_from(head)
        if body_len > MAX_FRAME_BYTES:
            self.closed = True
            raise ChannelClosedError(
                f"oversized frame ({body_len} bytes): protocol corruption"
            )
        body = self._recv_exact(body_len, stop, idle_return=False)
        if body is None:
            return None
        op, req_id, meta_len = _HEADER.unpack_from(body)
        meta_off = _HEADER.size
        meta = pickle.loads(
            memoryview(body)[meta_off:meta_off + meta_len])
        payload = memoryview(body)[meta_off + meta_len:body_len]
        return op, req_id, meta, payload

    def close(self) -> None:
        self.closed = True
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# connection establishment (both bounded)
# ---------------------------------------------------------------------------
def listen(socket_path: str) -> socket.socket:
    """Bind + listen a worker's AF_UNIX socket (stale file replaced);
    the returned listener runs under the bounded-accept quantum."""
    try:
        os.unlink(socket_path)
    except OSError:
        pass  # first bind: nothing stale to replace
    lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    lsock.bind(socket_path)
    lsock.listen(1)
    lsock.settimeout(QUANTUM_S)
    return lsock


def accept(lsock: socket.socket, timeout_s: float,
           stop: Optional[threading.Event] = None
           ) -> Optional[FleetChannel]:
    """Accept one peer within ``timeout_s`` (quantum-bounded); None on
    deadline/stop."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() <= deadline:
        if stop is not None and stop.is_set():
            return None
        try:
            sock, _ = lsock.accept()
        except socket.timeout:
            continue
        except OSError as e:
            raise ChannelClosedError(f"listener closed: {e}") from e
        return FleetChannel(sock)
    return None


def connect(socket_path: str, timeout_s: float = 30.0) -> FleetChannel:
    """Connect to a worker's socket, retrying per quantum until the
    worker has bound it (startup race) or the deadline passes."""
    deadline = time.monotonic() + timeout_s
    while True:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(QUANTUM_S)
        try:
            sock.connect(socket_path)
            return FleetChannel(sock)
        except (FileNotFoundError, ConnectionRefusedError, socket.timeout,
                OSError):
            sock.close()
            if time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"no worker listening at {socket_path} within "
                    f"{timeout_s}s"
                ) from None
            time.sleep(QUANTUM_S)
