"""Least-loaded front router over N replica serving workers.

The dispatch layer of the scale-out fleet (ISSUE 14, ROADMAP item 1;
reference frame: the TensorFlow system paper's many-workers-behind-one-
dispatch-layer scaling story, arXiv 1605.08695, with the TpuGraphs
learned-cost-signal idea, arXiv 2308.13490, supplying the load
estimate):

* **front door** - the PR-1 :class:`AdmissionController` unchanged
  (bounded queue, deadline shed at dequeue) with the ISSUE-14
  per-tenant quotas layered on: one chatty tenant sheds with
  ``TenantQuotaError`` while the rest of the fleet's traffic admits.
* **least-loaded dispatch** - one dispatcher thread assigns each queued
  batch to the replica with the smallest *expected wait*:
  ``(in_flight_rows + batch_rows) * service_s_per_row``, where the
  per-replica service time blends a live EWMA over this router's own
  response walls with the replica's shipped obs shard
  (``batch_rows_per_s`` / p99 from its ServingTelemetry view, read via
  :meth:`FleetRouter.refresh_from_shards`) and - when the deployed
  artifact carries an ``autotune.json`` - the PR-13 :class:`CostModel`
  (per-replica ``serve.batch/<instance>`` keys trained online from
  observed batch walls; its prediction replaces the cold-start default
  until live EWMAs exist).
* **at-least-once failover** - requests stay registered on their
  replica until the response arrives; a replica that dies (SIGKILL,
  channel EOF) has every in-flight request re-dispatched to survivors
  from the SAME encoded payload (encode-once), so an accepted request
  is never lost - the fleet may score a row twice, the caller sees
  exactly one response (idempotent scoring).
* **backpressure, never hang** - per-replica in-flight is capped; when
  every replica is full the dispatcher waits in 50 ms quanta while the
  bounded admission queue sheds new submissions at the front door.
  Every blocking wait in this module is quantum-bounded
  (tests/test_style.py extends the parallel/ bounded-wait gate to
  fleet/).

Fault points: ``fleet.router_stall`` (inject_sleep in the dispatch
loop) drills a wedged router without touching replica health.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from ..faults import injection as _faults
from ..obs.metrics import metrics_registry
from ..serving.admission import (
    AdmissionController,
    DeadlineExceededError,
    QueueFullError,
    RequestTimeoutError,
    TenantQuotaError,
    _Request,
)
from .channel import (
    OP_CONTROL,
    OP_CONTROL_RESULT,
    OP_ERROR,
    OP_RESULT,
    OP_SCORE,
    QUANTUM_S,
    ChannelClosedError,
    ChannelTimeoutError,
    FleetChannel,
    connect,
    decode_results,
)

log = logging.getLogger("transmogrifai_tpu.fleet")

LOG_PREFIX = "op_fleet_metrics"

#: cold-start per-row service-time guess (10 us ~ a fused CPU replica at
#: 100k rows/s) used only until an observation or cost-model prediction
#: replaces it
_DEFAULT_SVC_S = 1e-5

#: EWMA smoothing for the per-replica observed service time
_SVC_ALPHA = 0.3

#: failover budget per request: a batch that has already killed (or
#: been orphaned by) this many replicas is POISON, not bad luck - it
#: fails loudly instead of cascading through every survivor and
#: burning the whole fleet's restart budget
MAX_FAILOVERS = 2


class FleetError(RuntimeError):
    """Fleet-level routing failure (no live replica to serve on)."""


class FleetWorkerError(RuntimeError):
    """A replica reported a scoring/control failure for one request."""


@dataclass
class FleetBatch:
    """One queued unit of fleet work (rides ``_Request.record``): the
    encoded payload is retained until the response resolves so a
    failover re-sends the SAME bytes."""

    payload: bytes
    n_rows: int
    tenant: Optional[str] = None
    kind: str = "score"  # score | ctl
    ctl: dict = field(default_factory=dict)
    retries: int = 0


class FleetResult:
    """A replica's response with the result payload still encoded -
    decoded lazily so counting/relaying responses never pays the
    object-graph cost (the router-overhead floor in tests/test_fleet.py
    measures exactly this seam)."""

    __slots__ = ("meta", "payload", "_decoded")

    def __init__(self, meta: dict, payload: bytes) -> None:
        self.meta = meta
        self.payload = payload
        self._decoded: Optional[list] = None

    @property
    def n_rows(self) -> int:
        return int(self.meta.get("n_rows", 0))

    @property
    def version(self) -> Optional[str]:
        return self.meta.get("version")

    @property
    def generation(self) -> Optional[int]:
        return self.meta.get("generation")

    @property
    def instance(self) -> Optional[str]:
        return self.meta.get("instance")

    @property
    def results(self) -> list:
        if self._decoded is None:
            self._decoded = decode_results(self.payload) \
                if self.payload else []
        return self._decoded

    @property
    def doc(self) -> Any:
        """Control-response document (status/deploy acknowledgements)."""
        return decode_results(self.payload)[0] if self.payload else None


class ReplicaHandle:
    """Router-side state for one replica worker."""

    def __init__(self, instance: str, channel: FleetChannel,
                 pid: Optional[int] = None) -> None:
        self.instance = instance
        self.channel = channel
        self.pid = pid
        self.lock = threading.Lock()
        self.pending: dict[int, _Request] = {}
        self.in_flight_rows = 0
        self.alive = True
        self.drained = False
        self.rows_ok = 0
        self.requests_ok = 0
        self.last_version: Optional[str] = None
        self.last_generation: Optional[int] = None
        self.svc_s_ewma: Optional[float] = None
        #: latest shard-observed stats (refresh_from_shards)
        self.obs: dict = {}
        self.receiver: Optional[threading.Thread] = None

    # -- load estimate ------------------------------------------------------
    def service_s_per_row(self, cost_model=None) -> float:
        """Best current per-row service-time estimate: live EWMA >
        cost-model prediction > shipped-shard throughput > default."""
        if self.svc_s_ewma is not None:
            return self.svc_s_ewma
        if cost_model is not None:
            try:
                from ..autotune import candidate_features

                pred_ms = cost_model.predict_wall_ms(
                    "serve.batch/" + self.instance,
                    candidate_features(512, 0),
                )
                if pred_ms is not None and pred_ms > 0:
                    return pred_ms / 1e3 / 512.0
            except Exception as e:  # noqa: BLE001 - estimate only
                log.debug("cost-model estimate failed for %s: %s",
                          self.instance, e)
        rps = self.obs.get("batch_rows_per_s")
        if rps:
            return 1.0 / float(rps)
        return _DEFAULT_SVC_S

    def expected_wait_s(self, n_rows: int, cost_model=None) -> float:
        svc = self.service_s_per_row(cost_model)
        with self.lock:
            backlog = self.in_flight_rows
        return (backlog + n_rows) * svc

    def in_flight(self) -> int:
        with self.lock:
            return len(self.pending)

    def snapshot(self) -> dict:
        with self.lock:
            return {
                "instance": self.instance,
                "pid": self.pid,
                "alive": self.alive,
                "drained": self.drained,
                "in_flight": len(self.pending),
                "in_flight_rows": self.in_flight_rows,
                "rows_ok": self.rows_ok,
                "requests_ok": self.requests_ok,
                "version": self.last_version,
                "generation": self.last_generation,
                "service_us_per_row": (
                    round(self.svc_s_ewma * 1e6, 3)
                    if self.svc_s_ewma is not None else None),
                "obs": dict(self.obs),
            }


class FleetRouter:
    """Least-loaded dispatch + at-least-once failover over replica
    channels (module docstring).  In-process: the router lives in the
    controller/runner process, replicas are separate worker processes
    behind AF_UNIX channels."""

    def __init__(
        self,
        max_queue: int = 256,
        max_in_flight_per_replica: int = 4,
        tenant_quota: Optional[float] = None,
        cost_model=None,
        clock=time.monotonic,
        send_timeout_s: float = 10.0,
        start: bool = True,
    ) -> None:
        if max_in_flight_per_replica < 1:
            raise ValueError("max_in_flight_per_replica must be >= 1")
        self.max_in_flight_per_replica = int(max_in_flight_per_replica)
        self.cost_model = cost_model
        self.clock = clock
        self.send_timeout_s = float(send_timeout_s)
        self.admission = AdmissionController(
            max_queue=max_queue, clock=clock, tenant_quota=tenant_quota)
        self._handles: dict[str, ReplicaHandle] = {}
        self._handles_lock = threading.Lock()
        self._retry: deque[_Request] = deque()
        self._retry_lock = threading.Lock()
        self._req_ids = itertools.count(1)
        self._stop = threading.Event()
        #: set by every response arrival: the dispatcher parked on
        #: "every replica full" wakes the moment capacity frees instead
        #: of burning the whole 50 ms quantum (the wait itself stays
        #: quantum-BOUNDED - the event only makes it prompt)
        self._capacity = threading.Event()
        # counters (the fleet_router metrics view)
        self._ctr_lock = threading.Lock()
        self.rows_ok = 0
        self.rows_failed = 0
        self.requests_ok = 0
        self.requests_failed = 0
        self.shed_queue_full = 0
        self.shed_quota = 0
        self.shed_deadline = 0
        self.retries = 0
        self.replica_deaths = 0
        self.router_stalls = 0
        self._rows_by_generation: dict[str, int] = {}
        metrics_registry().register_view("fleet_router", self)
        self._dispatcher: Optional[threading.Thread] = None
        if start:
            self._dispatcher = threading.Thread(
                target=self._dispatch_loop, name="tx-fleet-dispatch",
                daemon=True)
            self._dispatcher.start()

    # -- replica membership -------------------------------------------------
    def add_replica(self, instance: str, socket_path: str,
                    connect_timeout_s: float = 60.0,
                    pid: Optional[int] = None) -> ReplicaHandle:
        """Connect a replica's channel and start its receiver thread.
        Re-adding an instance name (a restarted worker) replaces the
        dead handle; its in-flight work was already failed over."""
        channel = connect(socket_path, timeout_s=connect_timeout_s)
        handle = ReplicaHandle(instance, channel, pid=pid)
        handle.receiver = threading.Thread(
            target=self._receive_loop, args=(handle,),
            name=f"tx-fleet-recv-{instance}", daemon=True)
        with self._handles_lock:
            old = self._handles.get(instance)
            self._handles[instance] = handle
        if old is not None and old.alive:
            self._on_replica_dead(old, "replaced by a new connection")
        handle.receiver.start()
        return handle

    def replicas(self) -> list[ReplicaHandle]:
        with self._handles_lock:
            return list(self._handles.values())

    def live_replicas(self) -> list[ReplicaHandle]:
        return [h for h in self.replicas() if h.alive]

    def handle(self, instance: str) -> ReplicaHandle:
        with self._handles_lock:
            h = self._handles.get(instance)
        if h is None:
            raise FleetError(f"unknown replica {instance!r}")
        return h

    # -- submission ---------------------------------------------------------
    def submit(self, records: Optional[Sequence] = None,
               payload: Optional[bytes] = None,
               n_rows: Optional[int] = None,
               tenant: Optional[str] = None,
               deadline_ms: Optional[float] = None) -> _Request:
        """Queue one batch; returns the admission ``_Request`` handle
        (``.wait(timeout)`` -> :class:`FleetResult`).  Pass ``records``
        (encoded here, once) or an already-encoded ``payload`` +
        ``n_rows`` - the wire-form path for callers that hold the
        serialized batch already (a network front end, the bench's
        sustained-load driver)."""
        if payload is None:
            if records is None:
                raise ValueError("submit needs records or payload")
            from .channel import encode_records

            payload = encode_records(records)
            n_rows = len(records)
        if n_rows is None:
            raise ValueError("payload submission needs n_rows")
        batch = FleetBatch(payload=payload, n_rows=int(n_rows),
                           tenant=tenant)
        slept = _faults.inject_sleep("fleet.router_stall")
        if slept:
            with self._ctr_lock:
                self.router_stalls += 1
        try:
            req = self.admission.admit(
                batch,
                None if deadline_ms is None else deadline_ms / 1e3,
                tenant=tenant,
            )
        except TenantQuotaError:
            with self._ctr_lock:
                self.shed_quota += 1
            raise
        except QueueFullError:
            with self._ctr_lock:
                self.shed_queue_full += 1
            raise
        self._try_fast_dispatch()
        return req

    def score_batch(self, records: Sequence, timeout_s: float = 30.0,
                    tenant: Optional[str] = None,
                    deadline_ms: Optional[float] = None) -> list:
        """Synchronous scoring through the fleet; element i aligns with
        records[i] (the endpoint contract, preserved end to end)."""
        req = self.submit(records=records, tenant=tenant,
                          deadline_ms=deadline_ms)
        res: FleetResult = req.wait(timeout_s)
        return res.results

    # -- dispatch -----------------------------------------------------------
    def _try_fast_dispatch(self) -> None:
        """Caller-thread fast path: when nothing waits ahead (no
        failover retries) and a replica has capacity, take the queue
        head and send it right here - two context switches cheaper per
        request than waking the dispatcher thread, which remains the
        slow path for the queued/backpressure case.  FIFO holds: only
        the queue HEAD is taken, and only when the retry deque is
        empty."""
        with self._retry_lock:
            if self._retry:
                return
        if self._pick(0) is None:
            return  # every replica full: the dispatcher's park owns it
        live, shed = self.admission.take(1)
        for r in shed:
            if not r.abandoned:
                with self._ctr_lock:
                    self.shed_deadline += 1
        if not live:
            return
        req = live[0]
        while not self._stop.is_set():
            handle = self._pick(req.record.n_rows)
            if handle is None:
                # capacity vanished between the probe and the take
                # (racing caller): hand the head back to the FRONT of
                # the retry lane - the dispatcher drains it within one
                # quantum, order preserved
                with self._retry_lock:
                    self._retry.appendleft(req)
                return
            done, _rid = self._send_to(handle, req)
            if done:
                return
        # the router closed while we held a taken request: it is in no
        # queue and no pending map, so close()'s drain cannot reach it
        # - fail it here or its caller blocks out its full wait timeout
        req.resolve(error=FleetError("router closed"))

    def _next_request(self) -> Optional[_Request]:
        """Failover retries first (they already waited once), then the
        admission queue; returns None after a bounded idle quantum."""
        with self._retry_lock:
            if self._retry:
                return self._retry.popleft()
        if not self.admission.wait_nonempty(QUANTUM_S):
            return None
        live, shed = self.admission.take(1)
        for req in shed:
            if not req.abandoned:
                with self._ctr_lock:
                    self.shed_deadline += 1
        return live[0] if live else None

    def _pick(self, n_rows: int) -> Optional[ReplicaHandle]:
        candidates = [
            h for h in self.replicas()
            if h.alive and not h.drained
            and h.in_flight() < self.max_in_flight_per_replica
        ]
        if not candidates:
            return None
        return min(candidates,
                   key=lambda h: h.expected_wait_s(n_rows,
                                                   self.cost_model))

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                req = self._next_request()
                if req is None:
                    continue
                slept = _faults.inject_sleep("fleet.router_stall")
                if slept:
                    with self._ctr_lock:
                        self.router_stalls += 1
                self._dispatch_one(req)
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("fleet dispatch loop error")

    def _dispatch_one(self, req: _Request) -> None:
        """Assign one request to the least-loaded replica, waiting in
        bounded quanta while every replica is at its in-flight cap; a
        request whose deadline passes while waiting sheds, and a fleet
        with no live replica fails it loudly."""
        batch: FleetBatch = req.record  # type: ignore[assignment]
        while not self._stop.is_set():
            if req.deadline is not None and self.clock() > req.deadline:
                if req.resolve_delivered(error=DeadlineExceededError(
                        "deadline exceeded waiting for replica "
                        "capacity")):
                    with self._ctr_lock:
                        self.shed_deadline += 1
                return
            # clear BEFORE picking: a response landing between the pick
            # and the wait still wakes the next wait immediately
            self._capacity.clear()
            handle = self._pick(batch.n_rows)
            if handle is not None:
                done, _rid = self._send_to(handle, req)
                if done:
                    return
                continue  # the picked replica died mid-send: repick
            if not self.live_replicas():
                req.resolve_delivered(error=FleetError(
                    "no live replica to serve on"))
                with self._ctr_lock:
                    self.requests_failed += 1
                return
            # all replicas full: park until a response frees capacity,
            # bounded at one quantum either way
            self._capacity.wait(QUANTUM_S)
        req.resolve_delivered(error=FleetError("router closed"))

    def _send_to(self, handle: ReplicaHandle, req: _Request,
                 op: int = OP_SCORE) -> tuple[bool, Optional[int]]:
        """-> (owned_elsewhere_or_sent, rid).  ``True`` means the
        caller must NOT touch ``req`` again: it was either sent (rid
        returned, response pending) or - on a send failure that raced
        the receiver's death handling - already harvested into the
        retry lane by ``_on_replica_dead`` (rid None).  ``False`` means
        the send failed and the caller still OWNS the request (exactly
        one of the two failure paths keeps it: whoever popped the rid)
        and may re-dispatch it inline."""
        batch: FleetBatch = req.record  # type: ignore[assignment]
        rid = next(self._req_ids)
        if op == OP_SCORE:
            meta = {"tenant": batch.tenant, "n_rows": batch.n_rows}
        else:
            meta = dict(batch.ctl)
        with handle.lock:
            if not handle.alive:
                return False, None
            if (op == OP_SCORE
                    and len(handle.pending)
                    >= self.max_in_flight_per_replica):
                # the cap is enforced HERE, under the lock: _pick's
                # unlocked probe can race concurrent fast-path
                # submitters, and the per-replica in-flight bound is a
                # promise, not a hint (control ops bypass it - a
                # drained replica must still take its deploy).  The
                # caller repicks; _pick's own locked read then sees the
                # replica full.
                return False, None
            handle.pending[rid] = req
            handle.in_flight_rows += batch.n_rows
        # stash for the service-time EWMA (send->response wall)
        req.record._sent_at = time.perf_counter()  # type: ignore
        try:
            handle.channel.send(op, rid, meta, batch.payload,
                                timeout_s=self.send_timeout_s,
                                stop=self._stop)
        except (ChannelClosedError, ChannelTimeoutError) as e:
            # ownership race with the receiver thread's death handling:
            # if IT noticed the dead channel first, _on_replica_dead
            # already popped our rid and queued the request into the
            # retry lane - retrying here too would DOUBLE-dispatch (two
            # survivors both scoring, the ledger counting one request
            # twice).  Whoever pops the rid owns the retry.
            with handle.lock:
                popped = handle.pending.pop(rid, None)
                if popped is not None:
                    handle.in_flight_rows -= batch.n_rows
            self._on_replica_dead(handle, f"send failed: {e}")
            return (popped is None), None
        return True, rid

    # -- responses ----------------------------------------------------------
    def _receive_loop(self, handle: ReplicaHandle) -> None:
        while not self._stop.is_set() and handle.alive:
            try:
                msg = handle.channel.recv(stop=self._stop)
            except ChannelClosedError as e:
                self._on_replica_dead(handle, str(e))
                return
            if msg is None:
                continue
            op, rid, meta, payload = msg
            with handle.lock:
                req = handle.pending.pop(rid, None)
                if req is not None:
                    handle.in_flight_rows -= req.record.n_rows
            self._capacity.set()  # a parked dispatcher can send again
            if req is None:
                continue  # unknown id: already failed over elsewhere
            if op in (OP_RESULT, OP_CONTROL_RESULT):
                self._resolve_ok(handle, req, meta, payload,
                                 scored=op == OP_RESULT)
            elif op == OP_ERROR:
                if req.resolve_delivered(error=FleetWorkerError(
                        str(meta.get("error", "worker error")))):
                    with self._ctr_lock:
                        self.requests_failed += 1
                        self.rows_failed += req.record.n_rows

    def _resolve_ok(self, handle: ReplicaHandle, req: _Request,
                    meta: dict, payload: bytes, scored: bool) -> None:
        batch: FleetBatch = req.record  # type: ignore[assignment]
        meta = dict(meta, instance=handle.instance)
        delivered = req.resolve_delivered(result=FleetResult(meta, payload))
        if not scored:
            return
        n = int(meta.get("n_rows", batch.n_rows))
        wall = time.perf_counter() - getattr(batch, "_sent_at",
                                             time.perf_counter())
        if n > 0 and wall > 0:
            per_row = wall / n
            handle.svc_s_ewma = (
                per_row if handle.svc_s_ewma is None
                else (1 - _SVC_ALPHA) * handle.svc_s_ewma
                + _SVC_ALPHA * per_row
            )
            if self.cost_model is not None:
                try:
                    from ..autotune import candidate_features

                    self.cost_model.observe(
                        "serve.batch/" + handle.instance,
                        candidate_features(n, 0), wall * 1e3)
                except Exception as e:  # noqa: BLE001 - estimate only
                    log.debug("cost-model observe failed: %s", e)
        handle.last_version = meta.get("version")
        handle.last_generation = meta.get("generation")
        with handle.lock:
            handle.rows_ok += n
            handle.requests_ok += 1
        gen_key = f"{meta.get('version')}/g{meta.get('generation')}"
        with self._ctr_lock:
            if delivered:
                self.requests_ok += 1
                self.rows_ok += n
                self._rows_by_generation[gen_key] = (
                    self._rows_by_generation.get(gen_key, 0) + n)

    # -- failover -----------------------------------------------------------
    def _on_replica_dead(self, handle: ReplicaHandle,
                         reason: str) -> None:
        with handle.lock:
            if not handle.alive:
                return
            handle.alive = False
            orphans = list(handle.pending.items())
            handle.pending.clear()
            handle.in_flight_rows = 0
        handle.channel.close()
        self._capacity.set()  # wake a parked dispatcher to re-plan
        with self._ctr_lock:
            self.replica_deaths += 1
        log.warning("%s replica %s dead (%s): failing over %d in-flight "
                    "request(s) to survivors", LOG_PREFIX,
                    handle.instance, reason, len(orphans))
        for _rid, req in orphans:
            if req.done.is_set():
                continue
            if req.record.kind == "ctl":
                # control ops are not idempotent-by-construction the way
                # scoring is: surface the failure to the operator path
                req.resolve_delivered(error=FleetError(
                    f"replica {handle.instance} died during a control "
                    f"operation ({reason})"))
                continue
            if req.record.retries >= MAX_FAILOVERS:
                # a poison batch must not cascade replica to replica
                if req.resolve_delivered(error=FleetError(
                        f"request failed over {req.record.retries} "
                        f"times (last replica {handle.instance}: "
                        f"{reason}); refusing further retries")):
                    with self._ctr_lock:
                        self.requests_failed += 1
                        self.rows_failed += req.record.n_rows
                continue
            req.record.retries += 1
            with self._ctr_lock:
                self.retries += 1
            with self._retry_lock:
                self._retry.append(req)

    # -- control plane ------------------------------------------------------
    def control(self, instance: str, cmd: str,
                args: Optional[dict] = None,
                timeout_s: float = 120.0) -> Any:
        """One control round trip to a named replica (deploy / canary /
        status / ...); bypasses admission and the drain flag - draining
        a replica is exactly how a rolling deploy makes room to send it
        control traffic."""
        handle = self.handle(instance)
        if not handle.alive:
            raise FleetError(f"replica {instance!r} is not alive")
        batch = FleetBatch(payload=b"", n_rows=0, kind="ctl",
                           ctl=dict(args or {}, cmd=cmd))
        req = _Request(record=batch, enqueued_at=self.clock())
        sent, rid = self._send_to(handle, req, op=OP_CONTROL)
        if not sent or rid is None:
            raise FleetError(f"replica {instance!r} died mid-control")
        try:
            res: FleetResult = req.wait(timeout_s)
        except RequestTimeoutError:
            # reclaim the in-flight slot: a leaked pending entry would
            # hold one max_in_flight slot forever and keep
            # wait_drained() from ever seeing zero (a late reply finds
            # the rid gone and is dropped)
            with handle.lock:
                handle.pending.pop(rid, None)
            raise
        return res.doc

    def broadcast(self, cmd: str, args: Optional[dict] = None,
                  timeout_s: float = 120.0) -> dict:
        """The control op on every LIVE replica; per-instance results
        (exceptions captured as ``{"error": ...}`` so one dead replica
        cannot abort a fleet-wide rollback)."""
        out = {}
        for h in self.live_replicas():
            try:
                out[h.instance] = self.control(h.instance, cmd, args,
                                               timeout_s)
            except (FleetError, FleetWorkerError,
                    RequestTimeoutError) as e:
                out[h.instance] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def set_drained(self, instance: str, drained: bool = True) -> None:
        self.handle(instance).drained = bool(drained)

    def wait_drained(self, instance: str, timeout_s: float = 30.0) -> bool:
        """True once the replica has zero in-flight requests (its
        drained flag stops NEW dispatches; in-flight batches finish on
        the old generation - the zero-drop half of a rolling deploy)."""
        handle = self.handle(instance)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() <= deadline:
            if handle.in_flight() == 0:
                return True
            time.sleep(QUANTUM_S)
        return False

    # -- observed load refresh ----------------------------------------------
    def refresh_from_shards(self, metrics_docs: Sequence[dict]) -> int:
        """Fold the fleet aggregation dir's per-replica serving stats
        into the dispatch weights (ISSUE 14 satellite: the router reads
        observed throughput/p99 from fleet shards).  ``metrics_docs``
        is ``FleetAggregator.merged_metrics_docs()``; returns how many
        handles were updated."""
        from ..obs.fleet import serving_views

        by_instance = {str(d.get("instance")): d for d in metrics_docs}
        updated = 0
        for h in self.replicas():
            doc = by_instance.get(h.instance)
            if doc is None:
                continue
            best: dict = {}
            for _key, snap in serving_views(doc):
                rps = snap.get("batch_rows_per_s") or 0
                if rps >= best.get("batch_rows_per_s", 0):
                    best = {
                        "batch_rows_per_s": rps,
                        "p99_ms": (snap.get("latency_ms") or {}).get(
                            "p99"),
                        "queue_depth_p99": (snap.get("queue_depth")
                                            or {}).get("p99"),
                        "rows_scored": snap.get("rows_scored"),
                    }
            if best:
                h.obs = best
                updated += 1
        return updated

    # -- reporting ----------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``fleet_router`` metrics view: fleet-level counters plus
        per-replica dispatch state, scraped as ``tx_fleet_router_*``."""
        with self._ctr_lock:
            out = {
                "rows_ok": self.rows_ok,
                "rows_failed": self.rows_failed,
                "requests_ok": self.requests_ok,
                "requests_failed": self.requests_failed,
                "shed_queue_full": self.shed_queue_full,
                "shed_quota": self.shed_quota,
                "shed_deadline": self.shed_deadline,
                "retries": self.retries,
                "replica_deaths": self.replica_deaths,
                "router_stalls": self.router_stalls,
                "rows_by_generation": dict(self._rows_by_generation),
            }
        out["queue_depth"] = len(self.admission)
        out["tenants_held"] = {
            str(k): v for k, v in self.admission.tenants_held().items()
        }
        out["replicas"] = {
            h.instance: h.snapshot() for h in self.replicas()
        }
        return out

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop dispatching, fail everything still pending loudly, and
        close every channel (all joins bounded)."""
        self._stop.set()
        self.admission.close()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout_s)
        for req in self.admission.drain():
            req.resolve(error=FleetError("router closed"))
        with self._retry_lock:
            retry, self._retry = list(self._retry), deque()
        for req in retry:
            req.resolve(error=FleetError("router closed"))
        for h in self.replicas():
            with h.lock:
                pending = list(h.pending.values())
                h.pending.clear()
                h.alive = False
            for req in pending:
                req.resolve(error=FleetError("router closed"))
            h.channel.close()
            if h.receiver is not None:
                h.receiver.join(timeout_s)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
