"""Least-loaded front router over N replica serving workers.

The dispatch layer of the scale-out fleet (ISSUE 14/17, ROADMAP items
1/3; reference frame: the TensorFlow system paper's many-workers-
behind-one-dispatch-layer scaling story, arXiv 1605.08695, with the
TpuGraphs learned-cost-signal idea, arXiv 2308.13490, supplying the
load estimate):

* **front door** - the PR-1 :class:`AdmissionController` unchanged
  (bounded queue, deadline shed at dequeue) with the ISSUE-14
  per-tenant quotas layered on: one chatty tenant sheds with
  ``TenantQuotaError`` while the rest of the fleet's traffic admits.
* **least-loaded dispatch** - one dispatcher thread assigns each queued
  batch to the replica with the smallest *expected wait*:
  ``(in_flight_rows + batch_rows) * service_s_per_row``, where the
  per-replica service time blends a live EWMA over this router's own
  response walls with the replica's shipped obs shard
  (``batch_rows_per_s`` / p99 from its ServingTelemetry view, read via
  :meth:`FleetRouter.refresh_from_shards`) and - when the deployed
  artifact carries an ``autotune.json`` - the PR-13 :class:`CostModel`
  (per-replica ``serve.batch/<instance>`` keys trained online from
  observed batch walls; its prediction replaces the cold-start default
  until live EWMAs exist).
* **at-least-once failover** - requests stay registered on their
  replica until the response arrives; a replica that dies (SIGKILL,
  channel EOF) or is ejected has every in-flight request re-dispatched
  to survivors from the SAME encoded payload (encode-once), so an
  accepted request is never lost - the fleet may score a row twice,
  the caller sees exactly one response (idempotent scoring).
* **health-gated membership** (ISSUE 17) - each replica carries a
  :class:`ReplicaHealth` state machine with the PR-2 breaker semantics
  lifted to the fleet tier: ``eject_after`` consecutive response
  timeouts/transport failures EJECT the replica (its in-flight work
  fails over, no new dispatches), a rate-bounded half-open PROBE (one
  control ping per ``probe_interval_s``, reconnecting the channel
  first when it died) readmits it on the first pong.  A partitioned
  TCP peer looks alive - the socket stays open while frames vanish -
  which is exactly why ejection is keyed on response timeouts, not on
  channel EOF.  Ejection/readmission are trace events
  (``fleet.ejection`` / ``fleet.readmission``) and the per-replica
  machine is its own ``fleet_health`` metrics view
  (``tx_fleet_health_*``).
* **deadline propagation** - a request's remaining budget rides the
  wire meta as an absolute wall-clock deadline (the gRPC convention;
  cross-host skew eats into slack, never adds budget), so a replica
  drops work the caller already abandoned - the tf.data
  bounded-staleness stance (arXiv 2101.12127) applied to serving.
* **quorum brownout** - when fewer than ``quorum`` replicas are
  healthy, new submissions from tenants below
  ``brownout_min_priority`` shed with :class:`BrownoutShedError` at
  the front door: planned degradation sheds the lowest-priority
  tenants first instead of queuing the whole fleet toward a stall.
* **backpressure, never hang** - per-replica in-flight is capped; when
  every replica is full the dispatcher waits in 50 ms quanta while the
  bounded admission queue sheds new submissions at the front door.
  Every blocking wait in this module is quantum-bounded
  (tests/test_style.py extends the parallel/ bounded-wait gate to
  fleet/).

Fault points: ``fleet.router_stall`` (inject_sleep in the dispatch
loop) drills a wedged router without touching replica health; the
channel-seam points (``fleet.partition``, ``fleet.half_open``,
``channel.corrupt_frame``, ``fleet.reconnect_storm``) live in
channel.py and drill this module's detection/ejection/readmission
machinery end to end.
"""
from __future__ import annotations

import contextvars
import itertools
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from ..faults import injection as _faults
from ..obs.metrics import metrics_registry
from ..obs.trace import tracer
from ..serving.admission import (
    AdmissionController,
    DeadlineExceededError,
    QueueFullError,
    RequestTimeoutError,
    TenantQuotaError,
    _Request,
)
from .multimodel import UnhostedModelError
from .channel import (
    HANDSHAKE_TIMEOUT_S,
    OP_CONTROL,
    OP_CONTROL_RESULT,
    OP_ERROR,
    OP_HELLO,
    OP_RESULT,
    OP_SCORE,
    QUANTUM_S,
    ChannelClosedError,
    ChannelProtocolError,
    ChannelTimeoutError,
    FleetChannel,
    connect,
    decode_results,
    parse_address,
)

log = logging.getLogger("transmogrifai_tpu.fleet")

LOG_PREFIX = "op_fleet_metrics"


def _ctx_thread(target, name: str, *args) -> threading.Thread:
    """A daemon thread that runs ``target`` inside a COPY of the
    creating thread's contextvars - plain threads start with an empty
    context, which would root every ``fleet.ejection`` /
    ``fleet.readmission`` trace event in its own fresh trace id.
    Copying here keeps the whole fault envelope (detection in the
    receive loop, ejection in the health loop, readmission probes)
    under the one trace that created the router/handle."""
    ctx = contextvars.copy_context()
    return threading.Thread(target=lambda: ctx.run(target, *args),
                            name=name, daemon=True)

#: cold-start per-row service-time guess (10 us ~ a fused CPU replica at
#: 100k rows/s) used only until an observation or cost-model prediction
#: replaces it
_DEFAULT_SVC_S = 1e-5

#: EWMA smoothing for the per-replica observed service time
_SVC_ALPHA = 0.3

#: failover budget per request: a batch that has already killed (or
#: been orphaned by) this many replicas is POISON, not bad luck - it
#: fails loudly instead of cascading through every survivor and
#: burning the whole fleet's restart budget
MAX_FAILOVERS = 2

#: numeric encoding of ReplicaHealth.state for the gauge plane
HEALTH_CODES = {"healthy": 0, "probing": 1, "ejected": 2}


class FleetError(RuntimeError):
    """Fleet-level routing failure (no live replica to serve on)."""


class FleetWorkerError(RuntimeError):
    """A replica reported a scoring/control failure for one request."""


class FleetDecodeError(FleetWorkerError):
    """A replica's result payload failed to decode (ISSUE 17
    satellite: counted as ``decode_errors`` in the fleet_router view
    and attributed to request id + replica instance, never an anonymous
    pickle traceback in the caller's lap)."""


class BrownoutShedError(QueueFullError):
    """Shed at the front door because the fleet is below quorum and the
    tenant is below the brownout priority floor (planned degradation:
    lowest-priority traffic goes first, the fleet never queues toward a
    stall)."""


class ModelQuotaError(QueueFullError):
    """Shed at the front door because one model's in-flight rows hit
    its configured quota (ISSUE 20: a chatty model's tenants shed while
    the other hosted models' traffic keeps admitting)."""


class ReplicaHealth:
    """Per-replica failure-detector state machine (PR-2 circuit-breaker
    semantics at the fleet tier)::

        healthy --eject_after consecutive failures--> ejected
        ejected --rate-bounded probe sent-----------> probing
        probing --pong------------------------------> healthy
        probing --probe timeout/error---------------> ejected

    Channel death force-ejects regardless of the consecutive count
    (there is nothing to time out against a closed socket).  A
    response of ANY kind - including a worker error or a deadline
    drop - is evidence of transport life and resets the consecutive
    counter; only silence and channel failures count toward ejection.
    Mutations happen under the owning handle's lock.
    """

    __slots__ = (
        "eject_after", "state", "consecutive_failures", "last_rtt_ms",
        "last_error", "ejections", "readmissions", "probes_sent",
        "probes_failed", "ejected_at", "readmitted_at", "last_ok_at",
        "last_probe_at", "probe_rid", "probe_sent_at", "transitions",
    )

    def __init__(self, eject_after: int = 3) -> None:
        if eject_after < 1:
            raise ValueError("eject_after must be >= 1")
        self.eject_after = int(eject_after)
        self.state = "healthy"
        self.consecutive_failures = 0
        self.last_rtt_ms: Optional[float] = None
        self.last_error: Optional[str] = None
        self.ejections = 0
        self.readmissions = 0
        self.probes_sent = 0
        self.probes_failed = 0
        #: monotonic marks for latency accounting (bench reads these)
        self.ejected_at: Optional[float] = None
        self.readmitted_at: Optional[float] = None
        self.last_ok_at: Optional[float] = None
        self.last_probe_at: Optional[float] = None
        self.probe_rid: Optional[int] = None
        self.probe_sent_at: Optional[float] = None
        #: bounded transition log [{"to", "reason", "t"}]
        self.transitions: list[dict] = []

    def _transition(self, state: str, reason: str) -> None:
        self.state = state
        self.transitions.append(
            {"to": state, "reason": reason, "t": time.time()})
        if len(self.transitions) > 64:
            del self.transitions[0]

    def record_success(self, rtt_ms: Optional[float],
                       now: float) -> None:
        self.last_ok_at = now
        if rtt_ms is not None:
            self.last_rtt_ms = rtt_ms
        if self.state == "healthy":
            self.consecutive_failures = 0
        # probing/ejected: only an explicit probe pong readmits - a
        # straggler response from before the partition is not health

    def record_failure(self, reason: str, now: float) -> bool:
        """Count one failure; True when it newly ejects the replica."""
        self.consecutive_failures += 1
        self.last_error = str(reason)
        if (self.state == "healthy"
                and self.consecutive_failures >= self.eject_after):
            self.force_eject(reason, now)
            return True
        return False

    def force_eject(self, reason: str, now: float) -> None:
        if self.state != "ejected":
            self._transition("ejected", str(reason))
            self.ejections += 1
            self.ejected_at = now
            self.probe_rid = None
        self.last_error = str(reason)

    def begin_probe(self, now: float) -> None:
        self.probes_sent += 1
        self.last_probe_at = now
        self.probe_sent_at = now
        self.probe_rid = None
        if self.state == "ejected":
            self._transition("probing", "probe sent")

    def probe_failed(self, reason: str, now: float) -> None:
        self.probes_failed += 1
        self.last_error = str(reason)
        self.probe_rid = None
        if self.state == "probing":
            self._transition("ejected", f"probe failed: {reason}")

    def readmit(self, now: float) -> bool:
        """Probe pong arrived; True when this newly readmits."""
        if self.state == "healthy":
            return False
        self._transition("healthy", "probe pong")
        self.readmissions += 1
        self.readmitted_at = now
        self.consecutive_failures = 0
        self.probe_rid = None
        self.last_ok_at = now
        return True

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "state_code": HEALTH_CODES.get(self.state, -1),
            "consecutive_failures": self.consecutive_failures,
            "ejections": self.ejections,
            "readmissions": self.readmissions,
            "probes_sent": self.probes_sent,
            "probes_failed": self.probes_failed,
            "last_rtt_ms": (None if self.last_rtt_ms is None
                            else round(self.last_rtt_ms, 3)),
            "last_error": self.last_error,
        }


@dataclass
class FleetBatch:
    """One queued unit of fleet work (rides ``_Request.record``): the
    encoded payload is retained until the response resolves so a
    failover re-sends the SAME bytes."""

    payload: bytes
    n_rows: int
    tenant: Optional[str] = None
    kind: str = "score"  # score | ctl | probe
    ctl: dict = field(default_factory=dict)
    retries: int = 0
    #: per-model dispatch (ISSUE 20): routed only to replicas hosting
    #: this model; None = the legacy single-model lane
    model_id: Optional[str] = None


class FleetResult:
    """A replica's response with the result payload still encoded -
    decoded lazily so counting/relaying responses never pays the
    object-graph cost (the router-overhead floor in tests/test_fleet.py
    measures exactly this seam).  A payload that fails to decode raises
    :class:`FleetDecodeError` naming the request id and replica, and
    counts on the owning router's ``decode_errors``."""

    __slots__ = ("meta", "payload", "_decoded", "_on_decode_error")

    def __init__(self, meta: dict, payload: bytes,
                 on_decode_error=None) -> None:
        self.meta = meta
        self.payload = payload
        self._decoded: Optional[list] = None
        self._on_decode_error = on_decode_error

    @property
    def n_rows(self) -> int:
        return int(self.meta.get("n_rows", 0))

    @property
    def version(self) -> Optional[str]:
        return self.meta.get("version")

    @property
    def generation(self) -> Optional[int]:
        return self.meta.get("generation")

    @property
    def instance(self) -> Optional[str]:
        return self.meta.get("instance")

    @property
    def request_id(self) -> Optional[int]:
        return self.meta.get("request_id")

    def _decode(self) -> list:
        try:
            return decode_results(self.payload) if self.payload else []
        except Exception as e:
            if self._on_decode_error is not None:
                self._on_decode_error()
            raise FleetDecodeError(
                f"undecodable result payload for request "
                f"{self.request_id} from replica {self.instance}: {e}"
            ) from e

    @property
    def results(self) -> list:
        if self._decoded is None:
            self._decoded = self._decode()
        return self._decoded

    @property
    def doc(self) -> Any:
        """Control-response document (status/deploy acknowledgements)."""
        docs = self._decode()
        return docs[0] if docs else None


class ReplicaHandle:
    """Router-side state for one replica worker."""

    def __init__(self, instance: str, channel: FleetChannel,
                 pid: Optional[int] = None,
                 address: Optional[str] = None,
                 eject_after: int = 3) -> None:
        self.instance = instance
        self.channel = channel
        self.pid = pid
        #: the address the channel was connected to (the readmission
        #: probe reconnects through it when the channel died)
        self.address = address
        self.transport = (parse_address(address)[0]
                          if address is not None else "unix")
        self.lock = threading.Lock()
        self.pending: dict[int, _Request] = {}
        self.in_flight_rows = 0
        self.alive = True
        self.drained = False
        self.rows_ok = 0
        self.requests_ok = 0
        self.last_version: Optional[str] = None
        self.last_generation: Optional[int] = None
        self.svc_s_ewma: Optional[float] = None
        self.health = ReplicaHealth(eject_after=eject_after)
        #: wire-integrity counters accumulated across channel
        #: replacements (a reconnect must not zero the drill ledger)
        self.wire = {"protocol_errors": 0, "frames_dropped": 0,
                     "partitions": 0, "half_opens": 0,
                     "corrupt_injected": 0}
        #: latest shard-observed stats (refresh_from_shards)
        self.obs: dict = {}
        #: model_ids this replica hosts (ISSUE 20), fed by the
        #: placement plan (set_hosting) and by the replica's own
        #: shipped ``fleet_replica`` view (refresh_from_shards);
        #: model-routed batches only dispatch to hosting replicas
        self.hosted_models: set[str] = set()
        self.receiver: Optional[threading.Thread] = None

    def hosts(self, model_id: str) -> bool:
        with self.lock:
            return model_id in self.hosted_models

    # -- load estimate ------------------------------------------------------
    def service_s_per_row(self, cost_model=None) -> float:
        """Best current per-row service-time estimate: live EWMA >
        cost-model prediction > shipped-shard throughput > default."""
        if self.svc_s_ewma is not None:
            return self.svc_s_ewma
        if cost_model is not None:
            try:
                from ..autotune import candidate_features

                pred_ms = cost_model.predict_wall_ms(
                    "serve.batch/" + self.instance,
                    candidate_features(512, 0),
                )
                if pred_ms is not None and pred_ms > 0:
                    return pred_ms / 1e3 / 512.0
            except Exception as e:  # noqa: BLE001 - estimate only
                log.debug("cost-model estimate failed for %s: %s",
                          self.instance, e)
        rps = self.obs.get("batch_rows_per_s")
        if rps:
            return 1.0 / float(rps)
        return _DEFAULT_SVC_S

    def expected_wait_s(self, n_rows: int, cost_model=None) -> float:
        svc = self.service_s_per_row(cost_model)
        with self.lock:
            backlog = self.in_flight_rows
        return (backlog + n_rows) * svc

    def in_flight(self) -> int:
        with self.lock:
            return len(self.pending)

    def fold_wire_stats(self) -> None:
        """Accumulate the CURRENT channel's integrity counters into the
        handle-lifetime ledger (called right before the channel is
        replaced; callers hold ``self.lock``)."""
        for k, v in self.channel.stats().items():
            self.wire[k] = self.wire.get(k, 0) + v

    def wire_stats(self) -> dict:
        """Handle-lifetime wire counters: accumulated + live channel."""
        live = self.channel.stats()
        return {k: self.wire.get(k, 0) + live.get(k, 0)
                for k in set(self.wire) | set(live)}

    def snapshot(self) -> dict:
        with self.lock:
            return {
                "instance": self.instance,
                "pid": self.pid,
                "alive": self.alive,
                "drained": self.drained,
                "transport": self.transport,
                "in_flight": len(self.pending),
                "in_flight_rows": self.in_flight_rows,
                "rows_ok": self.rows_ok,
                "requests_ok": self.requests_ok,
                "version": self.last_version,
                "generation": self.last_generation,
                "service_us_per_row": (
                    round(self.svc_s_ewma * 1e6, 3)
                    if self.svc_s_ewma is not None else None),
                "health": self.health.snapshot(),
                "wire": self.wire_stats(),
                "obs": dict(self.obs),
                "hosted_models": sorted(self.hosted_models),
            }


class _FleetHealthView:
    """Adapter giving per-replica health its own metrics view
    (``fleet_health`` -> ``tx_fleet_health_*`` gauges) without
    re-snapshotting the whole router; owned by the router so the
    registry's weakref stays live exactly as long as the router."""

    def __init__(self, router: "FleetRouter") -> None:
        self._router = router

    def snapshot(self) -> dict:
        return self._router.health_snapshot()


class FleetRouter:
    """Least-loaded dispatch + at-least-once failover + health-gated
    membership over replica channels (module docstring).  In-process:
    the router lives in the controller/runner process, replicas are
    separate worker processes behind AF_UNIX (on-host) or TCP
    (cross-host) channels."""

    def __init__(
        self,
        max_queue: int = 256,
        max_in_flight_per_replica: int = 4,
        tenant_quota: Optional[float] = None,
        cost_model=None,
        clock=time.monotonic,
        send_timeout_s: float = 10.0,
        response_timeout_s: float = 30.0,
        eject_after: int = 3,
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 2.0,
        quorum: Optional[int] = None,
        tenant_priority: Optional[dict] = None,
        brownout_min_priority: int = 1,
        model_quotas: Optional[dict] = None,
        start: bool = True,
    ) -> None:
        if max_in_flight_per_replica < 1:
            raise ValueError("max_in_flight_per_replica must be >= 1")
        self.max_in_flight_per_replica = int(max_in_flight_per_replica)
        self.cost_model = cost_model
        self.clock = clock
        self.send_timeout_s = float(send_timeout_s)
        #: silence ceiling per in-flight score request: a replica that
        #: holds a request longer than this without ANY response is
        #: failing (partitioned peers keep the socket open - timeouts,
        #: not EOF, are the cross-host failure signal)
        self.response_timeout_s = float(response_timeout_s)
        self.eject_after = int(eject_after)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.quorum = None if quorum is None else int(quorum)
        self._tenant_priority = dict(tenant_priority or {})
        self.brownout_min_priority = int(brownout_min_priority)
        #: per-model in-flight row caps (ISSUE 20): {model_id: rows};
        #: a model at its cap sheds NEW submissions with
        #: ModelQuotaError while other models keep admitting
        self.model_quotas = {
            str(k): int(v) for k, v in (model_quotas or {}).items()}
        self.admission = AdmissionController(
            max_queue=max_queue, clock=clock, tenant_quota=tenant_quota)
        self._handles: dict[str, ReplicaHandle] = {}
        self._handles_lock = threading.Lock()
        self._retry: deque[_Request] = deque()
        self._retry_lock = threading.Lock()
        self._req_ids = itertools.count(1)
        self._stop = threading.Event()
        #: set by every response arrival: the dispatcher parked on
        #: "every replica full" wakes the moment capacity frees instead
        #: of burning the whole 50 ms quantum (the wait itself stays
        #: quantum-BOUNDED - the event only makes it prompt)
        self._capacity = threading.Event()
        # counters (the fleet_router metrics view)
        self._ctr_lock = threading.Lock()
        self.rows_ok = 0
        self.rows_failed = 0
        self.requests_ok = 0
        self.requests_failed = 0
        self.shed_queue_full = 0
        self.shed_quota = 0
        self.shed_deadline = 0
        self.shed_brownout = 0
        self.shed_model_quota = 0
        self.unhosted_model_errors = 0
        self.retries = 0
        self.replica_deaths = 0
        self.router_stalls = 0
        self.response_timeouts = 0
        self.protocol_errors = 0
        self.decode_errors = 0
        self.deadline_dropped_remote = 0
        self.ejections = 0
        self.readmissions = 0
        self.probes_sent = 0
        self.probes_failed = 0
        self._rows_by_generation: dict[str, int] = {}
        #: exact per-model row conservation ledger (ISSUE 20): every
        #: delivered scored row attributed to its model (None-keyed
        #: rows ride the legacy single-model lane)
        self._rows_by_model: dict[str, int] = {}
        metrics_registry().register_view("fleet_router", self)
        self._health_view = _FleetHealthView(self)
        metrics_registry().register_view("fleet_health",
                                         self._health_view)
        self._dispatcher: Optional[threading.Thread] = None
        self._health: Optional[threading.Thread] = None
        if start:
            self._dispatcher = _ctx_thread(
                self._dispatch_loop, "tx-fleet-dispatch")
            self._dispatcher.start()
            self._health = _ctx_thread(
                self._health_loop, "tx-fleet-health")
            self._health.start()

    # -- replica membership -------------------------------------------------
    def add_replica(self, instance: str, socket_path: str,
                    connect_timeout_s: float = 60.0,
                    pid: Optional[int] = None,
                    drained: bool = False) -> ReplicaHandle:
        """Connect a replica's channel (unix path or ``host:port``) and
        start its receiver thread.  Re-adding an instance name (a
        restarted worker) replaces the dead handle; its in-flight work
        was already failed over.  ``drained=True`` admits the handle
        with dispatch OFF - the scale-up path's probe gate: control
        traffic (ping) flows, score traffic waits for the explicit
        undrain after the health probe passes."""
        channel = connect(socket_path, timeout_s=connect_timeout_s)
        handle = ReplicaHandle(instance, channel, pid=pid,
                               address=socket_path,
                               eject_after=self.eject_after)
        handle.drained = bool(drained)
        handle.receiver = _ctx_thread(
            self._receive_loop, f"tx-fleet-recv-{instance}", handle)
        with self._handles_lock:
            old = self._handles.get(instance)
            self._handles[instance] = handle
        if old is not None and old.alive:
            self._on_replica_dead(old, "replaced by a new connection")
        handle.receiver.start()
        return handle

    def replicas(self) -> list[ReplicaHandle]:
        with self._handles_lock:
            return list(self._handles.values())

    def live_replicas(self) -> list[ReplicaHandle]:
        return [h for h in self.replicas() if h.alive]

    def healthy_replicas(self) -> list[ReplicaHandle]:
        return [h for h in self.replicas()
                if h.alive and h.health.state == "healthy"]

    def handle(self, instance: str) -> ReplicaHandle:
        with self._handles_lock:
            h = self._handles.get(instance)
        if h is None:
            raise FleetError(f"unknown replica {instance!r}")
        return h

    def remove_replica(self, instance: str,
                       reason: str = "scale_down",
                       timeout_s: float = 5.0) -> None:
        """Retire a replica from membership entirely (scale-down):
        stop dispatching to it, fail over anything still pending to
        survivors, close the channel, and FORGET the handle - unlike
        ejection, which keeps the handle around for probe-gated
        readmission.  Idempotent on unknown names (a victim that
        crashed mid-drain may already be gone)."""
        with self._handles_lock:
            handle = self._handles.pop(instance, None)
        if handle is None:
            return
        with handle.lock:
            handle.alive = False
            handle.drained = True
            orphans = list(handle.pending.values())
            handle.pending.clear()
            handle.in_flight_rows = 0
        handle.channel.close()
        self._capacity.set()  # wake a parked dispatcher to re-plan
        tracer().event("fleet.remove", instance=instance,
                       reason=str(reason))
        log.info("%s replica %s removed from membership (%s): %d "
                 "in-flight request(s) failing over", LOG_PREFIX,
                 instance, reason, len(orphans))
        self._requeue_orphans(handle, orphans, f"removed: {reason}")
        if handle.receiver is not None:
            handle.receiver.join(timeout_s)

    # -- submission ---------------------------------------------------------
    def _priority(self, tenant: Optional[str]) -> int:
        return int(self._tenant_priority.get(tenant, 0))

    def submit(self, records: Optional[Sequence] = None,
               payload: Optional[bytes] = None,
               n_rows: Optional[int] = None,
               tenant: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               model_id: Optional[str] = None) -> _Request:
        """Queue one batch; returns the admission ``_Request`` handle
        (``.wait(timeout)`` -> :class:`FleetResult`).  Pass ``records``
        (encoded here, once) or an already-encoded ``payload`` +
        ``n_rows`` - the wire-form path for callers that hold the
        serialized batch already (a network front end, the bench's
        sustained-load driver).  ``model_id`` selects one hosted model
        (ISSUE 20): the batch only dispatches to replicas hosting it,
        sheds loudly when nothing does, and counts toward that model's
        in-flight quota."""
        if payload is None:
            if records is None:
                raise ValueError("submit needs records or payload")
            from .channel import encode_records

            payload = encode_records(records)
            n_rows = len(records)
        if n_rows is None:
            raise ValueError("payload submission needs n_rows")
        if model_id is not None:
            model_id = str(model_id)
            if not any(h.alive and h.hosts(model_id)
                       for h in self.replicas()):
                with self._ctr_lock:
                    self.unhosted_model_errors += 1
                raise UnhostedModelError(
                    f"no replica hosts model {model_id!r} "
                    f"(hosting: {self.hosting_map()})")
            cap = self.model_quotas.get(model_id)
            if cap is not None:
                held = self._model_inflight_rows(model_id)
                if held + int(n_rows) > cap:
                    with self._ctr_lock:
                        self.shed_model_quota += 1
                    raise ModelQuotaError(
                        f"model {model_id!r} quota exceeded: "
                        f"{held} rows in flight + {n_rows} > {cap}")
        if self.quorum is not None:
            healthy = len(self.healthy_replicas())
            if (healthy < self.quorum
                    and self._priority(tenant)
                    < self.brownout_min_priority):
                with self._ctr_lock:
                    self.shed_brownout += 1
                raise BrownoutShedError(
                    f"fleet brownout: {healthy}/{self.quorum} replicas "
                    f"healthy; shedding tenant {tenant!r} (priority "
                    f"{self._priority(tenant)} < "
                    f"{self.brownout_min_priority})")
        batch = FleetBatch(payload=payload, n_rows=int(n_rows),
                           tenant=tenant, model_id=model_id)
        slept = _faults.inject_sleep("fleet.router_stall")
        if slept:
            with self._ctr_lock:
                self.router_stalls += 1
        try:
            req = self.admission.admit(
                batch,
                None if deadline_ms is None else deadline_ms / 1e3,
                tenant=tenant,
            )
        except TenantQuotaError:
            with self._ctr_lock:
                self.shed_quota += 1
            raise
        except QueueFullError:
            with self._ctr_lock:
                self.shed_queue_full += 1
            raise
        self._try_fast_dispatch()
        return req

    def score_batch(self, records: Sequence, timeout_s: float = 30.0,
                    tenant: Optional[str] = None,
                    deadline_ms: Optional[float] = None,
                    model_id: Optional[str] = None) -> list:
        """Synchronous scoring through the fleet; element i aligns with
        records[i] (the endpoint contract, preserved end to end)."""
        req = self.submit(records=records, tenant=tenant,
                          deadline_ms=deadline_ms, model_id=model_id)
        res: FleetResult = req.wait(timeout_s)
        return res.results

    # -- per-model hosting + quotas (ISSUE 20) ------------------------------
    def set_hosting(self, assignments: dict) -> None:
        """Install a placement plan's ``{instance: [model_id, ...]}``
        map onto the handles (unknown instances ignored: the plan may
        lead membership during a scale-up)."""
        for h in self.replicas():
            models = assignments.get(h.instance)
            if models is None:
                continue
            with h.lock:
                h.hosted_models = {str(m) for m in models}

    def hosting_map(self) -> dict:
        """``{instance: sorted hosted model_ids}`` across live
        replicas."""
        out = {}
        for h in self.replicas():
            if not h.alive:
                continue
            with h.lock:
                out[h.instance] = sorted(h.hosted_models)
        return out

    def _model_inflight_rows(self, model_id: str) -> int:
        """Rows currently dispatched (pending on some replica) or in
        the retry lane for one model - the quantity the per-model quota
        caps."""
        total = 0
        for h in self.replicas():
            with h.lock:
                for req in h.pending.values():
                    if getattr(req.record, "model_id", None) == model_id:
                        total += req.record.n_rows
        with self._retry_lock:
            for req in self._retry:
                if getattr(req.record, "model_id", None) == model_id:
                    total += req.record.n_rows
        return total

    # -- dispatch -----------------------------------------------------------
    def _try_fast_dispatch(self) -> None:
        """Caller-thread fast path: when nothing waits ahead (no
        failover retries) and a replica has capacity, take the queue
        head and send it right here - two context switches cheaper per
        request than waking the dispatcher thread, which remains the
        slow path for the queued/backpressure case.  FIFO holds: only
        the queue HEAD is taken, and only when the retry deque is
        empty."""
        with self._retry_lock:
            if self._retry:
                return
        if self._pick(0) is None:
            return  # every replica full: the dispatcher's park owns it
        live, shed = self.admission.take(1)
        for r in shed:
            if not r.abandoned:
                with self._ctr_lock:
                    self.shed_deadline += 1
        if not live:
            return
        req = live[0]
        while not self._stop.is_set():
            handle = self._pick(req.record.n_rows,
                                getattr(req.record, "model_id", None))
            if handle is None:
                # capacity vanished between the probe and the take
                # (racing caller): hand the head back to the FRONT of
                # the retry lane - the dispatcher drains it within one
                # quantum, order preserved
                with self._retry_lock:
                    self._retry.appendleft(req)
                return
            done, _rid = self._send_to(handle, req)
            if done:
                return
        # the router closed while we held a taken request: it is in no
        # queue and no pending map, so close()'s drain cannot reach it
        # - fail it here or its caller blocks out its full wait timeout
        req.resolve(error=FleetError("router closed"))

    def _next_request(self) -> Optional[_Request]:
        """Failover retries first (they already waited once), then the
        admission queue; returns None after a bounded idle quantum."""
        with self._retry_lock:
            if self._retry:
                return self._retry.popleft()
        if not self.admission.wait_nonempty(QUANTUM_S):
            return None
        live, shed = self.admission.take(1)
        for req in shed:
            if not req.abandoned:
                with self._ctr_lock:
                    self.shed_deadline += 1
        return live[0] if live else None

    def _pick(self, n_rows: int,
              model_id: Optional[str] = None) -> Optional[ReplicaHandle]:
        candidates = [
            h for h in self.replicas()
            if h.alive and not h.drained
            and h.health.state == "healthy"
            and h.in_flight() < self.max_in_flight_per_replica
            and (model_id is None or h.hosts(model_id))
        ]
        if not candidates:
            return None
        return min(candidates,
                   key=lambda h: h.expected_wait_s(n_rows,
                                                   self.cost_model))

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                req = self._next_request()
                if req is None:
                    continue
                slept = _faults.inject_sleep("fleet.router_stall")
                if slept:
                    with self._ctr_lock:
                        self.router_stalls += 1
                self._dispatch_one(req)
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("fleet dispatch loop error")

    def _dispatch_one(self, req: _Request) -> None:
        """Assign one request to the least-loaded replica, waiting in
        bounded quanta while every replica is at its in-flight cap (or
        ejected pending readmission); a request whose deadline passes
        while waiting sheds, and a fleet with no live replica fails it
        loudly."""
        batch: FleetBatch = req.record  # type: ignore[assignment]
        while not self._stop.is_set():
            if req.deadline is not None and self.clock() > req.deadline:
                if req.resolve_delivered(error=DeadlineExceededError(
                        "deadline exceeded waiting for replica "
                        "capacity")):
                    with self._ctr_lock:
                        self.shed_deadline += 1
                return
            # clear BEFORE picking: a response landing between the pick
            # and the wait still wakes the next wait immediately
            self._capacity.clear()
            handle = self._pick(batch.n_rows, batch.model_id)
            if handle is not None:
                done, _rid = self._send_to(handle, req)
                if done:
                    return
                continue  # the picked replica died mid-send: repick
            if not self.live_replicas():
                req.resolve_delivered(error=FleetError(
                    "no live replica to serve on"))
                with self._ctr_lock:
                    self.requests_failed += 1
                return
            if (batch.model_id is not None
                    and not any(h.alive and h.hosts(batch.model_id)
                                for h in self.replicas())):
                # the hosting set changed after admission (scale-down,
                # unhost): parked work for a model nobody hosts must
                # fail loudly, not wait forever for capacity
                if req.resolve_delivered(error=UnhostedModelError(
                        f"no replica hosts model {batch.model_id!r} "
                        "anymore")):
                    with self._ctr_lock:
                        self.unhosted_model_errors += 1
                        self.requests_failed += 1
                        self.rows_failed += batch.n_rows
                return
            # all replicas full (or ejected, probing toward
            # readmission): park until a response frees capacity,
            # bounded at one quantum either way
            self._capacity.wait(QUANTUM_S)
        req.resolve_delivered(error=FleetError("router closed"))

    def _send_to(self, handle: ReplicaHandle, req: _Request,
                 op: int = OP_SCORE) -> tuple[bool, Optional[int]]:
        """-> (owned_elsewhere_or_sent, rid).  ``True`` means the
        caller must NOT touch ``req`` again: it was either sent (rid
        returned, response pending) or - on a send failure that raced
        the receiver's death handling - already harvested into the
        retry lane by ``_on_replica_dead`` (rid None).  ``False`` means
        the send failed and the caller still OWNS the request (exactly
        one of the two failure paths keeps it: whoever popped the rid)
        and may re-dispatch it inline."""
        batch: FleetBatch = req.record  # type: ignore[assignment]
        rid = next(self._req_ids)
        if op == OP_SCORE:
            meta = {"tenant": batch.tenant, "n_rows": batch.n_rows}
            if batch.model_id is not None:
                meta["model_id"] = batch.model_id
            if req.deadline is not None:
                # the caller's remaining budget rides the wire as an
                # absolute wall-clock deadline (cross-host clock skew
                # eats into slack, never adds budget) so the replica
                # can drop work the caller already abandoned - e.g. a
                # batch that sat in a partitioned socket's kernel
                # buffer until long after its caller gave up
                remaining_s = req.deadline - self.clock()
                meta["deadline_unix"] = time.time() + remaining_s
        else:
            meta = dict(batch.ctl)
        with handle.lock:
            if not handle.alive:
                return False, None
            if (op == OP_SCORE
                    and len(handle.pending)
                    >= self.max_in_flight_per_replica):
                # the cap is enforced HERE, under the lock: _pick's
                # unlocked probe can race concurrent fast-path
                # submitters, and the per-replica in-flight bound is a
                # promise, not a hint (control ops bypass it - a
                # drained replica must still take its deploy).  The
                # caller repicks; _pick's own locked read then sees the
                # replica full.
                return False, None
            handle.pending[rid] = req
            handle.in_flight_rows += batch.n_rows
        # stash for the service-time EWMA (send->response wall) and the
        # health scanner's silence ceiling
        req.record._sent_at = time.perf_counter()  # type: ignore
        if op == OP_SCORE:
            req.record._resp_deadline = (  # type: ignore
                time.monotonic() + self.response_timeout_s)
        try:
            handle.channel.send(op, rid, meta, batch.payload,
                                timeout_s=self.send_timeout_s,
                                stop=self._stop)
        except (ChannelClosedError, ChannelTimeoutError) as e:
            # ownership race with the receiver thread's death handling:
            # if IT noticed the dead channel first, _on_replica_dead
            # already popped our rid and queued the request into the
            # retry lane - retrying here too would DOUBLE-dispatch (two
            # survivors both scoring, the ledger counting one request
            # twice).  Whoever pops the rid owns the retry.
            with handle.lock:
                popped = handle.pending.pop(rid, None)
                if popped is not None:
                    handle.in_flight_rows -= batch.n_rows
            self._on_replica_dead(handle, f"send failed: {e}")
            return (popped is None), None
        return True, rid

    # -- responses ----------------------------------------------------------
    def _receive_loop(self, handle: ReplicaHandle) -> None:
        while not self._stop.is_set() and handle.alive:
            try:
                msg = handle.channel.recv(stop=self._stop)
            except ChannelProtocolError as e:
                with self._ctr_lock:
                    self.protocol_errors += 1
                self._on_replica_dead(handle, f"protocol error: {e}")
                return
            except ChannelClosedError as e:
                self._on_replica_dead(handle, str(e))
                return
            if msg is None:
                continue
            op, rid, meta, payload = msg
            if op == OP_HELLO:
                continue  # connection management, not data
            now_pc = time.perf_counter()
            with handle.lock:
                req = handle.pending.pop(rid, None)
                if req is not None:
                    handle.in_flight_rows -= req.record.n_rows
                    sent_at = getattr(req.record, "_sent_at", None)
                    handle.health.record_success(
                        None if sent_at is None
                        else (now_pc - sent_at) * 1e3,
                        time.monotonic())
            self._capacity.set()  # a parked dispatcher can send again
            if req is None:
                continue  # unknown id: already failed over elsewhere
            if req.record.kind == "probe":
                if op in (OP_CONTROL_RESULT, OP_RESULT):
                    self._readmit(handle)
                else:
                    with handle.lock:
                        handle.health.probe_failed(
                            str(meta.get("error", "probe error")),
                            time.monotonic())
                    with self._ctr_lock:
                        self.probes_failed += 1
                continue
            if op in (OP_RESULT, OP_CONTROL_RESULT):
                self._resolve_ok(handle, req, rid, meta, payload,
                                 scored=op == OP_RESULT)
            elif op == OP_ERROR:
                if meta.get("kind") == "deadline":
                    # the replica dropped work whose caller had already
                    # abandoned it: deadline shed, not a worker failure
                    with self._ctr_lock:
                        self.deadline_dropped_remote += 1
                    if req.resolve_delivered(error=DeadlineExceededError(
                            f"replica {handle.instance} dropped work "
                            "whose deadline had already passed")):
                        with self._ctr_lock:
                            self.shed_deadline += 1
                elif req.resolve_delivered(error=FleetWorkerError(
                        str(meta.get("error", "worker error")))):
                    with self._ctr_lock:
                        self.requests_failed += 1
                        self.rows_failed += req.record.n_rows

    def _resolve_ok(self, handle: ReplicaHandle, req: _Request,
                    rid: int, meta: dict, payload: bytes,
                    scored: bool) -> None:
        batch: FleetBatch = req.record  # type: ignore[assignment]
        meta = dict(meta, instance=handle.instance, request_id=rid)
        delivered = req.resolve_delivered(result=FleetResult(
            meta, payload, on_decode_error=self._count_decode_error))
        if not scored:
            return
        n = int(meta.get("n_rows", batch.n_rows))
        wall = time.perf_counter() - getattr(batch, "_sent_at",
                                             time.perf_counter())
        if n > 0 and wall > 0:
            per_row = wall / n
            handle.svc_s_ewma = (
                per_row if handle.svc_s_ewma is None
                else (1 - _SVC_ALPHA) * handle.svc_s_ewma
                + _SVC_ALPHA * per_row
            )
            if self.cost_model is not None:
                try:
                    from ..autotune import candidate_features

                    self.cost_model.observe(
                        "serve.batch/" + handle.instance,
                        candidate_features(n, 0), wall * 1e3)
                except Exception as e:  # noqa: BLE001 - estimate only
                    log.debug("cost-model observe failed: %s", e)
        handle.last_version = meta.get("version")
        handle.last_generation = meta.get("generation")
        with handle.lock:
            handle.rows_ok += n
            handle.requests_ok += 1
        gen_key = f"{meta.get('version')}/g{meta.get('generation')}"
        model_key = str(meta.get("model_id", batch.model_id) or "_default")
        with self._ctr_lock:
            if delivered:
                self.requests_ok += 1
                self.rows_ok += n
                self._rows_by_generation[gen_key] = (
                    self._rows_by_generation.get(gen_key, 0) + n)
                self._rows_by_model[model_key] = (
                    self._rows_by_model.get(model_key, 0) + n)

    def _count_decode_error(self) -> None:
        with self._ctr_lock:
            self.decode_errors += 1

    # -- failover + health --------------------------------------------------
    def _requeue_orphans(self, handle: ReplicaHandle,
                         orphans: Sequence[_Request],
                         reason: str) -> None:
        """Fail over a dead/ejected replica's in-flight requests to
        survivors via the retry lane (at-least-once, MAX_FAILOVERS
        budgeted); control ops fail loudly, probes are the health
        loop's own bookkeeping."""
        for req in orphans:
            if req.done.is_set():
                continue
            if req.record.kind == "probe":
                continue  # the health loop owns the probe lifecycle
            if req.record.kind == "ctl":
                # control ops are not idempotent-by-construction the way
                # scoring is: surface the failure to the operator path
                req.resolve_delivered(error=FleetError(
                    f"replica {handle.instance} died during a control "
                    f"operation ({reason})"))
                continue
            if req.record.retries >= MAX_FAILOVERS:
                # a poison batch must not cascade replica to replica
                if req.resolve_delivered(error=FleetError(
                        f"request failed over {req.record.retries} "
                        f"times (last replica {handle.instance}: "
                        f"{reason}); refusing further retries")):
                    with self._ctr_lock:
                        self.requests_failed += 1
                        self.rows_failed += req.record.n_rows
                continue
            req.record.retries += 1
            with self._ctr_lock:
                self.retries += 1
            with self._retry_lock:
                self._retry.append(req)

    def _on_replica_dead(self, handle: ReplicaHandle,
                         reason: str) -> None:
        now = time.monotonic()
        with handle.lock:
            if not handle.alive:
                return
            handle.alive = False
            handle.health.force_eject(f"channel dead: {reason}", now)
            orphans = list(handle.pending.values())
            handle.pending.clear()
            handle.in_flight_rows = 0
        handle.channel.close()
        self._capacity.set()  # wake a parked dispatcher to re-plan
        with self._ctr_lock:
            self.replica_deaths += 1
            self.ejections += 1
        tracer().event("fleet.ejection", instance=handle.instance,
                       reason=f"channel dead: {reason}")
        log.warning("%s replica %s dead (%s): failing over %d in-flight "
                    "request(s) to survivors", LOG_PREFIX,
                    handle.instance, reason, len(orphans))
        self._requeue_orphans(handle, orphans, reason)

    def _eject(self, handle: ReplicaHandle, reason: str,
               now: float) -> None:
        """Eject a replica whose CHANNEL still looks alive (the
        partitioned-peer case): stop dispatching to it, fail its
        in-flight work over, leave the socket open so a heal can
        readmit over the same connection."""
        with handle.lock:
            handle.health.force_eject(reason, now)
            orphans = list(handle.pending.values())
            handle.pending.clear()
            handle.in_flight_rows = 0
        self._capacity.set()
        with self._ctr_lock:
            self.ejections += 1
        tracer().event("fleet.ejection", instance=handle.instance,
                       reason=str(reason))
        log.warning("%s replica %s EJECTED (%s): failing over %d "
                    "in-flight request(s) to survivors", LOG_PREFIX,
                    handle.instance, reason, len(orphans))
        self._requeue_orphans(handle, orphans, f"ejected: {reason}")

    def _readmit(self, handle: ReplicaHandle) -> None:
        with handle.lock:
            readmitted = handle.health.readmit(time.monotonic())
        if not readmitted:
            return
        self._capacity.set()
        with self._ctr_lock:
            self.readmissions += 1
        tracer().event("fleet.readmission", instance=handle.instance)
        log.warning("%s replica %s READMITTED after probe pong",
                    LOG_PREFIX, handle.instance)

    def _health_loop(self) -> None:
        """Failure detector + readmission prober: scans in-flight
        requests against the silence ceiling, ejects on consecutive
        failures, and probes ejected replicas at a bounded rate."""
        while not self._stop.is_set():
            try:
                now = time.monotonic()
                for handle in self.replicas():
                    self._health_tick(handle, now)
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("fleet health loop error")
            self._stop.wait(QUANTUM_S)

    def _health_tick(self, handle: ReplicaHandle, now: float) -> None:
        st = handle.health
        if st.state == "healthy":
            if handle.alive:
                self._scan_response_timeouts(handle, now)
            return
        if st.state == "probing":
            if (st.probe_sent_at is not None
                    and now - st.probe_sent_at > self.probe_timeout_s):
                with handle.lock:
                    if st.probe_rid is not None:
                        handle.pending.pop(st.probe_rid, None)
                    st.probe_failed("probe unanswered", now)
                with self._ctr_lock:
                    self.probes_failed += 1
            return
        # ejected: rate-bounded readmission probing - at most one
        # probe (or reconnect attempt) per probe_interval_s, so a
        # flapping or storming peer sees a bounded connect rate
        if (st.last_probe_at is not None
                and now - st.last_probe_at < self.probe_interval_s):
            return
        st.last_probe_at = now
        if not handle.alive or handle.channel.closed:
            if not self._probe_reconnect(handle):
                with handle.lock:
                    st.probes_sent += 1
                    st.probes_failed += 1
                with self._ctr_lock:
                    self.probes_sent += 1
                    self.probes_failed += 1
                return
        self._send_probe(handle, now)

    def _scan_response_timeouts(self, handle: ReplicaHandle,
                                now: float) -> None:
        """Pop score requests a replica has sat on past the silence
        ceiling and fail them over; enough consecutive timeouts eject
        the replica (the partition detector: a partitioned TCP peer
        never EOFs, it just goes quiet)."""
        timed_out = []
        with handle.lock:
            for rid, req in list(handle.pending.items()):
                batch = req.record
                if getattr(batch, "kind", "score") != "score":
                    continue  # ctl ops own their timeout (control())
                rd = getattr(batch, "_resp_deadline", None)
                if rd is not None and now > rd:
                    handle.pending.pop(rid)
                    handle.in_flight_rows -= batch.n_rows
                    timed_out.append(req)
            newly_ejected = False
            for _req in timed_out:
                if handle.health.record_failure("response timeout",
                                                now):
                    newly_ejected = True
        if not timed_out:
            return
        self._capacity.set()
        with self._ctr_lock:
            self.response_timeouts += len(timed_out)
        log.warning("%s replica %s silent past %.1fs on %d request(s):"
                    " failing over", LOG_PREFIX, handle.instance,
                    self.response_timeout_s, len(timed_out))
        self._requeue_orphans(
            handle, timed_out,
            f"response timeout (> {self.response_timeout_s}s)")
        if newly_ejected:
            self._eject(handle, "consecutive response timeouts", now)

    def _probe_reconnect(self, handle: ReplicaHandle) -> bool:
        """Reconnect a dead channel for probing (bounded by the probe
        timeout; the worker's newest-connection-wins accept loop makes
        this safe to race against the controller's restart path)."""
        if handle.address is None:
            return False
        try:
            channel = connect(
                handle.address, timeout_s=self.probe_timeout_s,
                handshake_timeout_s=min(self.probe_timeout_s,
                                        HANDSHAKE_TIMEOUT_S))
        except (ChannelClosedError, ChannelTimeoutError,
                ChannelProtocolError, OSError) as e:
            with handle.lock:
                handle.health.last_error = f"reconnect failed: {e}"
            log.info("%s replica %s reconnect probe failed: %s",
                     LOG_PREFIX, handle.instance, e)
            return False
        with handle.lock:
            old = handle.channel
            handle.fold_wire_stats()
            handle.channel = channel
            handle.alive = True
            if channel.peer and channel.peer.get("pid"):
                handle.pid = channel.peer["pid"]
        old.close()
        handle.receiver = _ctx_thread(
            self._receive_loop, f"tx-fleet-recv-{handle.instance}",
            handle)
        handle.receiver.start()
        log.info("%s replica %s channel reconnected by readmission "
                 "probe", LOG_PREFIX, handle.instance)
        return True

    def _send_probe(self, handle: ReplicaHandle, now: float) -> None:
        """One half-open probe: a control ping whose pong (and nothing
        else) readmits the replica."""
        st = handle.health
        with handle.lock:
            st.begin_probe(now)
        with self._ctr_lock:
            self.probes_sent += 1
        batch = FleetBatch(payload=b"", n_rows=0, kind="probe",
                           ctl={"cmd": "ping"})
        req = _Request(record=batch, enqueued_at=self.clock())
        sent, rid = self._send_to(handle, req, op=OP_CONTROL)
        if not sent or rid is None:
            with handle.lock:
                st.probe_failed("probe send failed", now)
            with self._ctr_lock:
                self.probes_failed += 1
            return
        with handle.lock:
            if st.state == "probing":
                # the pong can beat us here (readmitted already): only
                # arm the timeout bookkeeping while the probe is live
                st.probe_rid = rid

    # -- control plane ------------------------------------------------------
    def control(self, instance: str, cmd: str,
                args: Optional[dict] = None,
                timeout_s: float = 120.0) -> Any:
        """One control round trip to a named replica (deploy / canary /
        status / ...); bypasses admission and the drain flag - draining
        a replica is exactly how a rolling deploy makes room to send it
        control traffic."""
        handle = self.handle(instance)
        if not handle.alive:
            raise FleetError(f"replica {instance!r} is not alive")
        batch = FleetBatch(payload=b"", n_rows=0, kind="ctl",
                           ctl=dict(args or {}, cmd=cmd))
        req = _Request(record=batch, enqueued_at=self.clock())
        sent, rid = self._send_to(handle, req, op=OP_CONTROL)
        if not sent or rid is None:
            raise FleetError(f"replica {instance!r} died mid-control")
        try:
            res: FleetResult = req.wait(timeout_s)
        except RequestTimeoutError:
            # reclaim the in-flight slot: a leaked pending entry would
            # hold one max_in_flight slot forever and keep
            # wait_drained() from ever seeing zero (a late reply finds
            # the rid gone and is dropped)
            with handle.lock:
                handle.pending.pop(rid, None)
            raise
        return res.doc

    def broadcast(self, cmd: str, args: Optional[dict] = None,
                  timeout_s: float = 120.0) -> dict:
        """The control op on every LIVE replica; per-instance results
        (exceptions captured as ``{"error": ...}`` so one dead replica
        cannot abort a fleet-wide rollback)."""
        out = {}
        for h in self.live_replicas():
            try:
                out[h.instance] = self.control(h.instance, cmd, args,
                                               timeout_s)
            except (FleetError, FleetWorkerError,
                    RequestTimeoutError) as e:
                out[h.instance] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def set_drained(self, instance: str, drained: bool = True) -> None:
        self.handle(instance).drained = bool(drained)

    def wait_drained(self, instance: str, timeout_s: float = 30.0) -> bool:
        """True once the replica has zero in-flight requests (its
        drained flag stops NEW dispatches; in-flight batches finish on
        the old generation - the zero-drop half of a rolling deploy)."""
        handle = self.handle(instance)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() <= deadline:
            if handle.in_flight() == 0:
                return True
            time.sleep(QUANTUM_S)
        return False

    # -- observed load refresh ----------------------------------------------
    def refresh_from_shards(self, metrics_docs: Sequence[dict]) -> int:
        """Fold the fleet aggregation dir's per-replica serving stats
        into the dispatch weights (ISSUE 14 satellite: the router reads
        observed throughput/p99 from fleet shards).  ``metrics_docs``
        is ``FleetAggregator.merged_metrics_docs()``; returns how many
        handles were updated."""
        from ..obs.fleet import serving_views

        by_instance = {str(d.get("instance")): d for d in metrics_docs}
        updated = 0
        for h in self.replicas():
            doc = by_instance.get(h.instance)
            if doc is None:
                continue
            best: dict = {}
            for _key, snap in serving_views(doc):
                rps = snap.get("batch_rows_per_s") or 0
                if rps >= best.get("batch_rows_per_s", 0):
                    best = {
                        "batch_rows_per_s": rps,
                        "p99_ms": (snap.get("latency_ms") or {}).get(
                            "p99"),
                        "queue_depth_p99": (snap.get("queue_depth")
                                            or {}).get("p99"),
                        "rows_scored": snap.get("rows_scored"),
                    }
            if best:
                h.obs = best
                updated += 1
            # fold the replica's own hosted-model report (its
            # fleet_replica view rides the same shard): the replica is
            # the authority on what it actually hosts, so a placement
            # plan applied out-of-band still converges here
            for key, snap in (doc.get("views") or {}).items():
                if (key.partition("/")[0] == "fleet_replica"
                        and isinstance(snap, dict)
                        and snap.get("models")):
                    with h.lock:
                        h.hosted_models = {
                            str(r.get("model_id"))
                            for r in snap["models"]
                            if r.get("model_id")}
        return updated

    # -- reporting ----------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``fleet_router`` metrics view: fleet-level counters plus
        per-replica dispatch state, scraped as ``tx_fleet_router_*``."""
        with self._ctr_lock:
            out = {
                "rows_ok": self.rows_ok,
                "rows_failed": self.rows_failed,
                "requests_ok": self.requests_ok,
                "requests_failed": self.requests_failed,
                "shed_queue_full": self.shed_queue_full,
                "shed_quota": self.shed_quota,
                "shed_deadline": self.shed_deadline,
                "shed_brownout": self.shed_brownout,
                "shed_model_quota": self.shed_model_quota,
                "unhosted_model_errors": self.unhosted_model_errors,
                "retries": self.retries,
                "replica_deaths": self.replica_deaths,
                "router_stalls": self.router_stalls,
                "response_timeouts": self.response_timeouts,
                "protocol_errors": self.protocol_errors,
                "decode_errors": self.decode_errors,
                "deadline_dropped_remote": self.deadline_dropped_remote,
                "ejections": self.ejections,
                "readmissions": self.readmissions,
                "probes_sent": self.probes_sent,
                "probes_failed": self.probes_failed,
                "rows_by_generation": dict(self._rows_by_generation),
                "rows_by_model": dict(self._rows_by_model),
            }
        out["queue_depth"] = len(self.admission)
        out["tenants_held"] = {
            str(k): v for k, v in self.admission.tenants_held().items()
        }
        out["healthy_replicas"] = len(self.healthy_replicas())
        out["quorum"] = self.quorum
        out["replicas"] = {
            h.instance: h.snapshot() for h in self.replicas()
        }
        return out

    def health_snapshot(self) -> dict:
        """The ``fleet_health`` metrics view (``tx_fleet_health_*``):
        the failure-detector plane alone - per-replica state machine +
        fleet-level ejection/readmission/probe counters - small enough
        to scrape every tick without the full router document."""
        with self._ctr_lock:
            out: dict = {
                "ejections": self.ejections,
                "readmissions": self.readmissions,
                "probes_sent": self.probes_sent,
                "probes_failed": self.probes_failed,
                "response_timeouts": self.response_timeouts,
                "protocol_errors": self.protocol_errors,
                "decode_errors": self.decode_errors,
                "deadline_dropped_remote": self.deadline_dropped_remote,
                "shed_brownout": self.shed_brownout,
            }
        reps = self.replicas()
        out["healthy_replicas"] = sum(
            1 for h in reps if h.alive and h.health.state == "healthy")
        out["quorum"] = self.quorum
        out["replicas"] = {
            h.instance: h.health.snapshot() for h in reps
        }
        return out

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop dispatching, fail everything still pending loudly, and
        close every channel (all joins bounded)."""
        self._stop.set()
        self.admission.close()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout_s)
        if self._health is not None:
            self._health.join(timeout_s)
        for req in self.admission.drain():
            req.resolve(error=FleetError("router closed"))
        with self._retry_lock:
            retry, self._retry = list(self._retry), deque()
        for req in retry:
            req.resolve(error=FleetError("router closed"))
        for h in self.replicas():
            with h.lock:
                pending = list(h.pending.values())
                h.pending.clear()
                h.alive = False
            for req in pending:
                req.resolve(error=FleetError("router closed"))
            h.channel.close()
            if h.receiver is not None:
                h.receiver.join(timeout_s)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
