"""Fleet lifecycle: spawn/supervise N replicas, rolling hot-swap,
fleet-wide canary with aggregated rollback signals.

The control plane of the scale-out serving fleet (ISSUE 14), with the
PR-5 registry as the source of truth for WHAT each replica serves:

* **supervised replicas** - N :mod:`~.worker` processes spawned with the
  PR-9 trace-context env seam, each beating a heartbeat file; a dead or
  heartbeat-stale replica is killed (if needed) and re-dispatched with
  the PR-2 exponential backoff, while the router fails its in-flight
  requests over to survivors (at-least-once, no lost accepted
  requests).
* **rolling hot-swap** - :meth:`FleetController.rolling_deploy` flips
  generations ONE replica at a time: drain (router stops dispatching,
  in-flight batches finish on the old generation), send the ``deploy``
  control (the worker's PR-5 zero-drop pointer flip), undrain, next
  replica.  Traffic keeps flowing to the rest of the fleet the whole
  time - zero dropped, zero mixed-generation responses.
* **fleet-wide canary** - :meth:`start_canary` brings the candidate up
  on every replica at one deterministic hash fraction;
  :meth:`check_canary` merges the per-replica stable/canary telemetry
  from the obs aggregation dir (sum counters, max p99/drift - the
  fleet rollup convention) and evaluates the PR-5
  :class:`~..registry.rollback.RollbackPolicy` plus the PR-9 fleet
  :class:`~..obs.slo.SLOEngine` over the merged docs: one firing
  fleet-level SLO rolls the canary back across ALL replicas.
* **one consistent status document** - the controller atomically
  publishes ``fleet_status.json`` (per-replica generation, heartbeat
  age, in-flight, restart budget) which ``tx fleet status``, the
  workers' deploy summaries, and operators read instead of N shard
  re-reads; ``tx fleet drain`` drops command files the controller
  applies.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from ..obs.fleet import (
    FleetAggregator,
    ObsShipper,
    child_env,
    read_json_torn_safe,
)
from ..obs.slo import SLOEngine, default_objectives
from ..registry import ModelRegistry, RollbackDecision, RollbackPolicy
from ..workflow.supervisor import backoff_delay_s, staleness
from .channel import QUANTUM_S
from .multimodel import (
    PlacementPlan,
    PlacementPlanner,
    UnhostedModelError,
    artifact_cache_bytes,
    format_models_arg,
)
from .router import FleetError, FleetRouter, FleetWorkerError

log = logging.getLogger("transmogrifai_tpu.fleet")

LOG_PREFIX = "op_fleet_metrics"

#: fleet status document filename (atomically replaced in control_dir)
STATUS_FILENAME = "fleet_status.json"

#: drain/undrain command files dropped by ``tx fleet drain``
COMMANDS_DIR = "commands"


def _free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port the OS just proved free on ``host`` (the
    standard bind-0 probe; the worker re-binds it with SO_REUSEADDR, so
    the close->rebind race is benign on loopback and the port stays
    STABLE across replica restarts - the router's readmission probe
    reconnects to the same address the fleet was built with)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return int(s.getsockname()[1])


def merge_serving_snapshots(snaps: Sequence[dict]) -> dict:
    """Merge per-replica ServingTelemetry snapshots into ONE
    RollbackPolicy-consumable snapshot: counters SUM (how much fleet
    traffic failed), p99/drift MAX (how bad is the worst replica) -
    the FleetAggregator rollup convention applied to the rollback
    signal set."""
    out: dict = {
        "rows_scored": 0, "rows_failed": 0,
        "breaker": {"opens": 0, "closes": 0, "probes": 0,
                    "rows_shed": 0, "rows_nonfinite": 0},
        "latency_ms": {"p50": None, "p95": None, "p99": None},
        "data_contract": {"drift_js_max": 0.0},
        "model_version": None, "generation": None,
        "replicas": 0,
    }
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        out["replicas"] += 1
        out["rows_scored"] += int(snap.get("rows_scored", 0) or 0)
        out["rows_failed"] += int(snap.get("rows_failed", 0) or 0)
        for k in out["breaker"]:
            out["breaker"][k] += int(
                (snap.get("breaker") or {}).get(k, 0) or 0)
        for p in ("p50", "p95", "p99"):
            v = (snap.get("latency_ms") or {}).get(p)
            if v is not None and (out["latency_ms"][p] is None
                                  or v > out["latency_ms"][p]):
                out["latency_ms"][p] = v
        drift = (snap.get("data_contract") or {}).get("drift_js_max")
        if drift is not None and drift > out["data_contract"][
                "drift_js_max"]:
            out["data_contract"]["drift_js_max"] = drift
        if out["model_version"] is None:
            out["model_version"] = snap.get("model_version")
            out["generation"] = snap.get("generation")
    return out


@dataclass
class _Replica:
    index: int
    instance: str
    socket_path: str
    heartbeat_path: str
    proc: Optional[subprocess.Popen] = None
    restarts: int = 0
    restart_at: Optional[float] = None  # monotonic; None = not scheduled
    gave_up: bool = False
    #: a reconnect thread is in flight (the connect can take as long as
    #: a replica warm-up; supervision of the REST of the fleet must not
    #: stall behind it)
    reconnecting: bool = False
    #: a scale-down drain is in flight: supervision must NOT restart
    #: this replica when its process exits - retirement owns it
    retiring: bool = False
    events: list = field(default_factory=list)


class FleetController:
    """Spawn, supervise, and lifecycle a replica fleet (module
    docstring)."""

    def __init__(
        self,
        registry_root: str,
        workflow_spec: str,
        n_replicas: int = 2,
        work_dir: Optional[str] = None,
        fleet_dir: Optional[str] = None,
        control_dir: Optional[str] = None,
        version: Optional[str] = None,
        policy: Optional[RollbackPolicy] = None,
        slo_objectives: Optional[list] = None,
        router_kw: Optional[dict] = None,
        worker_args: Optional[Sequence[str]] = None,
        worker_env: Optional[dict] = None,
        worker_env_overrides: Optional[dict] = None,
        transport: str = "unix",
        tcp_host: str = "127.0.0.1",
        ship_router_obs: bool = False,
        max_restarts: int = 2,
        stale_after_s: float = 60.0,
        connect_timeout_s: float = 180.0,
        ship_interval_s: float = 0.25,
        use_cost_model: bool = True,
        monitor_interval_s: float = 0.2,
        eject_after: Optional[int] = None,
        probe_interval_s: Optional[float] = None,
        probe_timeout_s: Optional[float] = None,
        models: Optional[dict] = None,
        placement: Optional[PlacementPlanner] = None,
    ) -> None:
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.registry_root = registry_root
        self.workflow_spec = workflow_spec
        self.n_replicas = int(n_replicas)
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="tx-fleet-")
        self.fleet_dir = fleet_dir or os.path.join(self.work_dir, "obs")
        self.control_dir = control_dir or os.path.join(self.work_dir,
                                                       "control")
        self.version = version
        self.worker_args = list(worker_args or ())
        self.worker_env = dict(worker_env or {})
        #: per-instance env on top of ``worker_env`` (e.g. arming
        #: TX_FAULTS on exactly one replica for a partition drill)
        self.worker_env_overrides = dict(worker_env_overrides or {})
        if transport not in ("unix", "tcp"):
            raise ValueError(
                f"transport must be 'unix' or 'tcp', got {transport!r}")
        #: "unix" keeps the on-host fast path; "tcp" binds each replica
        #: to ``tcp_host:<ephemeral>`` - the cross-host wire, drillable
        #: on loopback
        self.transport = transport
        self.tcp_host = tcp_host
        #: ship the ROUTER process's obs (the fleet_router/fleet_health
        #: views) as its own shard so one scrape of the aggregation dir
        #: includes ejection/readmission gauges; off by default - a
        #: controller embedded in a test/serving process would ship that
        #: process's unrelated views too
        self.ship_router_obs = bool(ship_router_obs)
        self._router_shipper: Optional[ObsShipper] = None
        self.max_restarts = int(max_restarts)
        self.stale_after_s = float(stale_after_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.ship_interval_s = float(ship_interval_s)
        self.use_cost_model = bool(use_cost_model)
        self.monitor_interval_s = max(0.05, float(monitor_interval_s))
        self.registry = ModelRegistry(registry_root, create=False)
        self.aggregator = FleetAggregator(self.fleet_dir)
        self.slo_engine = SLOEngine(
            slo_objectives if slo_objectives is not None
            else default_objectives(),
            doc_fn=self.aggregator.merged_metrics_docs,
            register=False,
        )
        self.policy = policy if policy is not None else RollbackPolicy()
        self.policy.slo_engine = self.slo_engine
        self._router_kw = dict(router_kw or {})
        # ReplicaHealth eject/readmit knobs surfaced here (ISSUE 19
        # satellite) instead of router_kw-only: explicit kwargs win
        # over router_kw defaults, None leaves the router's own
        for knob, val in (("eject_after", eject_after),
                          ("probe_interval_s", probe_interval_s),
                          ("probe_timeout_s", probe_timeout_s)):
            if val is not None:
                self._router_kw[knob] = val
        self.router: Optional[FleetRouter] = None
        self.canary_version: Optional[str] = None
        # multi-model serving (ISSUE 20): {model_id: version} hosted
        # across the fleet; the placement planner decides co-residency
        # and is re-run on membership changes
        self.models = {str(k): str(v)
                       for k, v in (models or {}).items()}
        self.placement_planner = placement
        if self.models and self.placement_planner is None:
            self.placement_planner = PlacementPlanner()
        self.placement: Optional[PlacementPlan] = None
        #: per-model in-flight fleet canaries: {model_id: version} -
        #: each hosted model's lifecycle is independent of the fleet's
        #: single-model canary slot above
        self.model_canaries: dict[str, str] = {}
        #: attached by :class:`~.autoscaler.FleetAutoscaler.start` -
        #: folds its decision snapshot into ``status()`` /
        #: ``fleet_status.json``
        self.autoscaler = None
        self._next_index = 0
        self._replicas: dict[str, _Replica] = {}
        self._events: list[dict] = []
        self._events_lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.started = False

    # -- lifecycle ----------------------------------------------------------
    def _event(self, event: str, **kw: Any) -> None:
        entry = {"event": event, "t": time.time(), **kw}
        with self._events_lock:
            self._events.append(entry)
            if len(self._events) > 256:
                del self._events[0]

    def start(self) -> "FleetController":
        os.makedirs(self.work_dir, exist_ok=True)
        os.makedirs(self.fleet_dir, exist_ok=True)
        os.makedirs(os.path.join(self.control_dir, COMMANDS_DIR),
                    exist_ok=True)
        cost_model = self._load_cost_model() if self.use_cost_model \
            else None
        self.router = FleetRouter(cost_model=cost_model,
                                  **self._router_kw)
        if self.ship_router_obs:
            self._router_shipper = ObsShipper(
                self.fleet_dir, interval_s=self.ship_interval_s,
                instance="router").start()
        try:
            for _ in range(self.n_replicas):
                rep = self._new_replica()
                self._replicas[rep.instance] = rep
            # place BEFORE spawning so each worker's --models carries
            # exactly its assigned co-residency set (ISSUE 20)
            self._replan_placement(reason="fleet_start")
            for rep in self._replicas.values():
                self._spawn(rep)
            # connect AFTER spawning: replicas warm concurrently
            for rep in self._replicas.values():
                self.router.add_replica(
                    rep.instance, rep.socket_path,
                    connect_timeout_s=self.connect_timeout_s,
                    pid=rep.proc.pid if rep.proc else None)
            if self.placement is not None:
                self.router.set_hosting(self.placement.assignments)
        except BaseException:
            # a partially-failed bring-up (bad workflow spec, worker
            # crash at startup) must not leak spawned processes, the
            # router's threads, or its registered metrics view onto the
            # caller - `with FleetController(...)` never reaches
            # __exit__ when start() raises
            self.stop(timeout_s=5.0)
            raise
        self._event("fleet_start", replicas=self.n_replicas,
                    registry=self.registry_root)
        self.started = True
        self._write_status()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="tx-fleet-monitor",
            daemon=True)
        self._monitor.start()
        log.info("%s fleet up: %d replicas over %s", LOG_PREFIX,
                 self.n_replicas, self.registry_root)
        return self

    def _load_cost_model(self):
        """The PR-13 cost model rides the deployed artifact
        (``autotune.json`` next to the model); when present the router
        weights its dispatch with it (ISSUE 14 satellite)."""
        try:
            version = self.version or self.registry.stable
            if version is None:
                return None
            path = os.path.join(self.registry.artifact_path(version),
                                "autotune.json")
            if not os.path.exists(path):
                return None
            from ..autotune import CostModel

            cm = CostModel.load(path)
            log.info("%s router dispatch weighted by cost model %s",
                     LOG_PREFIX, path)
            return cm
        except Exception as e:  # noqa: BLE001 - weighting is optional
            log.warning("cost model load failed (round-robin-ish "
                        "weights): %s", e)
            return None

    def _new_replica(self) -> _Replica:
        """Allocate the next replica slot (monotonic index: a retired
        ``replica-2`` is never reused for a later scale-up, so events,
        heartbeat files, and trace history stay unambiguous)."""
        i = self._next_index
        self._next_index += 1
        if self.transport == "tcp":
            address = f"{self.tcp_host}:{_free_port(self.tcp_host)}"
        else:
            address = os.path.join(self.work_dir, f"replica-{i}.sock")
        return _Replica(
            index=i,
            instance=f"replica-{i}",
            socket_path=address,
            heartbeat_path=os.path.join(self.work_dir,
                                        f"replica-{i}.hb"),
        )

    def _worker_cmd(self, rep: _Replica) -> list[str]:
        cmd = [
            sys.executable, "-m", "transmogrifai_tpu.fleet.worker",
            "--registry-root", self.registry_root,
            "--workflow", self.workflow_spec,
            "--socket", rep.socket_path,
            "--instance", rep.instance,
            "--heartbeat", rep.heartbeat_path,
            "--fleet-dir", self.fleet_dir,
            "--fleet-status-path",
            os.path.join(self.control_dir, STATUS_FILENAME),
            "--ship-interval-s", str(self.ship_interval_s),
        ]
        if self.version:
            cmd += ["--version", self.version]
        assigned = self._models_for_instance(rep.instance)
        if assigned:
            cmd += ["--models", format_models_arg(assigned)]
        cmd += self.worker_args
        return cmd

    def _models_for_instance(self, instance: str) -> dict:
        """{model_id: version} this replica should host under the
        current placement plan (all configured models when no plan has
        been computed yet)."""
        if not self.models:
            return {}
        if self.placement is None:
            return dict(self.models)
        return {m: self.models[m]
                for m in self.placement.models_for(instance)
                if m in self.models}

    def _spawn(self, rep: _Replica) -> None:
        env = child_env(dict(
            os.environ, **self.worker_env,
            **self.worker_env_overrides.get(rep.instance, {})))
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the package is not pip-installed: children import it from the
        # repo root, wherever the controller process found it
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        stale_files = [rep.heartbeat_path]
        if self.transport != "tcp":
            stale_files.append(rep.socket_path)
        for stale in stale_files:
            # the DEAD incarnation's heartbeat file must go too: its
            # frozen mtime is by construction older than stale_after_s
            # by restart time, and judging the fresh warming process by
            # it would stale-kill every restart (staleness() returns
            # None until the new process actually beats)
            try:
                os.unlink(stale)
            except OSError:
                pass  # nothing stale to clear
        rep.proc = subprocess.Popen(self._worker_cmd(rep), env=env)
        rep.events.append({"event": "spawn", "pid": rep.proc.pid,
                           "t": time.time()})

    # -- supervision --------------------------------------------------------
    def _monitor_loop(self) -> None:
        last_status = 0.0
        last_refresh = 0.0
        while not self._stop.wait(self.monitor_interval_s):
            try:
                self._check_replicas()
                self._poll_commands()
                now = time.monotonic()
                shards = None
                if now - last_refresh >= 0.5:
                    last_refresh = now
                    # ONE shard read serves both the weight refresh and
                    # the status publish this tick - shards carry the
                    # whole span ring, and double-parsing them twice a
                    # second is pure waste
                    shards = self.aggregator.shards()
                    self.router.refresh_from_shards([
                        dict(d.get("metrics", {}),
                             instance=str(d.get("instance")))
                        for d in shards
                    ])
                if now - last_status >= 0.5:
                    last_status = now
                    self._write_status(shards=shards)
            except Exception:  # noqa: BLE001 - supervision must survive
                log.exception("fleet monitor loop error")

    def _reconnect(self, rep: _Replica) -> None:
        """Connect a restarted worker's channel on a side thread: the
        connect blocks for the replica's whole warm-up (up to
        ``connect_timeout_s``), and the monitor loop must keep
        supervising the REST of the fleet - heartbeat kills, drain
        commands, status publishing - meanwhile."""
        try:
            self.router.add_replica(
                rep.instance, rep.socket_path,
                connect_timeout_s=self.connect_timeout_s,
                pid=rep.proc.pid if rep.proc else None)
            self._event("replica_restarted", instance=rep.instance,
                        attempt=rep.restarts)
        except Exception as e:  # noqa: BLE001 - keep supervising
            log.warning("restarted replica %s did not come up: %s",
                        rep.instance, e)
        finally:
            rep.reconnecting = False

    def _check_replicas(self) -> None:
        for rep in list(self._replicas.values()):
            if rep.gave_up or rep.proc is None or rep.reconnecting \
                    or rep.retiring:
                continue
            rc = rep.proc.poll()
            stale = staleness(rep.heartbeat_path)
            if rc is None and stale is not None \
                    and stale > self.stale_after_s:
                # alive but wedged: the supervision rule - kill it and
                # let the restart path take over (PR-2 semantics)
                log.warning("%s replica %s heartbeat stale %.0fs: "
                            "killing", LOG_PREFIX, rep.instance, stale)
                rep.proc.kill()
                try:
                    rep.proc.wait(timeout=30.0)
                except subprocess.TimeoutExpired:
                    continue  # D-state child: retry next tick
                rc = rep.proc.returncode
            if rc is None:
                continue
            # dead: the router's receiver notices the closed channel on
            # its own and fails in-flight work over; supervision owns
            # the restart budget
            handle = None
            try:
                handle = self.router.handle(rep.instance)
            except FleetError:
                pass
            if handle is not None and handle.alive:
                self.router._on_replica_dead(
                    handle, f"process exit {rc}")
            if rep.restart_at is None:
                if rep.restarts >= self.max_restarts:
                    rep.gave_up = True
                    self._event("replica_gave_up", instance=rep.instance,
                                exit_code=rc, restarts=rep.restarts)
                    log.error("%s replica %s exhausted its restart "
                              "budget (%d)", LOG_PREFIX, rep.instance,
                              rep.restarts)
                    continue
                import random

                delay = backoff_delay_s(rep.restarts, 0.2, 10.0, 0.1,
                                        random.Random(rep.index))
                rep.restart_at = time.monotonic() + delay
                self._event("replica_down", instance=rep.instance,
                            exit_code=rc, backoff_s=round(delay, 3))
            elif time.monotonic() >= rep.restart_at:
                rep.restart_at = None
                rep.restarts += 1
                self._spawn(rep)
                rep.reconnecting = True
                threading.Thread(
                    target=self._reconnect, args=(rep,),
                    name=f"tx-fleet-reconnect-{rep.instance}",
                    daemon=True).start()

    def _poll_commands(self) -> None:
        """Apply (and consume) ``tx fleet drain`` command files."""
        cdir = os.path.join(self.control_dir, COMMANDS_DIR)
        try:
            names = os.listdir(cdir)
        except OSError:
            return
        for name in names:
            if not name.endswith(".json"):
                continue
            path = os.path.join(cdir, name)
            doc = read_json_torn_safe(path)
            if doc is None:
                continue  # torn write in flight: retry next tick
            instance = str(doc.get("replica", name[:-len(".json")]))
            try:
                drained = bool(doc.get("drain", True))
                self.router.set_drained(instance, drained)
                self._event("drain" if drained else "undrain",
                            instance=instance, source="command_file")
            except FleetError as e:
                self._event("command_rejected", instance=instance,
                            error=str(e))
            try:
                os.unlink(path)
            except OSError as e:
                log.warning("could not consume command file %s: %s",
                            path, e)

    # -- elastic membership (ISSUE 19) --------------------------------------
    def member_instances(self) -> list[str]:
        """Instance names the controller currently OWNS (spawned, not
        retiring) - the autoscaler's notion of fleet size.  A replica
        mid-backoff or gave-up still counts as a member; capacity
        accounting (not membership) handles its missing throughput."""
        return [r.instance for r in self._replicas.values()
                if not r.retiring]

    def gave_up_instances(self) -> list[str]:
        """Members whose restart budget is exhausted: dead weight the
        supervisor will never bring back.  The autoscaler replaces
        their CAPACITY (sized from demand) instead of blindly
        restarting 1:1."""
        return [r.instance for r in self._replicas.values()
                if r.gave_up and not r.retiring]

    def add_replica(self, probe_timeout_s: float = 30.0) -> str:
        """Grow the fleet by one replica with probe-gated admission:
        spawn at the next free index (warming from the AOT executable
        cache like any bring-up), connect it DRAINED so no score
        traffic can reach it, health-probe it with a ``ping`` control
        round trip, and only then undrain.  A replica that fails to
        warm or answer the probe is reaped and never admitted - a bad
        scale-up is a no-op, not a degraded fleet."""
        rep = self._new_replica()
        self._replicas[rep.instance] = rep
        # re-plan placement BEFORE spawning so the new worker's
        # --models carries exactly its assigned co-residency set
        self._replan_placement(reason=f"scale_up:{rep.instance}")
        self._spawn(rep)
        try:
            self.router.add_replica(
                rep.instance, rep.socket_path,
                connect_timeout_s=self.connect_timeout_s,
                pid=rep.proc.pid if rep.proc else None,
                drained=True)
            self.router.control(rep.instance, "ping",
                                timeout_s=probe_timeout_s)
            self.router.set_drained(rep.instance, False)
            if self.placement is not None:
                # existing replicas may have lost/gained assignments
                # under the new plan: converge them
                self._reconcile_hosting()
        except BaseException:
            # failed bring-up must not leak the process or a dead
            # handle: reap both, leave the fleet exactly as it was
            self._replicas.pop(rep.instance, None)
            self.router.remove_replica(rep.instance,
                                       reason="admission failed")
            self._replan_placement(
                reason=f"admission_failed:{rep.instance}")
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.kill()
                try:
                    rep.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    log.warning("unadmitted replica %s did not reap",
                                rep.instance)
            raise
        self.n_replicas = len(self.member_instances())
        self._event("replica_added", instance=rep.instance,
                    pid=rep.proc.pid if rep.proc else None,
                    members=self.n_replicas)
        self._write_status()
        log.info("%s replica %s admitted after health probe "
                 "(%d members)", LOG_PREFIX, rep.instance,
                 self.n_replicas)
        return rep.instance

    def remove_replica(self, instance: str,
                       drain_timeout_s: float = 30.0) -> dict:
        """Shrink the fleet by retiring ``instance``, shed-never-hang:
        mark it retiring (supervision stops restarting it), drain via
        the router (no new dispatches; in-flight batches finish), then
        retire the handle and terminate the process.  A victim that
        dies mid-drain - SIGKILL included - is already owned by the
        router's failover: anything it stranded re-dispatches to
        survivors, and removal proceeds."""
        rep = self._replicas.get(instance)
        if rep is None:
            raise FleetError(f"unknown replica {instance!r}")
        if rep.retiring:
            return {"instance": instance, "already_retiring": True}
        rep.retiring = True
        report: dict = {"instance": instance, "drained": False}
        t0 = time.perf_counter()
        try:
            self.router.set_drained(instance, True)
            report["drained"] = self.router.wait_drained(
                instance, drain_timeout_s)
        except FleetError:
            # already out of router membership (died mid-drain and a
            # racing removal reaped the handle): failover owned its
            # in-flight work, nothing left to drain
            report["drained"] = True
        self.router.remove_replica(instance, reason="scale_down")
        if rep.proc is not None and rep.proc.poll() is None:
            rep.proc.terminate()
            deadline = time.monotonic() + 10.0
            while rep.proc.poll() is None \
                    and time.monotonic() < deadline:
                time.sleep(QUANTUM_S)
            if rep.proc.poll() is None:
                rep.proc.kill()
                try:
                    rep.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    log.warning("retired replica %s did not reap",
                                instance)
        self._replicas.pop(instance, None)
        self.n_replicas = max(1, len(self.member_instances()))
        if self.models:
            # the victim's hosted models need their replication copies
            # back on survivors: re-plan and converge
            self._replan_placement(reason=f"scale_down:{instance}")
            self._reconcile_hosting()
        report["drain_s"] = round(time.perf_counter() - t0, 4)
        self._event("replica_retired", **report,
                    members=len(self.member_instances()))
        self._write_status()
        log.info("%s replica %s retired (drained=%s, %.2fs, %d "
                 "members left)", LOG_PREFIX, instance,
                 report["drained"], report["drain_s"],
                 len(self.member_instances()))
        return report

    # -- multi-model placement (ISSUE 20) -----------------------------------
    def _replan_placement(self,
                          reason: str = "membership"
                          ) -> Optional[PlacementPlan]:
        """Re-run the placement planner over current membership and
        push the hosting map to the router.  Called at fleet start and
        on every membership change (scale-up/down re-balances
        co-residency)."""
        if not self.models or self.placement_planner is None:
            return None
        if (self.placement_planner.cost_model is None
                and self.router is not None
                and self.router.cost_model is not None):
            self.placement_planner.cost_model = self.router.cost_model
        instances = self.member_instances()
        if not instances:
            return None
        specs = [
            {"model_id": m, "version": v,
             "weight_bytes": artifact_cache_bytes(self.registry, v)}
            for m, v in sorted(self.models.items())
        ]
        self.placement = self.placement_planner.plan(specs, instances)
        if self.router is not None:
            self.router.set_hosting(self.placement.assignments)
        self._event("placement_replan", reason=reason,
                    rev=self.placement.rev,
                    assignments=self.placement.assignments)
        log.info("%s placement re-planned (%s): rev %d", LOG_PREFIX,
                 reason, self.placement.rev)
        return self.placement

    def _reconcile_hosting(self, ctl_timeout_s: float = 300.0) -> dict:
        """Converge every live replica's ModelTable onto the current
        placement plan: host what the plan assigns it but it lacks,
        unhost what the plan moved away.  Per-replica errors are
        captured (one slow/broken replica must not abort fleet-wide
        convergence); a pinned model (canary in flight) stays put."""
        if self.placement is None:
            return {}
        report: dict = {}
        for h in list(self.router.live_replicas()):
            want = set(self.placement.models_for(h.instance))
            steps: list = []
            try:
                doc = self.router.control(h.instance, "models",
                                          timeout_s=ctl_timeout_s)
                table = (doc or {}).get("table") or {}
                have = {str(r["model_id"])
                        for r in table.get("models", [])}
            except (FleetError, FleetWorkerError) as e:
                report[h.instance] = {"error": str(e)}
                continue
            for model_id in sorted(want - have):
                try:
                    self.router.control(
                        h.instance, "host",
                        {"model_id": model_id,
                         "version": self.models[model_id]},
                        timeout_s=ctl_timeout_s)
                    steps.append({"host": model_id})
                except (FleetError, FleetWorkerError) as e:
                    steps.append({"host": model_id, "error": str(e)})
            for model_id in sorted(have - want):
                try:
                    self.router.control(h.instance, "unhost",
                                        {"model_id": model_id},
                                        timeout_s=ctl_timeout_s)
                    steps.append({"unhost": model_id})
                except (FleetError, FleetWorkerError) as e:
                    steps.append({"unhost": model_id, "error": str(e)})
            report[h.instance] = {"steps": steps}
        self.router.set_hosting(self.placement.assignments)
        if any(r for r in report.values() if r.get("steps")):
            self._event("hosting_reconciled", report=report)
        return report

    def model_hosts(self, model_id: str) -> list[str]:
        """Live replica instances hosting ``model_id`` (the router's
        converged view, which follows the placement plan)."""
        return [inst for inst, models
                in self.router.hosting_map().items()
                if model_id in models]

    def _hosting_instances(self, model_id: str) -> list[str]:
        hosts = self.model_hosts(model_id)
        if not hosts:
            raise UnhostedModelError(
                f"no live replica hosts model {model_id!r} "
                f"(hosting: {self.router.hosting_map()})")
        return hosts

    def host_model(self, model_id: str, version: str,
                   ctl_timeout_s: float = 300.0) -> dict:
        """Add (or hot-swap) one hosted model fleet-wide: record it in
        the model map, re-plan placement, and converge the replicas."""
        self.models[str(model_id)] = str(version)
        self._replan_placement(reason=f"host:{model_id}")
        report = self._reconcile_hosting(ctl_timeout_s=ctl_timeout_s)
        self._write_status()
        return report

    def unhost_model(self, model_id: str,
                     ctl_timeout_s: float = 120.0) -> dict:
        """Retire one hosted model fleet-wide."""
        self.models.pop(str(model_id), None)
        self.model_canaries.pop(str(model_id), None)
        self._replan_placement(reason=f"unhost:{model_id}")
        report = self._reconcile_hosting(ctl_timeout_s=ctl_timeout_s)
        self._write_status()
        return report

    # -- per-model canary lifecycle (ISSUE 20) ------------------------------
    def start_model_canary(self, model_id: str, version: str,
                           fraction: float = 0.05,
                           shadow: bool = False,
                           ctl_timeout_s: float = 300.0) -> dict:
        """Bring ``version`` up as ``model_id``'s canary on every
        replica hosting it — each hosted model's canary lifecycle is
        independent: two models can canary (and one promote while the
        other rolls back) concurrently."""
        model_id = str(model_id)
        out: dict = {}
        errors: dict = {}
        for inst in self._hosting_instances(model_id):
            try:
                out[inst] = self.router.control(
                    inst, "canary",
                    {"model_id": model_id, "version": version,
                     "fraction": fraction, "shadow": shadow},
                    timeout_s=ctl_timeout_s)
            except (FleetError, FleetWorkerError) as e:
                errors[inst] = str(e)
                out[inst] = {"error": str(e)}
        if errors and len(errors) == len(out):
            raise FleetError(
                f"canary {version} for model {model_id!r} failed on "
                f"every hosting replica: {errors}")
        self.model_canaries[model_id] = str(version)
        self._event("model_canary_start", model_id=model_id,
                    version=version, fraction=fraction, shadow=shadow,
                    replicas=sorted(set(out) - set(errors)),
                    errors=errors or None)
        return out

    def _model_ctl(self, model_id: str, cmd: str,
                   args: Optional[dict] = None,
                   ctl_timeout_s: float = 120.0) -> dict:
        out: dict = {}
        for inst in self._hosting_instances(model_id):
            try:
                out[inst] = self.router.control(
                    inst, cmd, dict(args or {}, model_id=model_id),
                    timeout_s=ctl_timeout_s)
            except (FleetError, FleetWorkerError) as e:
                out[inst] = {"error": str(e)}
        return out

    def promote_model_canary(self, model_id: str) -> dict:
        model_id = str(model_id)
        out = self._model_ctl(model_id, "promote_canary")
        version = self.model_canaries.pop(model_id, None)
        if version is not None:
            self.models[model_id] = version
        self._event("model_canary_promote", model_id=model_id,
                    version=version, replicas=sorted(out))
        self._write_status()
        return out

    def rollback_model_canary(self, model_id: str,
                              decision: Optional[RollbackDecision]
                              = None,
                              reason: str = "fleet-policy") -> dict:
        model_id = str(model_id)
        out = self._model_ctl(
            model_id, "rollback",
            {"reason": reason if decision is None else "policy"})
        version = self.model_canaries.pop(model_id, None)
        self._event(
            "model_canary_rollback", model_id=model_id,
            version=version,
            reason=reason if decision is None else "policy",
            reasons=[dict(r) for r in decision.reasons] if decision
            else [],
            replicas=sorted(out))
        self._write_status()
        log.warning("%s model %s canary %s ROLLED BACK across %d "
                    "replicas", LOG_PREFIX, model_id, version, len(out))
        return out

    def release_model_canary(self, model_id: str,
                             reason: str = "undecided") -> dict:
        model_id = str(model_id)
        out = self._model_ctl(model_id, "release_canary",
                              {"reason": reason})
        version = self.model_canaries.pop(model_id, None)
        self._event("model_canary_release", model_id=model_id,
                    version=version, reason=reason,
                    replicas=sorted(out))
        self._write_status()
        return out

    def check_model_canary(self, model_id: str
                           ) -> Optional[RollbackDecision]:
        """Evaluate the rollback policy against ``model_id``'s own
        merged stable/canary telemetry split; a breach rolls back ONLY
        this model's canary — the other hosted models' lifecycles are
        untouched."""
        model_id = str(model_id)
        if model_id not in self.model_canaries:
            return None
        stable_snaps, canary_snaps = self._arm_snapshots(
            model_id=model_id,
            canary_version=self.model_canaries[model_id])
        decision = self.policy.evaluate(
            merge_serving_snapshots(stable_snaps),
            merge_serving_snapshots(canary_snaps),
        )
        if decision.rollback:
            self.rollback_model_canary(model_id, decision=decision)
        return decision

    # -- rolling deploy -----------------------------------------------------
    def rolling_deploy(self, version: str,
                       drain_timeout_s: float = 60.0,
                       ctl_timeout_s: float = 300.0) -> list[dict]:
        """Flip the whole fleet to ``version``, one replica at a time
        (module docstring).  Returns the per-replica step report; a
        replica that cannot drain or deploy raises with the fleet left
        in a loudly-reported mixed state (the registry already names
        the intended stable - retry completes the roll)."""
        if self.registry.get(version).stage != "stable":
            self.registry.promote(version, to="stable")
        self.version = version
        report = []
        for h in list(self.router.live_replicas()):
            step = {"instance": h.instance, "version": version}
            self.router.set_drained(h.instance, True)
            try:
                if not self.router.wait_drained(h.instance,
                                                drain_timeout_s):
                    raise FleetError(
                        f"replica {h.instance} did not drain within "
                        f"{drain_timeout_s}s")
                t0 = time.perf_counter()
                doc = self.router.control(
                    h.instance, "deploy", {"version": version},
                    timeout_s=ctl_timeout_s)
                step["generation"] = doc.get("generation")
                step["swap_s"] = round(time.perf_counter() - t0, 4)
            finally:
                self.router.set_drained(h.instance, False)
            report.append(step)
            self._event("rolling_deploy_step", **step)
        self._event("rolling_deploy_done", version=version,
                    replicas=len(report))
        self._write_status()
        log.info("%s rolling deploy of %s complete across %d replicas",
                 LOG_PREFIX, version, len(report))
        return report

    # -- fleet canary -------------------------------------------------------
    def start_canary(self, version: str, fraction: float = 0.05,
                     shadow: bool = False,
                     ctl_timeout_s: float = 300.0) -> dict:
        """Bring ``version`` up as the canary on every live replica at
        one deterministic hash fraction (the same record routes to the
        same arm on every replica - the PR-5 split, fleet-wide)."""
        out = self.router.broadcast(
            "canary",
            {"version": version, "fraction": fraction, "shadow": shadow},
            timeout_s=ctl_timeout_s)
        errors = {k: v for k, v in out.items()
                  if isinstance(v, dict) and v.get("error")}
        if len(errors) == len(out):
            raise FleetError(f"canary {version} failed on every "
                             f"replica: {errors}")
        self.canary_version = version
        self._event("fleet_canary_start", version=version,
                    fraction=fraction, shadow=shadow,
                    replicas=sorted(set(out) - set(errors)),
                    errors=errors or None)
        return out

    def _arm_snapshots(self, model_id: Optional[str] = None,
                       canary_version: Optional[str] = None
                       ) -> tuple[list[dict], list[dict]]:
        """Split every live shard's serving views into (stable pool,
        canary pool) by model version.  With ``model_id`` only that
        hosted model's views are pooled (each ServingTelemetry carries
        its model_id label, ISSUE 20) and the split compares against
        ``canary_version`` instead of the fleet-wide canary slot."""
        from ..obs.fleet import serving_views

        against = (canary_version if model_id is not None
                   else self.canary_version)
        stable_snaps: list[dict] = []
        canary_snaps: list[dict] = []
        for doc in self.aggregator.shards():
            if str(doc.get("instance")) == "router":
                # the router's own shard (ship_router_obs) carries this
                # process's views, not replica serving telemetry -
                # folding it in would pollute the canary verdict pools
                continue
            for _key, snap in serving_views(doc.get("metrics", {})):
                if model_id is not None \
                        and snap.get("model_id") != model_id:
                    continue
                if snap.get("model_version") == against:
                    canary_snaps.append(snap)
                else:
                    stable_snaps.append(snap)
        return stable_snaps, canary_snaps

    def canary_telemetry(self, model_id: Optional[str] = None) -> dict:
        """The merged (stable, canary) serving telemetry split — the
        PUBLIC read seam for automated canary verdicts (ISSUE 16: the
        continuous trainer polls this for canary row counts instead of
        reaching into the aggregator's internals).  Same merge
        :meth:`check_canary` evaluates the rollback policy against.
        With ``model_id`` the split covers that hosted model alone
        (its own canary slot, ISSUE 20)."""
        stable_snaps, canary_snaps = self._arm_snapshots(
            model_id=None if model_id is None else str(model_id),
            canary_version=(None if model_id is None
                            else self.model_canaries.get(str(model_id))))
        return {
            "stable": merge_serving_snapshots(stable_snaps),
            "canary": merge_serving_snapshots(canary_snaps),
        }

    def check_canary(self) -> Optional[RollbackDecision]:
        """Evaluate the rollback policy (and the fleet SLO engine)
        against the MERGED per-replica telemetry; a breach rolls the
        canary back across the whole fleet."""
        if self.canary_version is None:
            return None
        stable_snaps, canary_snaps = self._arm_snapshots()
        decision = self.policy.evaluate(
            merge_serving_snapshots(stable_snaps),
            merge_serving_snapshots(canary_snaps),
        )
        if decision.rollback:
            self.rollback_canary(decision=decision)
        return decision

    def rollback_canary(self,
                        decision: Optional[RollbackDecision] = None,
                        reason: str = "fleet-policy") -> dict:
        """Demote the canary on EVERY replica (each worker's rollback
        is its own pointer flip; the first one also records the
        registry rollback, the rest observe it already rolled back)."""
        out = self.router.broadcast(
            "rollback",
            {"reason": reason if decision is None else "policy"})
        version, self.canary_version = self.canary_version, None
        self._event(
            "fleet_rollback", version=version,
            reason=reason if decision is None else "policy",
            reasons=[dict(r) for r in decision.reasons] if decision
            else [],
            replicas=sorted(out),
        )
        self._write_status()
        log.warning("%s fleet canary %s ROLLED BACK across %d "
                    "replicas", LOG_PREFIX, version, len(out))
        return out

    def release_canary(self, reason: str = "undecided") -> dict:
        """Release the canary slot on EVERY replica without a verdict
        (each worker's release is a pointer flip back to 100% stable;
        the first one also records the registry ``release_canary``, the
        rest observe the slot already freed) — the fleet-wide
        counterpart of ``DeploymentController.release_canary`` for a
        canary whose evaluation window expired undecided."""
        out = self.router.broadcast("release_canary",
                                    {"reason": reason})
        version, self.canary_version = self.canary_version, None
        self._event("fleet_canary_release", version=version,
                    reason=reason, replicas=sorted(out))
        self._write_status()
        log.info("%s fleet canary %s released undecided across %d "
                 "replicas: %s", LOG_PREFIX, version, len(out), reason)
        return out

    def promote_canary(self) -> dict:
        out = self.router.broadcast("promote_canary")
        version, self.canary_version = self.canary_version, None
        self.version = version
        self._event("fleet_canary_promote", version=version,
                    replicas=sorted(out))
        self._write_status()
        return out

    # -- status -------------------------------------------------------------
    def status(self, shards=None) -> dict:
        """The one consistent fleet document (per-replica generation,
        heartbeat age, in-flight, restart budget + router + registry
        pointers) - what ``tx fleet status`` renders and
        ``fleet_status.json`` persists.  ``shards`` reuses an
        already-read shard list (the monitor's once-per-tick read)."""
        shard_fleet = {}
        if shards is None:
            shards = self.aggregator.shards()
        for doc in shards:
            info = doc.get("fleet")
            if isinstance(info, dict):
                shard_fleet[str(doc.get("instance"))] = info
        replicas = {}
        router_snap = self.router.snapshot() if self.router else {}
        for rep in self._replicas.values():
            hb = staleness(rep.heartbeat_path)
            handle_snap = (router_snap.get("replicas") or {}).get(
                rep.instance, {})
            health = handle_snap.get("health") or {}
            replicas[rep.instance] = {
                "pid": rep.proc.pid if rep.proc else None,
                "running": (rep.proc is not None
                            and rep.proc.poll() is None),
                "restarts": rep.restarts,
                "gave_up": rep.gave_up,
                "heartbeat_age_s": (None if hb is None
                                    else round(hb, 3)),
                "generation": handle_snap.get("generation"),
                "version": handle_snap.get("version"),
                "in_flight": handle_snap.get("in_flight"),
                "in_flight_rows": handle_snap.get("in_flight_rows"),
                "drained": handle_snap.get("drained"),
                "alive": handle_snap.get("alive"),
                "rows_ok": handle_snap.get("rows_ok"),
                "transport": handle_snap.get("transport"),
                "health": health.get("state"),
                "consecutive_failures": health.get(
                    "consecutive_failures"),
                "last_rtt_ms": health.get("last_rtt_ms"),
                "ejections": health.get("ejections"),
                "readmissions": health.get("readmissions"),
                "wire": handle_snap.get("wire"),
                "worker": shard_fleet.get(rep.instance),
            }
        with self._events_lock:
            events = [dict(e) for e in self._events]
        out = {
            "t": time.time(),
            "registry_root": self.registry_root,
            "stable_version": self.registry.stable,
            "canary_version": self.canary_version,
            "replicas": replicas,
            "router": {k: v for k, v in router_snap.items()
                       if k != "replicas"},
            "shards": dict(self.aggregator.last_report),
            "events": events,
        }
        if self.models:
            out["models"] = self._model_status_rows(shard_fleet)
            out["model_canaries"] = dict(self.model_canaries)
            if self.placement is not None:
                out["placement"] = self.placement.to_json()
        if self.autoscaler is not None:
            try:
                out["autoscaler"] = self.autoscaler.snapshot()
            except Exception as e:  # noqa: BLE001 - status must publish
                out["autoscaler"] = {"error": str(e)}
        return out

    def _model_status_rows(self, shard_fleet: dict) -> dict:
        """Fold every replica's per-model table rows (shipped in its
        ``fleet`` shard info) into one fleet-wide per-model document:
        who hosts it, cache-resident vs evicted copies, per-model rows
        scored — the per-model rows ``tx fleet status`` renders."""
        rows_by_model = {}
        if self.router is not None:
            rows_by_model = self.router.snapshot().get(
                "rows_by_model", {})
        out: dict = {}
        for instance, info in sorted(shard_fleet.items()):
            for row in (info or {}).get("models") or []:
                model_id = str(row.get("model_id"))
                agg = out.setdefault(model_id, {
                    "version": row.get("version"),
                    "hosts": [],
                    "resident_on": [],
                    "evicted_on": [],
                    "rows_scored": 0,
                    "cold_hits": 0,
                    "rehydrations": 0,
                    "canary_version":
                        self.model_canaries.get(model_id),
                })
                agg["hosts"].append(instance)
                key = ("resident_on" if row.get("resident")
                       else "evicted_on")
                agg[key].append(instance)
                agg["rows_scored"] += int(row.get("rows_scored", 0)
                                          or 0)
                agg["cold_hits"] += int(row.get("cold_hits", 0) or 0)
                agg["rehydrations"] += int(row.get("rehydrations", 0)
                                           or 0)
        for model_id, agg in out.items():
            agg["rows_delivered"] = rows_by_model.get(model_id, 0)
        return out

    def _write_status(self, shards=None) -> None:
        """Atomically publish the status doc (tempfile + replace: a
        reader - worker deploy summaries, ``tx fleet status`` - sees a
        complete document or the previous one, never a torn one)."""
        path = os.path.join(self.control_dir, STATUS_FILENAME)
        try:
            doc = self.status(shards=shards)
            fd, tmp = tempfile.mkstemp(dir=self.control_dir,
                                       suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("fleet status publish failed: %s", e)

    # -- shutdown -----------------------------------------------------------
    def stop(self, timeout_s: float = 15.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout_s)
        if self._router_shipper is not None:
            self._router_shipper.stop()
            self._router_shipper = None
        if self.router is not None:
            try:
                self.router.broadcast("stop", timeout_s=5.0)
            except Exception as e:  # noqa: BLE001 - best-effort goodbye
                log.debug("fleet stop broadcast failed: %s", e)
            self.router.close()
        for rep in self._replicas.values():
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.terminate()
        deadline = time.monotonic() + timeout_s
        for rep in self._replicas.values():
            if rep.proc is None:
                continue
            while rep.proc.poll() is None \
                    and time.monotonic() < deadline:
                time.sleep(QUANTUM_S)
            if rep.proc.poll() is None:
                rep.proc.kill()
                try:
                    rep.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    log.warning("replica %s did not reap", rep.instance)
        self._write_status()

    def __enter__(self) -> "FleetController":
        return self.start() if not self.started else self

    def __exit__(self, *exc) -> None:
        self.stop()
