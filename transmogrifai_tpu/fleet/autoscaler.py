"""Elastic fleet autoscaling: the capacity control loop (ISSUE 19).

Closes the ROADMAP item-3 arc on top of the PR-14/17 fleet: a control
loop on :class:`~.controller.FleetController` that scales the replica
count against OFFERED load and survives every failure mode of doing
so.

* **evidence, not thresholds** - each window the loop reads three
  signals: the PR-9 :class:`~..obs.slo.SLOEngine` multi-window burn
  rates (the scale-up trigger), per-replica observed throughput/p99
  from the obs-plane fleet shards (via the router's handle ``obs``
  fold), and the PR-13 cost model's PREDICTED per-replica capacity.
  The cost model sizes a surge - ``ceil(demand / capacity)`` replicas,
  not "+1" - falling back to the observed-throughput waterfall when it
  cannot predict yet.
* **hysteresis so the fleet never flaps** - directions feed a
  :class:`ScaleGovernor` (the PR-16 ``RefitGovernor`` discipline:
  consecutive-window streaks per direction + a shared cooldown).  A
  flap-storm input - up/down alternating every window - resets the
  streaks forever and never triggers.
* **probe-gated grow, shed-never-hang shrink** - scale-up spawns
  replicas that warm from the PR-12 AOT executable cache and are
  admitted to routing only after a ``ping`` health probe (connected
  DRAINED until then); scale-down drains the victim via the router
  (stop dispatching, in-flight finishes) and the router's at-least-once
  failover owns anything a mid-drain SIGKILL strands.  Double-entry row
  conservation holds across every transition.
* **the envelope** - brownout (the router's quorum rule) remains the
  last line when scaling cannot keep up: at ``max_replicas`` the loop
  records the hold and defers to shedding.  A replica death is
  replacement CAPACITY accounting - the gave-up replica's missing
  throughput raises utilization and the next trigger sizes from
  demand - never a blind 1:1 restart.  And the loop's own death (fault
  point ``autoscaler.crash``, armed OUTSIDE the decision guard) kills
  only the control plane: replicas, router, and supervision keep
  serving, and a restarted autoscaler ADOPTS the live fleet with fresh
  streaks - it cannot justify a scale event except from new evidence.
* **live knob retune rides the loop** - when replica count holds but
  p99 burns, the loop A/B-probes micro-batch knobs on the live
  replicas (PR-13 :meth:`~..autotune.KnobTuner.ab_probe` over the
  worker ``retune`` verb - the ``MicroBatchScheduler.retune()``
  contract).  The baseline wins ties and margins: tuned knobs never
  regress past the hand-set default.

Every decision is a bounded, trace-event-recorded
:class:`AutoscaleDecision` carrying its evidence (burn rates, observed
vs predicted capacity, streak state), surfaced as ``tx_autoscaler_*``
metrics, ``fleet_status.json`` columns, and ``tx fleet status``.
"""
from __future__ import annotations

import contextvars
import logging
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from ..faults import injection as _faults
from ..obs.metrics import metrics_registry
from ..obs.trace import tracer

log = logging.getLogger("transmogrifai_tpu.fleet")

LOG_PREFIX = "op_fleet_metrics"

#: decision ring bound (the controller-events convention)
MAX_DECISIONS = 256

#: cold-start per-replica capacity guess (rows/s) used only until an
#: observation or cost-model prediction replaces it - matches the
#: router's ``_DEFAULT_SVC_S`` of 10 us/row
DEFAULT_CAPACITY_ROWS_S = 1e5


def _ctx_thread(target, name: str) -> threading.Thread:
    """A daemon thread running inside a COPY of the creating thread's
    contextvars, so every ``autoscaler.decision`` trace event stays
    under the one trace that started the loop (the router convention)."""
    ctx = contextvars.copy_context()
    return threading.Thread(target=lambda: ctx.run(target),
                            name=name, daemon=True)


class ScaleGovernor:
    """Hysteresis for capacity decisions - the PR-16 ``RefitGovernor``
    discipline with a streak per DIRECTION: a scale fires only after
    ``consecutive`` agreeing windows, any disagreeing window resets
    both streaks, and a trigger opens a shared ``cooldown`` during
    which further triggers are suppressed.  Alternating up/down input
    (a flap storm) therefore never fires."""

    def __init__(self, up_consecutive: int = 2,
                 down_consecutive: int = 4,
                 cooldown: int = 4) -> None:
        self.up_consecutive = max(1, int(up_consecutive))
        self.down_consecutive = max(1, int(down_consecutive))
        self.cooldown = max(0, int(cooldown))
        self.up_streak = 0
        self.down_streak = 0
        self.cooldown_left = 0
        self.windows = 0
        self.triggers = 0
        self.suppressed = 0

    def observe_window(self, direction: str) -> str:
        """Feed one window's direction (``up`` / ``down`` / ``hold``);
        returns ``clear`` (hold: streaks reset), ``over`` (streak
        building), ``suppressed`` (streak complete but cooling down),
        or ``trigger`` (act now; streaks reset, cooldown opens)."""
        if direction not in ("up", "down", "hold"):
            raise ValueError(f"unknown direction {direction!r}")
        self.windows += 1
        cooling = self.cooldown_left > 0
        if cooling:
            self.cooldown_left -= 1
        if direction == "hold":
            self.up_streak = 0
            self.down_streak = 0
            return "clear"
        if direction == "up":
            self.up_streak += 1
            self.down_streak = 0
            streak, need = self.up_streak, self.up_consecutive
        else:
            self.down_streak += 1
            self.up_streak = 0
            streak, need = self.down_streak, self.down_consecutive
        if streak < need:
            return "over"
        if cooling:
            self.suppressed += 1
            return "suppressed"
        self.triggers += 1
        self.up_streak = 0
        self.down_streak = 0
        self.cooldown_left = self.cooldown
        return "trigger"

    def snapshot(self) -> dict:
        return {
            "up_streak": self.up_streak,
            "down_streak": self.down_streak,
            "up_consecutive": self.up_consecutive,
            "down_consecutive": self.down_consecutive,
            "cooldown": self.cooldown,
            "cooldown_left": self.cooldown_left,
            "windows": self.windows,
            "triggers": self.triggers,
            "suppressed": self.suppressed,
        }


@dataclass
class AutoscaleDecision:
    """One recorded control-loop decision WITH its evidence: what the
    loop saw (burn rates, observed vs predicted capacity, streaks),
    what it decided, and what actually happened."""

    action: str        # adopt | scale_up | scale_down | retune | hold
    outcome: str       # governor outcome or what happened (e.g. at_max)
    reason: str
    members_before: int
    members_after: int
    target: Optional[int]
    evidence: dict = field(default_factory=dict)
    t: float = field(default_factory=time.time)

    def to_json(self) -> dict:
        return {
            "action": self.action,
            "outcome": self.outcome,
            "reason": self.reason,
            "members_before": self.members_before,
            "members_after": self.members_after,
            "target": self.target,
            "evidence": dict(self.evidence),
            "t": self.t,
        }


class FleetAutoscaler:
    """The elastic capacity control loop over a live
    :class:`~.controller.FleetController` (module docstring).  Drives
    the fleet exclusively through PUBLIC controller/router seams
    (style-gated): ``add_replica`` / ``remove_replica`` /
    ``member_instances`` / ``slo_engine.observe`` / router snapshots.

    ``step()`` is the deterministic single-window decision function
    (unit-testable without a fleet); ``start()`` runs it on a bounded
    interval loop whose death - the ``autoscaler.crash`` fault point -
    never touches the data plane."""

    def __init__(
        self,
        controller,
        min_replicas: int = 1,
        max_replicas: int = 8,
        interval_s: float = 0.5,
        up_consecutive: int = 2,
        down_consecutive: int = 4,
        cooldown_windows: int = 4,
        target_utilization: float = 0.7,
        idle_utilization: float = 0.3,
        ref_batch_rows: int = 512,
        probe_timeout_s: float = 60.0,
        drain_timeout_s: float = 30.0,
        retune_enabled: bool = True,
        retune_margin: float = 0.03,
        retune_probe_repeats: int = 2,
        retune_cooldown_windows: int = 8,
        probe_records: Optional[Sequence] = None,
        measure_fn: Optional[Callable[[dict], float]] = None,
    ) -> None:
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.controller = controller
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = max(0.05, float(interval_s))
        #: capacity is provisioned so steady demand lands at this
        #: utilization - the surge headroom knob
        self.target_utilization = min(max(float(target_utilization),
                                          0.05), 1.0)
        #: below this utilization (and only with the SLO plane quiet
        #: and the queue empty) the fleet is idle enough to shrink
        self.idle_utilization = min(max(float(idle_utilization), 0.0),
                                    self.target_utilization)
        self.ref_batch_rows = max(1, int(ref_batch_rows))
        self.probe_timeout_s = float(probe_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.retune_enabled = bool(retune_enabled)
        self.retune_margin = float(retune_margin)
        self.retune_probe_repeats = max(1, int(retune_probe_repeats))
        self.retune_cooldown_windows = max(0,
                                           int(retune_cooldown_windows))
        #: records scored through the router to measure a knob arm
        #: (the default measure seam); tests inject ``measure_fn``
        self.probe_records = (list(probe_records)
                              if probe_records is not None else None)
        self.measure_fn = measure_fn
        self.governor = ScaleGovernor(
            up_consecutive=up_consecutive,
            down_consecutive=down_consecutive,
            cooldown=cooldown_windows)
        self._lock = threading.Lock()
        self._decisions: list[AutoscaleDecision] = []
        self.decisions_total = 0
        self.steps = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.retunes = 0
        self.replicas_added = 0
        self.replicas_removed = 0
        self.errors = 0
        self.crashed = False
        self._retune_cooldown_left = 0
        self._prev_rows_ok: Optional[int] = None
        self._prev_t: Optional[float] = None
        self._served_ewma: Optional[float] = None
        self._last_capacity: dict = {}
        self._last_utilization: Optional[float] = None
        self._last_demand: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.started = False

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FleetAutoscaler":
        """Adopt the live fleet and start the loop.  Adoption is a
        recorded decision with only FRESH evidence: streaks start at
        zero, so a restarted autoscaler cannot justify a scale event
        from anything but new windows - the crash-recovery rule."""
        if self.started:
            return self
        self.controller.autoscaler = self
        metrics_registry().register_view("autoscaler", self)
        members = self.controller.member_instances()
        self._record(AutoscaleDecision(
            action="adopt", outcome="adopted",
            reason="adopted live fleet; any scale event requires "
                   "fresh consecutive-window evidence",
            members_before=len(members), members_after=len(members),
            target=None,
            evidence={"members": sorted(members),
                      "gave_up": sorted(
                          self.controller.gave_up_instances()),
                      "governor": self.governor.snapshot()},
        ))
        self._stop.clear()
        self._thread = _ctx_thread(self._loop, "tx-fleet-autoscaler")
        self._thread.start()
        self.started = True
        log.info("%s autoscaler up over %d member(s) "
                 "[%d..%d replicas, %.2fs windows]", LOG_PREFIX,
                 len(members), self.min_replicas, self.max_replicas,
                 self.interval_s)
        return self

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
        self.started = False

    def alive(self) -> bool:
        """True while the control loop thread runs; False after stop()
        OR after an ``autoscaler.crash`` killed the loop (the data
        plane keeps serving either way)."""
        return self._thread is not None and self._thread.is_alive()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            # the control-plane death drill: armed OUTSIDE the decision
            # guard, so the fault kills this loop (and only this loop)
            # - replicas, router, and supervision never notice
            try:
                _faults.inject("autoscaler.crash")
            except _faults.InjectedFault as e:
                self.crashed = True
                log.error("%s autoscaler control loop CRASHED (%s); "
                          "data plane unaffected", LOG_PREFIX, e)
                return
            try:
                self.step()
            except Exception:  # noqa: BLE001 - the loop must survive
                with self._lock:
                    self.errors += 1
                log.exception("autoscaler step error")

    def __enter__(self) -> "FleetAutoscaler":
        return self.start() if not self.started else self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the decision function ----------------------------------------------
    def step(self) -> Optional[AutoscaleDecision]:
        """One evidence->direction->governor->action window.  Pure
        control flow over public seams; deterministic given the
        evidence - the unit-testable heart of the loop."""
        router = self.controller.router
        if router is None:
            return None
        evidence = self._gather_evidence(router)
        direction, reason = self._direction(evidence)
        outcome = self.governor.observe_window(direction)
        evidence["governor"] = self.governor.snapshot()
        with self._lock:
            self.steps += 1
            if self._retune_cooldown_left > 0:
                self._retune_cooldown_left -= 1
        if outcome == "trigger" and direction == "up":
            return self._scale_up(evidence, reason)
        if outcome == "trigger" and direction == "down":
            return self._scale_down(evidence, reason)
        if self._should_retune(direction, outcome, evidence):
            return self._ab_retune(evidence, reason)
        if outcome in ("over", "suppressed"):
            # streak state IS evidence: record the hold so the trace
            # shows the loop seeing the burn and waiting out hysteresis
            n = evidence["members_n"]
            return self._record(AutoscaleDecision(
                action="hold", outcome=outcome, reason=reason,
                members_before=n, members_after=n, target=None,
                evidence=evidence))
        return None

    # -- evidence -----------------------------------------------------------
    def _gather_evidence(self, router) -> dict:
        now = time.monotonic()
        snap = router.snapshot()
        slo = self.controller.slo_engine.observe()
        burn = {}
        for name, obj in (slo.get("objectives") or {}).items():
            burn[name] = {
                "state": obj.get("state"),
                "burn_long": obj.get("burn_long"),
                "burn_short": obj.get("burn_short"),
                "burn_threshold": obj.get("burn_threshold"),
            }
        firing = sorted(str(f.get("name"))
                        for f in (slo.get("firing") or []))
        members = self.controller.member_instances()
        gave_up = self.controller.gave_up_instances()
        healthy = int(snap.get("healthy_replicas") or 0)
        queue_depth = int(snap.get("queue_depth") or 0)
        rows_ok = int(snap.get("rows_ok") or 0)
        requests_ok = int(snap.get("requests_ok") or 0)
        in_flight_rows = sum(
            int(r.get("in_flight_rows") or 0)
            for r in (snap.get("replicas") or {}).values())
        served = 0.0
        if (self._prev_t is not None and now > self._prev_t
                and self._prev_rows_ok is not None):
            served = max(0.0, (rows_ok - self._prev_rows_ok)
                         / (now - self._prev_t))
        self._prev_rows_ok, self._prev_t = rows_ok, now
        self._served_ewma = (served if self._served_ewma is None
                             else 0.5 * self._served_ewma
                             + 0.5 * served)
        rows_per_req = (rows_ok / requests_ok if requests_ok
                        else float(self.ref_batch_rows))
        backlog_rows = in_flight_rows + queue_depth * rows_per_req
        # demand = what we are serving + clearing the backlog within
        # one full up-hysteresis window
        window_s = self.interval_s * self.governor.up_consecutive
        demand = self._served_ewma + backlog_rows / window_s
        capacity = self._replica_capacity(router)
        serving_n = healthy if healthy > 0 else max(
            1, len(members) - len(gave_up))
        # heterogeneous capacity (ISSUE 20): under a multi-model
        # placement plan replicas have DIFFERENT predicted capacities
        # (each hosts a different model mix), so fleet capacity is the
        # SUM of the per-replica mix, not one-capacity * N
        mix = self._capacity_mix(members, gave_up, capacity)
        fleet_capacity = sum(mix.values())
        if mix and len(mix) != serving_n:
            fleet_capacity *= serving_n / len(mix)
        if fleet_capacity <= 0:
            fleet_capacity = (capacity["per_replica_rows_s"]
                              * serving_n)
        utilization = demand / max(fleet_capacity, 1e-9)
        self._last_capacity = capacity
        self._last_utilization = utilization
        self._last_demand = demand
        return {
            "slo_firing": firing,
            "burn": burn,
            "members": sorted(members),
            "members_n": len(members),
            "gave_up": sorted(gave_up),
            "healthy_replicas": healthy,
            "serving_n": serving_n,
            "queue_depth": queue_depth,
            "in_flight_rows": in_flight_rows,
            "served_rows_s": round(self._served_ewma, 1),
            "demand_rows_s": round(demand, 1),
            "capacity": capacity,
            "capacity_mix": {k: round(v, 1)
                             for k, v in sorted(mix.items())},
            "fleet_capacity_rows_s": round(fleet_capacity, 1),
            "utilization": round(utilization, 4),
        }

    def _capacity_mix(self, members: Sequence[str],
                      gave_up: Sequence[str],
                      capacity: dict) -> dict:
        """Per-replica capacity map for the serving members.  With a
        multi-model placement plan each replica's predicted capacity
        under its hosted mix shapes the ratios, anchored to the
        observed/predicted absolute level (``capacity`` waterfall);
        without one every replica gets the homogeneous estimate -
        byte-for-byte the old sizing."""
        base = float(capacity["per_replica_rows_s"])
        serving = [m for m in members if m not in set(gave_up)]
        plan = getattr(self.controller, "placement", None)
        if plan is None or not getattr(plan, "capacity_rows_s", None):
            return {m: base for m in serving}
        mean = plan.mean_capacity() or base
        factor = base / mean if mean > 0 else 1.0
        return {m: plan.replica_capacity(m, mean) * factor
                for m in serving}

    def _replica_capacity(self, router) -> dict:
        """Per-replica capacity estimate with its provenance: the
        cost model's prediction when it can predict (the PR-13 sizing
        input), else the live service-time EWMA, else shard-observed
        throughput, else the cold-start default.  Observed AND
        predicted both ride the evidence so every decision shows the
        observed-vs-predicted gap."""
        live = router.live_replicas()
        observed = [float(h.obs["batch_rows_per_s"]) for h in live
                    if h.obs.get("batch_rows_per_s")]
        p99s = [float(h.obs["p99_ms"]) for h in live
                if h.obs.get("p99_ms") is not None]
        ewma = [1.0 / h.svc_s_ewma for h in live
                if h.svc_s_ewma]
        predicted: list[float] = []
        cm = router.cost_model
        if cm is not None:
            from ..autotune import candidate_features

            for h in live:
                key = "serve.batch/" + h.instance
                try:
                    if not cm.can_predict(key):
                        continue
                    wall_ms = cm.predict_wall_ms(
                        key,
                        candidate_features(self.ref_batch_rows, 0))
                    if wall_ms is not None and wall_ms > 0:
                        predicted.append(
                            self.ref_batch_rows / (wall_ms / 1e3))
                except Exception as e:  # noqa: BLE001 - estimate only
                    log.debug("capacity prediction failed for %s: %s",
                              h.instance, e)
        for source, pool in (("cost_model", predicted),
                             ("observed_ewma", ewma),
                             ("observed_shards", observed)):
            if pool:
                per_replica = sum(pool) / len(pool)
                break
        else:
            source, per_replica = "default", DEFAULT_CAPACITY_ROWS_S
        return {
            "per_replica_rows_s": round(per_replica, 1),
            "source": source,
            "predicted_rows_s": (round(sum(predicted) / len(predicted),
                                       1) if predicted else None),
            "observed_peak_rows_s": (round(max(observed), 1)
                                     if observed else None),
            "observed_p99_ms": (round(max(p99s), 3) if p99s else None),
        }

    def _direction(self, evidence: dict) -> tuple[str, str]:
        util = evidence["utilization"]
        if evidence["slo_firing"] and (
                util > self.idle_utilization
                or evidence["queue_depth"] > 0):
            # a burn with NO offered load is stale evidence (p99 from a
            # past surge that no fresh traffic can clear): scaling up an
            # idle fleet fixes nothing, and treating it as a trigger
            # would deadlock scale-down forever
            return "up", ("slo_burn:"
                          + ",".join(evidence["slo_firing"]))
        if util >= 1.0:
            # demand exceeds effective capacity - includes the
            # replica-death case, where gave-up members' missing
            # throughput pushes utilization over the line
            # (replacement CAPACITY, not blind 1:1 restart)
            return "up", f"overload:utilization={util:.2f}"
        if (util <= self.idle_utilization
                and evidence["queue_depth"] == 0
                and evidence["serving_n"] > self.min_replicas):
            return "down", f"idle:utilization={util:.2f}"
        return "hold", f"steady:utilization={util:.2f}"

    # -- actions ------------------------------------------------------------
    def _sized_target(self, evidence: dict) -> int:
        """How many SERVING replicas the current demand needs at the
        target utilization - the cost-model sizing rule, never '+1'."""
        demand = evidence["demand_rows_s"]
        mix = evidence.get("capacity_mix") or {}
        if mix:
            # heterogeneous fleet: accumulate the per-replica capacity
            # mix (largest first - existing replicas keep serving)
            # until the demand fits at target utilization; replicas we
            # would ADD beyond the current mix are assumed mean-sized
            caps = sorted(mix.values(), reverse=True)
            mean = sum(caps) / len(caps)
            need = demand / max(self.target_utilization, 1e-9)
            total, n = 0.0, 0
            while total < need and n < self.max_replicas + len(caps):
                total += caps[n] if n < len(caps) else max(mean, 1e-9)
                n += 1
            return n
        capacity = evidence["capacity"]["per_replica_rows_s"]
        return int(math.ceil(
            demand / max(capacity * self.target_utilization, 1e-9)))

    def _scale_up(self, evidence: dict,
                  reason: str) -> AutoscaleDecision:
        members_before = evidence["members_n"]
        effective = max(1, members_before - len(evidence["gave_up"]))
        # a triggered surge always adds at least one replica even when
        # the demand estimate lags (SLO burn said capacity is short)
        target = max(self._sized_target(evidence), effective + 1)
        target = min(target, self.max_replicas)
        if effective >= self.max_replicas:
            return self._record(AutoscaleDecision(
                action="hold", outcome="at_max", reason=reason
                + f"; at max_replicas={self.max_replicas}, brownout "
                  "(quorum shed) is the last line",
                members_before=members_before,
                members_after=members_before,
                target=target, evidence=evidence))
        added: list[str] = []
        failures: list[str] = []
        for _ in range(target - effective):
            try:
                added.append(self.controller.add_replica(
                    probe_timeout_s=self.probe_timeout_s))
            except Exception as e:  # noqa: BLE001 - a failed
                # admission reaps its own replica; the loop records
                # the shortfall and retries on fresh evidence
                failures.append(f"{type(e).__name__}: {e}")
                log.warning("%s scale-up admission failed: %s",
                            LOG_PREFIX, e)
                break
        with self._lock:
            self.scale_ups += 1
            self.replicas_added += len(added)
        members_after = len(self.controller.member_instances())
        log.info("%s autoscaler SCALE UP %d -> %d (%s): added %s",
                 LOG_PREFIX, members_before, members_after, reason,
                 added or "none")
        return self._record(AutoscaleDecision(
            action="scale_up", outcome="trigger", reason=reason,
            members_before=members_before, members_after=members_after,
            target=target,
            evidence=dict(evidence, added=added,
                          admission_failures=failures or None)))

    def _scale_down(self, evidence: dict,
                    reason: str) -> AutoscaleDecision:
        members_before = evidence["members_n"]
        target = max(self.min_replicas, self._sized_target(evidence))
        victims_n = evidence["serving_n"] - target
        if victims_n <= 0:
            return self._record(AutoscaleDecision(
                action="hold", outcome="at_target", reason=reason,
                members_before=members_before,
                members_after=members_before, target=target,
                evidence=evidence))
        # retire the youngest members first: the longest-lived
        # replicas keep their warm caches and observation history
        victims = sorted(
            self.controller.member_instances(), reverse=True,
            key=lambda name: (len(name), name))[:victims_n]
        retired: list[dict] = []
        for victim in victims:
            try:
                retired.append(self.controller.remove_replica(
                    victim, drain_timeout_s=self.drain_timeout_s))
            except Exception as e:  # noqa: BLE001 - a victim that
                # cannot retire (already dead, race with supervision)
                # is recorded; the next window re-plans from evidence
                retired.append({"instance": victim, "error": str(e)})
                log.warning("%s scale-down of %s failed: %s",
                            LOG_PREFIX, victim, e)
        with self._lock:
            self.scale_downs += 1
            self.replicas_removed += sum(
                1 for r in retired if not r.get("error"))
        members_after = len(self.controller.member_instances())
        log.info("%s autoscaler SCALE DOWN %d -> %d (%s): retired %s",
                 LOG_PREFIX, members_before, members_after, reason,
                 [r.get("instance") for r in retired])
        return self._record(AutoscaleDecision(
            action="scale_down", outcome="trigger", reason=reason,
            members_before=members_before, members_after=members_after,
            target=target, evidence=dict(evidence, retired=retired)))

    # -- live knob retune (satellite) ---------------------------------------
    def _should_retune(self, direction: str, outcome: str,
                       evidence: dict) -> bool:
        """Retune rides the loop when replica count HOLDS but p99
        burns: latency pressure without a capacity trigger is a knob
        problem, not a fleet-size problem."""
        if not self.retune_enabled or direction != "up" \
                or outcome == "trigger":
            return False
        if self.measure_fn is None and self.probe_records is None:
            return False  # no probe seam wired: nothing to measure
        with self._lock:
            if self._retune_cooldown_left > 0:
                return False
        return any("latency" in name or "p99" in name
                   for name in evidence["slo_firing"])

    def _ab_retune(self, evidence: dict,
                   reason: str) -> AutoscaleDecision:
        from ..autotune import KnobTuner, microbatch_candidates

        router = self.controller.router
        baseline = {"max_batch_size": self.ref_batch_rows,
                    "max_wait_us": 0}
        tuner = KnobTuner(cost_model=router.cost_model,
                          margin=self.retune_margin,
                          repeats=self.retune_probe_repeats)
        decision = tuner.ab_probe(
            "serving.microbatch", baseline,
            microbatch_candidates(baseline,
                                  cost_model=router.cost_model),
            self._measure_knobs)
        # apply the winner fleet-wide; a baseline win RESTORES the
        # hand-set default - tuned knobs never regress past it
        source = "autotune" if decision.tuned else "hand_set"
        winner = (dict(decision.winner) if decision.tuned
                  else {"max_batch_size": 0, "max_wait_us": 0})
        applied = router.broadcast("retune",
                                   dict(winner, source=source))
        with self._lock:
            self.retunes += 1
            self._retune_cooldown_left = self.retune_cooldown_windows
        n = evidence["members_n"]
        log.info("%s autoscaler retune (%s): %s -> %s on %d "
                 "replica(s)", LOG_PREFIX, reason,
                 "tuned" if decision.tuned else "baseline held",
                 decision.winner, len(applied))
        return self._record(AutoscaleDecision(
            action="retune",
            outcome="tuned" if decision.tuned else "baseline_held",
            reason=reason, members_before=n, members_after=n,
            target=None,
            evidence=dict(evidence,
                          knob_decision=decision.to_json(),
                          applied_on=sorted(applied))))

    def _measure_knobs(self, knobs: dict) -> float:
        """Measure one knob arm: the injected ``measure_fn`` when the
        caller provided one (tests; custom drivers), else apply the
        knobs live via the worker ``retune`` verb and score the probe
        records through the router, returning rows/s."""
        if self.measure_fn is not None:
            return float(self.measure_fn(knobs))
        router = self.controller.router
        router.broadcast("retune", dict(knobs, source="probe"))
        records = self.probe_records or []
        if not records:
            raise RuntimeError("no probe records to measure with")
        t0 = time.perf_counter()
        res = router.score_batch(records,
                                 timeout_s=self.probe_timeout_s)
        wall = max(time.perf_counter() - t0, 1e-9)
        return len(res.results()) / wall

    # -- recording + reporting ----------------------------------------------
    def _record(self,
                decision: AutoscaleDecision) -> AutoscaleDecision:
        with self._lock:
            self._decisions.append(decision)
            if len(self._decisions) > MAX_DECISIONS:
                del self._decisions[0]
            self.decisions_total += 1
        tracer().event("autoscaler.decision",
                       action=decision.action,
                       outcome=decision.outcome,
                       reason=decision.reason,
                       members_before=decision.members_before,
                       members_after=decision.members_after,
                       target=decision.target,
                       evidence=dict(decision.evidence))
        return decision

    def decisions(self) -> list[AutoscaleDecision]:
        with self._lock:
            return list(self._decisions)

    def snapshot(self) -> dict:
        """The ``autoscaler`` metrics view (``tx_autoscaler_*``) and
        the ``fleet_status.json`` / ``tx fleet status`` column set."""
        with self._lock:
            last = (self._decisions[-1].to_json()
                    if self._decisions else None)
            out: dict[str, Any] = {
                "alive": self.alive(),
                "crashed": self.crashed,
                "steps": self.steps,
                "decisions_total": self.decisions_total,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "retunes": self.retunes,
                "replicas_added": self.replicas_added,
                "replicas_removed": self.replicas_removed,
                "errors": self.errors,
                "retune_cooldown_left": self._retune_cooldown_left,
            }
        out["min_replicas"] = self.min_replicas
        out["max_replicas"] = self.max_replicas
        out["members"] = len(self.controller.member_instances())
        out["governor"] = self.governor.snapshot()
        out["demand_rows_s"] = (round(self._last_demand, 1)
                                if self._last_demand is not None
                                else None)
        out["utilization"] = (round(self._last_utilization, 4)
                              if self._last_utilization is not None
                              else None)
        out["capacity"] = dict(self._last_capacity)
        out["last_decision"] = last
        return out
