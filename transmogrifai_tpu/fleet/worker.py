"""Replica serving worker: one supervised endpoint process of the fleet.

Each replica (ISSUE 14) is its own process owning one
:class:`~..registry.deployment.DeploymentController` over the shared
model registry:

* **warm-up is deserialize, not compile** - the replica loads the
  registry-stable artifact, and the PR-12 AOT executable cache inside
  it means an XLA-backed endpoint rehydrates compiled binaries instead
  of re-tracing (``fused_backend`` rides the CLI);
* **observability ships from birth** - the worker stamps its process
  instance (``--instance`` -> the Prometheus ``instance`` label and the
  obs shard filename) and runs a PR-9 :class:`~..obs.fleet.ObsShipper`
  into the fleet aggregation dir, with per-replica ``fleet`` info
  (version/generation, rows scored, in-flight) merged into every shard
  - one scrape of the dir covers the whole fleet;
* **lifecycle over the control channel** - the router sends
  ``deploy`` / ``canary`` / ``promote_canary`` / ``rollback`` /
  ``status`` / ``stop`` control messages; a deploy is the PR-5
  zero-drop hot-swap (build+warm off-pointer, one pointer flip), run
  while the router has the replica DRAINED so in-flight batches
  finished on the old generation - the per-replica step of the
  fleet-wide rolling deploy;
* **bounded everything** - the serve loop runs on the channel's 50 ms
  quanta (style-gated), beats the supervision heartbeat file between
  messages, and a router that goes away (EOF) ends the worker cleanly.

Fault point ``fleet.replica_kill`` (``inject_kill``) dies mid-serve
exactly like a SIGKILL - the router's at-least-once failover and the
controller's restart-with-backoff are drilled against it.

Run as ``python -m transmogrifai_tpu.fleet.worker --registry-root R
--workflow mod:fn --socket S --instance NAME [...]``.
"""
from __future__ import annotations

import argparse
import importlib
import logging
import os
import threading
import time
from typing import Mapping, Optional

from ..faults import injection as _faults
from ..obs import set_process_instance
from ..obs.fleet import ObsShipper
from ..obs.metrics import metrics_registry
from ..registry import DeploymentController, ModelRegistry, RollbackPolicy
from ..workflow.supervisor import beat
from . import channel as _ch
from .multimodel import ModelTable, UnknownModelError, parse_models_arg
from .channel import (
    OP_CONTROL,
    OP_CONTROL_RESULT,
    OP_ERROR,
    OP_RESULT,
    OP_SCORE,
    ChannelClosedError,
    ChannelTimeoutError,
    FleetChannel,
    decode_records,
    encode_results,
)

log = logging.getLogger("transmogrifai_tpu.fleet")

#: how long a freshly-started worker waits for its router to connect
#: before concluding it is orphaned (bounded in 50 ms accept quanta)
DEFAULT_ACCEPT_TIMEOUT_S = 300.0

#: heartbeat throttle: at most one beat per this interval
_BEAT_EVERY_S = 0.25

#: bound on any single response send: a router that stops DRAINING its
#: socket (frozen process, GIL stall) while staying connected must not
#: wedge the serve loop forever - the response is dropped (the router
#: retries or fails the request on its side) and the loop lives on
DEFAULT_SEND_TIMEOUT_S = 30.0


def load_workflow_factory(spec: str):
    """``module:function`` -> the zero-arg factory (the runner-CLI
    convention); the factory may return a workflow or a tuple whose
    first element is one."""
    mod_name, _, fn_name = spec.partition(":")
    if not mod_name or not fn_name:
        raise ValueError(
            f"workflow spec must be module:function, got {spec!r}")
    return getattr(importlib.import_module(mod_name), fn_name)


class ReplicaWorker:
    """One replica process: deployment controller + obs shipper behind
    a bounded fleet channel (module docstring)."""

    def __init__(
        self,
        registry_root: str,
        workflow_spec: str,
        socket_path: str,
        instance: str,
        version: Optional[str] = None,
        fleet_dir: Optional[str] = None,
        heartbeat_path: Optional[str] = None,
        fleet_status_path: Optional[str] = None,
        ship_interval_s: float = 0.5,
        accept_timeout_s: float = DEFAULT_ACCEPT_TIMEOUT_S,
        models: Optional[Mapping[str, str]] = None,
        model_cache_bytes: Optional[int] = None,
        max_resident_models: Optional[int] = None,
        evict_min_interval_s: Optional[float] = None,
        **endpoint_kw,
    ) -> None:
        self.registry_root = registry_root
        self.workflow_spec = workflow_spec
        self.socket_path = socket_path
        self.instance = instance
        self.version = version
        self.fleet_dir = fleet_dir
        self.heartbeat_path = heartbeat_path
        self.fleet_status_path = fleet_status_path
        self.ship_interval_s = float(ship_interval_s)
        self.accept_timeout_s = float(accept_timeout_s)
        self._endpoint_kw = dict(endpoint_kw)
        self._factory = load_workflow_factory(workflow_spec)
        # live-retunable serving knobs (ISSUE 19 satellite): the
        # worker-side mirror of MicroBatchScheduler.retune - the
        # ``retune`` control verb applies them between batches.
        # max_batch_size caps the score-chunk size (smaller chunks pad
        # to smaller XLA buckets); None = hand-set default (whole
        # batch, endpoint bucket chunking only)
        self.max_batch_size: Optional[int] = None
        self.max_wait_us: Optional[int] = None
        self.knob_source = "hand_set"
        self._stopping = False
        self._in_flight_rows = 0
        self.rows_scored = 0
        self.batches = 0
        #: batches refused because the caller's wire deadline had
        #: already passed on arrival (deadline propagation, ISSUE 17)
        self.deadline_dropped = 0
        #: wire-integrity counters folded across channel replacements
        self._wire: dict = {}
        self._chan: Optional[FleetChannel] = None
        self.started_at = time.monotonic()
        self.controller: Optional[DeploymentController] = None
        self.registry: Optional[ModelRegistry] = None
        self._shipper: Optional[ObsShipper] = None
        # multi-model hosting (ISSUE 20): N registry versions behind
        # this one serve lane, each with its own lifecycle, under a
        # weighted LRU over their AOT executables.  None until start()
        # (and stays None on a pure single-model replica with no
        # ``models`` map - zero new moving parts on the legacy path).
        self.initial_models = dict(models) if models else {}
        self.model_cache_bytes = model_cache_bytes
        self.max_resident_models = max_resident_models
        self.evict_min_interval_s = evict_min_interval_s
        self.models_table: Optional[ModelTable] = None

    def _fresh_workflow(self):
        built = self._factory()
        return built[0] if isinstance(built, tuple) else built

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ReplicaWorker":
        set_process_instance(self.instance)
        self.registry = ModelRegistry(self.registry_root, create=False)
        self.controller = DeploymentController(
            registry=self.registry, policy=RollbackPolicy(),
            **self._endpoint_kw)
        if self.fleet_status_path:
            # satellite: the deploy summary's `fleet` view reads the
            # controller-published one-document fleet status instead of
            # re-reading N obs shards
            self.controller.fleet_status_source = self.fleet_status_path
        version = self.version or self.registry.stable
        if version is None:
            raise RuntimeError(
                f"registry at {self.registry_root} has no stable version "
                "to serve")
        self.controller.deploy_version(version, self._fresh_workflow())
        if self.initial_models:
            self._init_model_table()
        metrics_registry().register_view("fleet_replica", self)
        if self.fleet_dir:
            self._shipper = ObsShipper(
                self.fleet_dir, interval_s=self.ship_interval_s,
                instance=self.instance,
                extra_fn=lambda: {"fleet": self.replica_info()},
            ).start()
        return self

    def _init_model_table(self) -> None:
        """Bring the ModelTable up (lazily on the first model-scoped
        control verb, eagerly when ``models`` was configured) and host
        the initial map."""
        if self.models_table is None:
            table_kw: dict = {}
            if self.evict_min_interval_s is not None:
                table_kw["evict_min_interval_s"] = float(
                    self.evict_min_interval_s)
            self.models_table = ModelTable(
                self.registry, self._fresh_workflow,
                capacity_bytes=self.model_cache_bytes,
                max_resident=self.max_resident_models,
                policy=RollbackPolicy(), **table_kw,
                **self._endpoint_kw)
        for model_id, version in self.initial_models.items():
            if not self.models_table.has(model_id):
                self.models_table.host(model_id, version)

    def replica_info(self) -> dict:
        gen = self.controller.stable_generation if self.controller \
            else None
        can = self.controller.canary_generation if self.controller \
            else None
        table = self.models_table
        return {
            "instance": self.instance,
            "pid": os.getpid(),
            "version": gen.version if gen else None,
            "generation": gen.generation if gen else None,
            "canary_version": can.version if can else None,
            "canary_generation": can.generation if can else None,
            "rows_scored": self.rows_scored,
            "batches": self.batches,
            "in_flight_rows": self._in_flight_rows,
            "deadline_dropped": self.deadline_dropped,
            "knobs": self.knobs(),
            "wire": self._wire_stats(),
            "uptime_s": round(time.monotonic() - self.started_at, 3),
            # multi-model hosting (ISSUE 20): per-model rows ride the
            # obs shard's `fleet` info, so `tx fleet status` and the
            # router's refresh_from_shards learn who hosts what without
            # a new wire verb
            "models": table.rows() if table is not None else [],
            "model_table": table.counters() if table is not None
            else None,
        }

    # -- live knobs ---------------------------------------------------------
    def knobs(self) -> dict:
        """Current live knobs + provenance (the
        ``MicroBatchScheduler.knobs()`` contract, worker-side)."""
        return {"max_batch_size": self.max_batch_size,
                "max_wait_us": self.max_wait_us,
                "source": self.knob_source}

    def retune(self, max_batch_size: Optional[int] = None,
               max_wait_us: Optional[int] = None,
               source: str = "autotune") -> dict:
        """Apply knob changes live, between batches (the
        ``MicroBatchScheduler.retune()`` contract: atomic attribute
        writes, returns what was applied).  ``max_batch_size <= 0``
        resets to the hand-set default (no chunk cap)."""
        applied: dict = {}
        if max_batch_size is not None:
            cap = int(max_batch_size)
            self.max_batch_size = cap if cap > 0 else None
            applied["max_batch_size"] = self.max_batch_size
        if max_wait_us is not None:
            # recorded for knob-contract parity; the single-threaded
            # serve loop has no micro-batch wait to apply it to
            self.max_wait_us = max(0, int(max_wait_us))
            applied["max_wait_us"] = self.max_wait_us
        if applied:
            self.knob_source = str(source)
        return applied

    def _wire_stats(self) -> dict:
        chan = self._chan
        live = chan.stats() if chan is not None else {}
        return {k: self._wire.get(k, 0) + live.get(k, 0)
                for k in set(self._wire) | set(live)}

    def snapshot(self) -> dict:
        """Metrics-view shape (kind ``fleet_replica``) so per-replica
        serving state rides the ordinary scrape."""
        return self.replica_info()

    def _beat(self, last: float) -> float:
        now = time.monotonic()
        if self.heartbeat_path and now - last >= _BEAT_EVERY_S:
            beat(self.heartbeat_path)
            return now
        return last

    def _send(self, chan: FleetChannel, op: int, rid: int, meta: dict,
              payload: bytes = b"") -> bool:
        """Every worker->router send is BOUNDED (the channel contract:
        a wedged peer must never block the serve loop forever).  A
        timed-out send drops the response - the router's failover/
        timeout machinery owns the request from there - and the worker
        keeps serving (and beating) instead of being stale-killed for
        the ROUTER's stall."""
        try:
            chan.send(op, rid, meta, payload,
                      timeout_s=DEFAULT_SEND_TIMEOUT_S)
            return True
        except ChannelTimeoutError as e:
            log.warning("replica %s: response %d dropped (router not "
                        "draining: %s)", self.instance, rid, e)
            return False

    # -- serving ------------------------------------------------------------
    def serve_forever(self) -> None:
        """Accept a router connection and serve it until EOF/protocol
        death or ``stop``, then accept again: after a network fault
        BOTH the router's readmission probe and the controller's
        restart path may reconnect, so losing one channel must not end
        the replica.  A worker nobody talks to within
        ``accept_timeout_s`` concludes it is orphaned and exits."""
        lsock = _ch.listen(self.socket_path)
        try:
            while not self._stopping:
                chan = self._accept_beating(lsock)
                if chan is None:
                    log.warning("no router connected to %s within "
                                "%.0fs; exiting", self.socket_path,
                                self.accept_timeout_s)
                    return
                self._chan = chan
                try:
                    self._serve_channel(chan, lsock)
                finally:
                    self._fold_wire(chan)
                    self._chan = None
                    chan.close()
        finally:
            try:
                lsock.close()
                os.unlink(self.socket_path)
            except OSError:
                pass  # socket file already gone (or TCP: never a file)
            if self._shipper is not None:
                self._shipper.stop()

    def _accept_beating(self,
                        lsock: "_ch.socket.socket"
                        ) -> Optional[FleetChannel]:
        """Bounded accept that keeps the supervision heartbeat alive:
        waiting for a router to (re)connect is a legitimate state, not
        staleness."""
        last_beat = 0.0
        deadline = time.monotonic() + self.accept_timeout_s
        while not self._stopping and time.monotonic() <= deadline:
            last_beat = self._beat(last_beat)
            chan = _ch.accept(lsock, timeout_s=_ch.QUANTUM_S)
            if chan is not None:
                return chan
        return None

    def _fold_wire(self, chan: FleetChannel) -> None:
        for k, v in chan.stats().items():
            self._wire[k] = self._wire.get(k, 0) + v

    def _serve_channel(self, chan: FleetChannel,
                       lsock: "_ch.socket.socket") -> None:
        """Single-threaded serve loop: decode -> score -> encode in
        order on the one scoring lane.  (A three-stage threaded
        pipeline was tried and measured SLOWER - the codec stages are
        GIL-bound, so splitting them onto threads only added switch
        overhead against the scoring thread's GIL hold.)

        On idle quanta the listener is polled: a NEWLY accepted
        connection replaces this channel (newest wins).  That resolves
        the probe-vs-restart reconnect race deterministically - the
        replica always serves whoever dialed last, and the older
        peer's next recv sees EOF and re-plans."""
        last_beat = 0.0
        while not self._stopping:
            last_beat = self._beat(last_beat)
            try:
                # idle_return: one 50 ms quantum with no traffic hands
                # control back so the loop can beat its heartbeat
                msg = chan.recv(idle_return=True)
            except ChannelClosedError:
                log.info("router disconnected; replica %s re-listening",
                         self.instance)
                return
            except _ch.ChannelProtocolError as e:
                log.warning("replica %s: protocol error on channel "
                            "(%s); dropping connection", self.instance,
                            e)
                return
            if msg is None:
                newer = _ch.accept(lsock, timeout_s=0.0)
                if newer is not None:
                    log.info("replica %s: newer connection accepted; "
                             "replacing current channel",
                             self.instance)
                    self._fold_wire(chan)
                    chan.close()
                    chan = newer
                    self._chan = newer
                continue
            op, rid, meta, payload = msg
            if op == _ch.OP_HELLO:
                self._send(chan, _ch.OP_HELLO, rid,
                           dict(chan.hello_reply_meta(),
                                instance=self.instance))
            elif op == OP_SCORE:
                self._handle_score(chan, rid, meta, payload)
            elif op == OP_CONTROL:
                self._handle_control(chan, rid, meta)

    def _handle_score(self, chan: FleetChannel, rid: int, meta: dict,
                      payload) -> None:
        # the slow-peer drill: scoring wall inflates exactly like a
        # replica thrashing under memory pressure - the router's
        # silence ceiling (response_timeout_s) is what must catch it
        _faults.inject_sleep("fleet.slow_peer")
        deadline_unix = meta.get("deadline_unix")
        if deadline_unix is not None and time.time() > float(deadline_unix):
            # the caller's deadline passed while this batch sat in a
            # queue (or a partitioned socket's kernel buffer): the
            # caller already gave up, so scoring it would be pure waste
            # - drop it and say so (kind="deadline" is shed accounting
            # on the router, not a worker failure)
            self.deadline_dropped += 1
            self._send(chan, OP_ERROR, rid,
                       {"error": "deadline already passed on arrival",
                        "kind": "deadline"})
            return
        try:
            records = decode_records(payload)
        except Exception as e:  # noqa: BLE001 - poison payload isolation
            self._send(chan, OP_ERROR, rid,
                       {"error": f"undecodable batch: "
                                 f"{type(e).__name__}: {e}"})
            return
        # the SIGKILL drill: dies here exactly like a preemption landing
        # mid-serve - the request is accepted but unanswered, and the
        # router must retry it on survivors
        _faults.inject_kill("fleet.replica_kill")
        # the bulk-job drill (ISSUE 18): a replica dying mid-shard while
        # a BulkScoringJob fans chunk batches across the fleet - the
        # router reassigns through ReplicaHealth, the job's journal
        # keeps the output shard exactly-once
        _faults.inject_kill("bulk.replica_die_midshard")
        self._in_flight_rows = len(records)
        # per-model dispatch (ISSUE 20): model_id rides the meta dict
        # (no wire-format change); absent -> the legacy single-model
        # lane, byte-for-byte today's path
        model_id = meta.get("model_id")
        try:
            results, info = self._score_records(records,
                                                model_id=model_id)
        except UnknownModelError as e:
            self._send(chan, OP_ERROR, rid,
                       {"error": str(e), "kind": "unknown_model",
                        "model_id": model_id})
            return
        except Exception as e:  # noqa: BLE001 - per-request isolation
            self._send(chan, OP_ERROR, rid,
                       {"error": f"{type(e).__name__}: {e}"})
            return
        finally:
            self._in_flight_rows = 0
        self.rows_scored += len(results)
        self.batches += 1
        out_meta = {
            "n_rows": len(results),
            "version": info.get("stable_version"),
            "generation": info.get("stable_generation"),
            "canary_rows": info.get("canary_rows", 0),
            "canary_version": info.get("canary_version"),
        }
        if model_id is not None:
            out_meta["model_id"] = info.get("model_id", model_id)
            if info.get("cold_hit"):
                out_meta["cold_hit"] = True
                out_meta["rehydrate_ms"] = info.get("rehydrate_ms")
        self._send(chan, OP_RESULT, rid, out_meta,
                   encode_results(results))

    def _score_records(self, records: list,
                       model_id: Optional[str] = None) -> tuple:
        """Score one wire batch, honoring the live ``max_batch_size``
        chunk cap: smaller chunks pad to smaller XLA buckets, which is
        exactly the knob the autoscaler's A/B retune probes.  Chunk
        canary_rows are summed; version/generation come from the last
        chunk (a deploy cannot land mid-batch - the replica is drained
        first).  With ``model_id`` the batch dispatches through the
        ModelTable (ISSUE 20) instead of the default controller."""
        if model_id is not None:
            if self.models_table is None:
                raise UnknownModelError(
                    f"model {model_id!r}: this replica hosts no "
                    "multi-model table")
            score = lambda recs: self.models_table.score(  # noqa: E731
                model_id, recs)
        else:
            score = self.controller.score_batch_with_info
        cap = self.max_batch_size
        if not cap or len(records) <= cap:
            return score(records)
        results: list = []
        canary_rows = 0
        cold_hit = False
        rehydrate_ms = None
        info: dict = {}
        for i in range(0, len(records), cap):
            chunk, info = score(records[i:i + cap])
            results.extend(chunk)
            canary_rows += int(info.get("canary_rows", 0) or 0)
            if info.get("cold_hit"):
                cold_hit = True
                rehydrate_ms = info.get("rehydrate_ms")
        info = dict(info, canary_rows=canary_rows)
        if cold_hit:
            info["cold_hit"] = True
            info["rehydrate_ms"] = rehydrate_ms
        return results, info

    # -- control ------------------------------------------------------------
    def _handle_control(self, chan: FleetChannel, rid: int,
                        meta: dict) -> None:
        cmd = str(meta.get("cmd", ""))
        # a deploy/canary control blocks this lane for a whole model
        # load + endpoint build + warm (budgeted up to the router's
        # ctl timeout - minutes), so a side thread keeps the
        # supervision heartbeat alive: the controller's staleness rule
        # must not kill a replica for doing exactly what it was asked.
        # SCORING deliberately gets no such keeper - a wedged endpoint
        # stopping the beat is the liveness signal working.
        stop_beats = threading.Event()
        keeper = None
        if self.heartbeat_path:
            def _keep_beating() -> None:
                while not stop_beats.wait(0.25):
                    beat(self.heartbeat_path)
            keeper = threading.Thread(target=_keep_beating,
                                      name="tx-fleet-ctl-beats",
                                      daemon=True)
            keeper.start()
        try:
            doc = self._control(cmd, meta)
        except Exception as e:  # noqa: BLE001 - operator path isolation
            self._send(chan, OP_ERROR, rid,
                       {"error": f"{type(e).__name__}: {e}",
                        "cmd": cmd})
            return
        finally:
            stop_beats.set()
            if keeper is not None:
                keeper.join(timeout=2.0)
        self._send(chan, OP_CONTROL_RESULT, rid, {"cmd": cmd},
                   encode_results([doc]))

    def _control(self, cmd: str, meta: dict) -> dict:
        ctl = self.controller
        # a model-scoped verb (meta carries model_id) routes through
        # the ModelTable's per-model controller; without one it is the
        # legacy single-model lane, unchanged
        model_id = meta.get("model_id")
        if model_id is not None:
            return self._control_model(cmd, str(model_id), meta)
        if cmd == "ping":
            return {"ok": True, "instance": self.instance,
                    "pid": os.getpid()}
        if cmd == "status":
            return dict(self.replica_info(),
                        events=len(ctl.events()),
                        telemetry=self._stable_telemetry())
        if cmd == "models":
            table = self.models_table
            return {"ok": True,
                    "table": table.snapshot() if table else None}
        if cmd == "deploy":
            gen = ctl.deploy_version(str(meta["version"]),
                                     self._fresh_workflow())
            self._ship_soon()
            return {"ok": True, "version": gen.version,
                    "generation": gen.generation}
        if cmd == "canary":
            gen = ctl.start_canary_version(
                str(meta["version"]), self._fresh_workflow(),
                fraction=meta.get("fraction"),
                shadow=meta.get("shadow"),
            )
            self._ship_soon()
            return {"ok": True, "version": gen.version,
                    "generation": gen.generation}
        if cmd == "promote_canary":
            gen = ctl.promote_canary()
            self._ship_soon()
            return {"ok": True, "version": gen.version,
                    "generation": gen.generation}
        if cmd == "rollback":
            event = ctl.rollback_canary(
                reason=str(meta.get("reason", "fleet")))
            self._ship_soon()
            return {"ok": True, "rolled_back": event is not None,
                    "event": event}
        if cmd == "release_canary":
            event = ctl.release_canary(
                reason=str(meta.get("reason", "fleet")))
            self._ship_soon()
            return {"ok": True, "released": event is not None,
                    "event": event}
        if cmd == "check_canary":
            decision = ctl.check_canary()
            return {"ok": True,
                    "decision": decision.to_json() if decision else None}
        if cmd == "retune":
            applied = self.retune(
                max_batch_size=meta.get("max_batch_size"),
                max_wait_us=meta.get("max_wait_us"),
                source=str(meta.get("source", "autotune")))
            self._ship_soon()
            return {"ok": True, "applied": applied,
                    "knobs": self.knobs()}
        if cmd == "stop":
            self._stopping = True
            return {"ok": True, "stopping": True}
        raise ValueError(f"unknown fleet control command {cmd!r}")

    def _control_model(self, cmd: str, model_id: str,
                       meta: dict) -> dict:
        """Model-scoped control verbs (ISSUE 20): each hosted model's
        deploy/canary lifecycle is independent, so every single-model
        verb has a per-model twin selected by ``meta["model_id"]``."""
        if cmd in ("host", "deploy", "canary") \
                and self.models_table is None:
            # first model-scoped mutation on a legacy replica brings
            # the table up lazily
            self._init_model_table()
        table = self.models_table
        if table is None:
            raise UnknownModelError(
                f"model {model_id!r}: this replica hosts no "
                "multi-model table")
        if cmd in ("host", "deploy"):
            gen = table.host(model_id, str(meta["version"]))
            self._ship_soon()
            return {"ok": True, "model_id": model_id,
                    "version": gen.version,
                    "generation": gen.generation}
        if cmd == "unhost":
            table.unhost(model_id)
            self._ship_soon()
            return {"ok": True, "model_id": model_id,
                    "unhosted": True}
        if cmd == "canary":
            gen = table.start_canary(
                model_id, str(meta["version"]),
                fraction=meta.get("fraction"),
                shadow=meta.get("shadow"))
            self._ship_soon()
            return {"ok": True, "model_id": model_id,
                    "version": gen.version,
                    "generation": gen.generation}
        if cmd == "promote_canary":
            gen = table.promote_canary(model_id)
            self._ship_soon()
            return {"ok": True, "model_id": model_id,
                    "version": gen.version,
                    "generation": gen.generation}
        if cmd == "rollback":
            event = table.rollback_canary(
                model_id, reason=str(meta.get("reason", "fleet")))
            self._ship_soon()
            return {"ok": True, "model_id": model_id,
                    "rolled_back": event is not None, "event": event}
        if cmd == "release_canary":
            event = table.release_canary(
                model_id, reason=str(meta.get("reason", "fleet")))
            self._ship_soon()
            return {"ok": True, "model_id": model_id,
                    "released": event is not None, "event": event}
        if cmd == "check_canary":
            decision = table.check_canary(model_id)
            return {"ok": True, "model_id": model_id,
                    "decision": decision.to_json() if decision
                    else None}
        if cmd == "status":
            rows = [r for r in table.rows()
                    if r["model_id"] == model_id]
            if not rows:
                raise UnknownModelError(
                    f"model {model_id!r} is not hosted here")
            return dict(rows[0], ok=True)
        raise ValueError(
            f"unknown model-scoped fleet control command {cmd!r}")

    def _stable_telemetry(self) -> Optional[dict]:
        gen = self.controller.stable_generation
        if gen is None:
            return None
        snap = gen.endpoint.telemetry.snapshot()
        return {
            "rows_scored": snap["rows_scored"],
            "rows_failed": snap["rows_failed"],
            "latency_ms": snap["latency_ms"],
            "breaker": snap["breaker"],
        }

    def _ship_soon(self) -> None:
        """Ship the plane right after a lifecycle change so the
        aggregation dir reflects the new generation within one beat."""
        if self._shipper is not None:
            self._shipper._ship_once()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="transmogrifai_tpu fleet replica worker")
    p.add_argument("--registry-root", required=True)
    p.add_argument("--workflow", required=True,
                   help="module:function workflow factory")
    p.add_argument("--socket", required=True,
                   help="AF_UNIX socket path to serve on")
    p.add_argument("--instance", required=True,
                   help="replica instance name (obs shard + labels)")
    p.add_argument("--version", default=None,
                   help="registry version to serve (default: stable)")
    p.add_argument("--fleet-dir", default=None,
                   help="obs aggregation dir to ship shards into")
    p.add_argument("--heartbeat", default=None,
                   help="supervision heartbeat file to beat")
    p.add_argument("--fleet-status-path", default=None,
                   help="controller-published fleet_status.json (the "
                        "deploy summary's one-document fleet view)")
    p.add_argument("--ship-interval-s", type=float, default=0.5)
    p.add_argument("--accept-timeout-s", type=float,
                   default=DEFAULT_ACCEPT_TIMEOUT_S)
    p.add_argument("--buckets", default=None,
                   help="comma-separated serving shape buckets")
    p.add_argument("--drift-policy", default="warn",
                   choices=("raise", "warn", "shed"))
    p.add_argument("--fused-backend", default=None,
                   choices=("auto", "numpy", "xla"))
    p.add_argument("--canary-fraction", type=float, default=0.05)
    p.add_argument("--models", default=None,
                   help="host N models: model_id=version[,model_id="
                        "version...] (ISSUE 20 multi-model serving)")
    p.add_argument("--model-cache-bytes", type=int, default=None,
                   help="weighted-LRU byte budget over hosted models' "
                        "AOT executables")
    p.add_argument("--max-resident-models", type=int, default=None,
                   help="cap on concurrently-resident hosted models")
    p.add_argument("--evict-min-interval-s", type=float, default=None,
                   help="minimum spacing between LRU evictions (thrash "
                        "rate bound)")
    args = p.parse_args(argv)
    endpoint_kw: dict = {
        "drift_policy": args.drift_policy,
        "canary_fraction": args.canary_fraction,
    }
    if args.buckets:
        endpoint_kw["batch_buckets"] = tuple(
            int(b) for b in args.buckets.split(","))
    if args.fused_backend:
        endpoint_kw["fused_backend"] = args.fused_backend
    worker = ReplicaWorker(
        registry_root=args.registry_root,
        workflow_spec=args.workflow,
        socket_path=args.socket,
        instance=args.instance,
        version=args.version,
        fleet_dir=args.fleet_dir,
        heartbeat_path=args.heartbeat,
        fleet_status_path=args.fleet_status_path,
        ship_interval_s=args.ship_interval_s,
        accept_timeout_s=args.accept_timeout_s,
        models=parse_models_arg(args.models) if args.models else None,
        model_cache_bytes=args.model_cache_bytes,
        max_resident_models=args.max_resident_models,
        evict_min_interval_s=args.evict_min_interval_s,
        **endpoint_kw,
    )
    worker.start()
    worker.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
