"""Multi-model serving: the fleet as a model-multiplexed platform.

ISSUE 20 (reference frame: TensorFlow Serving's multi-tenant model
server, arXiv 1605.08695 — model identity as a routing dimension,
loaded models as a managed cache, placement as a resource decision).
Two pieces live here, both pure composition over seams earlier PRs
built:

:class:`ModelTable` — one per replica worker.  Hosts N registry
versions behind the single serve lane, each with its OWN
:class:`~..registry.deployment.DeploymentController` (independent
stable/canary lifecycle, ``track_registry=False`` so N lifecycles never
race the registry's single stage slots) and its own ``ServingTelemetry``
carrying the ``model_id`` label.  Loaded models are a **weighted LRU
over the PR-12 AOT executables**: when resident bytes (weighted by each
artifact's serialized ``xla_cache`` size) exceed the cache budget — or
resident count exceeds ``max_resident`` — the least-recently-used cold
model's generations are dropped via ``DeploymentController.unload()``
(freeing its compiled programs), and the next hit on it REHYDRATES by
re-deploying from the registry: the artifact's AOT cache makes that a
~5–300 ms executable deserialize, never a full retrace on the serve
path.  Rehydrate walls and cold-hit latencies are sampled so the p99 a
cold model pays is measured, and evictions are RATE-BOUNDED
(``evict_min_interval_s``) so pathological pressure — drilled by the
``fleet.model_evict_storm`` fault point — degrades to denied-eviction
counters, not cache thrash.

:class:`PlacementPlanner` — fleet-side.  Decides which models co-reside
on which replica, balancing predicted per-model throughput (the PR-13
cost model when it can predict, observed rates when offered, a default
otherwise) against executable-cache pressure (first-fit-decreasing by
artifact bytes under each replica's cache budget).  The resulting
:class:`PlacementPlan` answers ``hosts(model_id)`` for the router's
per-model dispatch and ``replica_capacity(instance)`` for the
autoscaler's heterogeneous demand sizing, and is re-planned by the
fleet controller on membership changes (PR-19 autoscaler add/remove).

Style contract (tests/test_style.py): no unbounded waits (this module
takes no locks while scoring and owns no sockets/threads) and no
silent excepts.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

from ..faults import injection as _faults
from ..obs.metrics import percentiles
from ..registry.deployment import DeploymentController, Generation
from ..registry.store import ModelRegistry, RegistryError

log = logging.getLogger("transmogrifai_tpu.fleet")

LOG_PREFIX = "op_multimodel_metrics"

#: bounded latency-sample reservoirs (telemetry discipline)
_MAX_SAMPLES = 4096

#: default minimum spacing between evictions: the thrash rate bound the
#: ``fleet.model_evict_storm`` drill proves (an eviction implies a
#: future rehydrate deserialize; unbounded eviction churn would turn
#: cache pressure into a retrace-rate serve path)
DEFAULT_EVICT_MIN_INTERVAL_S = 0.25

#: planner fallback when neither the cost model nor observation can
#: rate a model (the PR-14 measured single-replica order of magnitude)
DEFAULT_MODEL_ROWS_PER_S = 1e5


class MultiModelError(RuntimeError):
    """Base for model-multiplexing failures."""


class UnknownModelError(MultiModelError):
    """The replica's ModelTable does not host this model_id."""


class UnhostedModelError(MultiModelError):
    """No replica in the fleet hosts this model_id (router-side)."""


def parse_models_arg(spec: str) -> Dict[str, str]:
    """``"a=v1,b=v2"`` -> ``{"a": "v1", "b": "v2"}`` — the shared
    ``--models`` CLI grammar (worker argv + controller worker_args must
    never drift).  Order-preserving; blanks rejected loudly."""
    out: Dict[str, str] = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        model_id, sep, version = part.partition("=")
        if not sep or not model_id.strip() or not version.strip():
            raise ValueError(
                f"bad --models entry {part!r}: expected model_id=version")
        out[model_id.strip()] = version.strip()
    if not out:
        raise ValueError(f"--models spec {spec!r} names no models")
    return out


def format_models_arg(models: Mapping[str, str]) -> str:
    """Inverse of :func:`parse_models_arg`."""
    return ",".join(f"{m}={v}" for m, v in models.items())


def artifact_cache_bytes(registry: ModelRegistry, version: str) -> int:
    """Byte weight of one version's serialized executables: the
    artifact's ``xla_cache``/``train_xla_cache`` dirs when present
    (what residency actually costs), else the whole artifact dir.
    Missing files weigh 0 — the weight only shapes eviction order."""
    try:
        entry = registry.get(version)
    except RegistryError:
        return 0
    root = os.path.join(registry.root, entry.path)
    totals = {"cache": 0, "all": 0}
    for dirpath, _dirnames, filenames in os.walk(root):
        in_cache = "xla_cache" in os.path.basename(dirpath)
        for fn in filenames:
            try:
                size = os.path.getsize(os.path.join(dirpath, fn))
            except OSError:
                continue  # racing a writer: weight is advisory
            totals["all"] += size
            if in_cache:
                totals["cache"] += size
    return totals["cache"] or totals["all"]


@dataclass
class HostedModel:
    """One hosted model's table row (controller + LRU bookkeeping)."""

    model_id: str
    version: str
    controller: DeploymentController
    weight_bytes: int = 0
    last_used: float = field(default_factory=time.monotonic)
    rows_scored: int = 0
    deploys: int = 0
    rehydrations: int = 0
    cold_hits: int = 0

    @property
    def resident(self) -> bool:
        return self.controller.loaded

    @property
    def pinned(self) -> bool:
        """An in-flight canary pins the model (unload would drop a live
        lifecycle mid-judgement)."""
        return self.controller.canary_generation is not None


class ModelTable:
    """N registry versions behind one replica serve lane, with a
    weighted LRU over their AOT executables.

    Thread contract: the table lock guards only the map + LRU
    bookkeeping; scoring resolves a controller under the lock and
    scores OUTSIDE it (the controller's own pointer discipline makes an
    eviction racing an in-flight batch safe — the batch finishes on the
    generation object it resolved; the next call rehydrates).
    """

    def __init__(
        self,
        registry: ModelRegistry,
        workflow_factory: Callable[[], Any],
        capacity_bytes: Optional[int] = None,
        max_resident: Optional[int] = None,
        evict_min_interval_s: float = DEFAULT_EVICT_MIN_INTERVAL_S,
        **controller_kw: Any,
    ) -> None:
        if max_resident is not None and int(max_resident) < 1:
            raise ValueError("max_resident must be >= 1")
        self.registry = registry
        self.workflow_factory = workflow_factory
        self.capacity_bytes = (
            None if capacity_bytes is None else int(capacity_bytes))
        self.max_resident = (
            None if max_resident is None else int(max_resident))
        self.evict_min_interval_s = float(evict_min_interval_s)
        self._controller_kw = dict(controller_kw)
        self._lock = threading.Lock()
        self._models: Dict[str, HostedModel] = {}
        self._last_evict_at = float("-inf")
        # -- table counters (obs + the eviction-storm drill) --
        self.evictions = 0
        self.evictions_denied = 0
        self.rehydrations = 0
        self.cold_hits = 0
        self.unknown_model_errors = 0
        self._rehydrate_ms: List[float] = []
        self._cold_hit_ms: List[float] = []

    # -- hosting ------------------------------------------------------------
    def _sample(self, bucket: List[float], value: float) -> None:
        bucket.append(float(value))
        if len(bucket) > _MAX_SAMPLES:
            del bucket[::2]

    def host(self, model_id: str, version: str,
             **endpoint_kw: Any) -> Generation:
        """Bring ``version`` up as hosted model ``model_id`` (or
        hot-swap an already-hosted model to a new version).  Builds and
        warms OFF the table lock, then publishes the row and applies
        cache pressure."""
        model_id = str(model_id)
        with self._lock:
            row = self._models.get(model_id)
        if row is None:
            controller = DeploymentController(
                registry=self.registry, model_id=model_id,
                track_registry=False, **self._controller_kw)
            row = HostedModel(model_id=model_id, version=version,
                              controller=controller)
        gen = row.controller.deploy_version(
            version, self.workflow_factory(), **endpoint_kw)
        row.version = version
        row.weight_bytes = artifact_cache_bytes(self.registry, version)
        row.deploys += 1
        row.last_used = time.monotonic()
        with self._lock:
            self._models[model_id] = row
        self._maybe_evict(protect=model_id)
        log.info("%s hosted model %s version %s (generation %d, "
                 "weight %d bytes)", LOG_PREFIX, model_id, version,
                 gen.generation, row.weight_bytes)
        return gen

    def unhost(self, model_id: str) -> None:
        """Drop a hosted model entirely (its row, not just residency).
        Refuses while its canary is in flight — finish or roll back the
        lifecycle first."""
        row = self._row(model_id)
        if row.pinned:
            raise MultiModelError(
                f"cannot unhost {model_id!r}: canary in flight")
        if row.resident:
            row.controller.unload()
        with self._lock:
            self._models.pop(model_id, None)

    def _row(self, model_id: str) -> HostedModel:
        with self._lock:
            row = self._models.get(str(model_id))
        if row is None:
            self.unknown_model_errors += 1
            raise UnknownModelError(
                f"model {model_id!r} is not hosted here "
                f"(hosting: {sorted(self._models)})")
        return row

    def has(self, model_id: str) -> bool:
        with self._lock:
            return str(model_id) in self._models

    def hosted_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def controller(self, model_id: str) -> DeploymentController:
        return self._row(model_id).controller

    # -- the weighted LRU ---------------------------------------------------
    def _resident_rows(self) -> List[HostedModel]:
        with self._lock:
            return [r for r in self._models.values() if r.resident]

    def _over_budget(self, resident: Sequence[HostedModel]) -> bool:
        if self.max_resident is not None and len(resident) > self.max_resident:
            return True
        if self.capacity_bytes is not None:
            if sum(r.weight_bytes for r in resident) > self.capacity_bytes:
                return True
        return False

    def _maybe_evict(self, protect: Optional[str] = None) -> int:
        """Evict least-recently-used resident models while over the
        cache budget (count or weighted bytes), never the ``protect``-ed
        (just-touched) model and never a pinned one.  The
        ``fleet.model_evict_storm`` fault point forces pressure — every
        armed fire demands an eviction — which is exactly what the rate
        bound must absorb: at most one eviction per
        ``evict_min_interval_s``; demands past the bound are counted
        (``evictions_denied``), not served."""
        evicted = 0
        while True:
            resident = self._resident_rows()
            forced = _faults.fires("fleet.model_evict_storm") is not None
            if not forced and not self._over_budget(resident):
                return evicted
            victims = sorted(
                (r for r in resident
                 if r.model_id != protect and not r.pinned),
                key=lambda r: r.last_used)
            if not victims:
                return evicted
            now = time.monotonic()
            if now - self._last_evict_at < self.evict_min_interval_s:
                self.evictions_denied += 1
                return evicted
            victim = victims[0]
            try:
                victim.controller.unload()
            except RegistryError as e:
                # raced a canary start: the pin won, pressure stands
                log.warning("%s eviction of %s refused: %s", LOG_PREFIX,
                            victim.model_id, e)
                self.evictions_denied += 1
                return evicted
            self._last_evict_at = now
            self.evictions += 1
            evicted += 1
            log.info("%s evicted model %s (%d bytes, idle %.3fs)",
                     LOG_PREFIX, victim.model_id, victim.weight_bytes,
                     now - victim.last_used)
            if forced and not self._over_budget(self._resident_rows()):
                return evicted

    def ensure_resident(self, model_id: str) -> tuple:
        """-> (row, rehydrate_ms | None): rehydrate an evicted model by
        re-deploying its remembered version — the PR-12 AOT cache in
        the artifact makes this an executable deserialize, measured
        here so the cold-hit p99 bound is provable."""
        row = self._row(model_id)
        if row.resident:
            return row, None
        t0 = time.perf_counter()
        row.controller.deploy_version(
            row.version, self.workflow_factory())
        rehydrate_ms = (time.perf_counter() - t0) * 1e3
        row.rehydrations += 1
        self.rehydrations += 1
        self._sample(self._rehydrate_ms, rehydrate_ms)
        self._maybe_evict(protect=row.model_id)
        log.info("%s rehydrated model %s version %s in %.1fms",
                 LOG_PREFIX, row.model_id, row.version, rehydrate_ms)
        return row, rehydrate_ms

    # -- scoring ------------------------------------------------------------
    def score(self, model_id: str,
              records: Sequence[Mapping[str, Any]]) -> tuple:
        """Score one batch on one hosted model; -> ``(results, info)``
        with the controller's info extended by model attribution and
        the cold-hit cost when this batch paid a rehydrate."""
        t0 = time.perf_counter()
        row, rehydrate_ms = self.ensure_resident(model_id)
        results, info = row.controller.score_batch_with_info(records)
        row.last_used = time.monotonic()
        row.rows_scored += len(records)
        # every score is a cache decision: touch the LRU, then apply
        # pressure (this is the point the evict-storm drill forces)
        self._maybe_evict(protect=row.model_id)
        info = dict(info, model_id=row.model_id)
        if rehydrate_ms is not None:
            row.cold_hits += 1
            self.cold_hits += 1
            cold_ms = (time.perf_counter() - t0) * 1e3
            self._sample(self._cold_hit_ms, cold_ms)
            info["cold_hit"] = True
            info["rehydrate_ms"] = round(rehydrate_ms, 3)
        return results, info

    # -- per-model lifecycle passthroughs ------------------------------------
    def start_canary(self, model_id: str, version: str,
                     **kw: Any) -> Generation:
        row, _ = self.ensure_resident(model_id)
        gen = row.controller.start_canary_version(
            version, self.workflow_factory(), **kw)
        row.last_used = time.monotonic()
        return gen

    def promote_canary(self, model_id: str) -> Generation:
        row = self._row(model_id)
        gen = row.controller.promote_canary()
        row.version = gen.version
        row.weight_bytes = artifact_cache_bytes(self.registry, gen.version)
        return gen

    def rollback_canary(self, model_id: str, reason: str = "manual"):
        return self._row(model_id).controller.rollback_canary(
            reason=reason)

    def release_canary(self, model_id: str, reason: str = "undecided"):
        return self._row(model_id).controller.release_canary(
            reason=reason)

    def check_canary(self, model_id: str):
        return self._row(model_id).controller.check_canary()

    # -- reporting ----------------------------------------------------------
    def rows(self) -> List[dict]:
        """Per-model status rows for ``fleet_status.json`` / the obs
        shard: hosted version, residency, LRU weight/recency, rows."""
        with self._lock:
            rows = list(self._models.values())
        now = time.monotonic()
        out = []
        for r in sorted(rows, key=lambda r: r.model_id):
            stable = r.controller.stable_generation
            canary = r.controller.canary_generation
            out.append({
                "model_id": r.model_id,
                "version": r.version,
                "resident": r.resident,
                "canary_version": canary.version if canary else None,
                "generation": stable.generation if stable else None,
                "weight_bytes": r.weight_bytes,
                "idle_s": round(now - r.last_used, 3),
                "rows_scored": r.rows_scored,
                "deploys": r.deploys,
                "rehydrations": r.rehydrations,
                "cold_hits": r.cold_hits,
            })
        return out

    def counters(self) -> dict:
        """The table-level counters alone (no per-model rows): the
        compact shape that rides ``replica_info`` next to ``models``."""
        snap = self.snapshot()
        snap.pop("models", None)
        return snap

    def snapshot(self) -> dict:
        rows = self.rows()
        return {
            "hosted": len(rows),
            "resident": sum(1 for r in rows if r["resident"]),
            "resident_bytes": sum(
                r["weight_bytes"] for r in rows if r["resident"]),
            "capacity_bytes": self.capacity_bytes,
            "max_resident": self.max_resident,
            "evictions": self.evictions,
            "evictions_denied": self.evictions_denied,
            "rehydrations": self.rehydrations,
            "cold_hits": self.cold_hits,
            "unknown_model_errors": self.unknown_model_errors,
            "rehydrate_ms": {
                k: round(v, 3) if v == v else None
                for k, v in percentiles(
                    self._rehydrate_ms, (50.0, 99.0)).items()
            },
            "cold_hit_ms": {
                k: round(v, 3) if v == v else None
                for k, v in percentiles(
                    self._cold_hit_ms, (50.0, 99.0)).items()
            },
            "models": rows,
        }


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------
@dataclass
class PlacementPlan:
    """Which models live where, plus each replica's predicted capacity
    under its hosted mix (the autoscaler's heterogeneous sizing input).
    """

    assignments: Dict[str, List[str]]       # instance -> [model_id]
    capacity_rows_s: Dict[str, float]       # instance -> predicted rows/s
    model_rows_s: Dict[str, float]          # model_id -> full-rate rows/s
    pressure_bytes: Dict[str, int] = field(default_factory=dict)
    rev: int = 0

    def hosts(self, model_id: str) -> List[str]:
        return [inst for inst, models in self.assignments.items()
                if model_id in models]

    def models_for(self, instance: str) -> List[str]:
        return list(self.assignments.get(instance, []))

    def replica_capacity(self, instance: str,
                         default: Optional[float] = None) -> Optional[float]:
        return self.capacity_rows_s.get(instance, default)

    def mean_capacity(self) -> Optional[float]:
        vals = list(self.capacity_rows_s.values())
        return sum(vals) / len(vals) if vals else None

    def to_json(self) -> dict:
        return {
            "rev": self.rev,
            "assignments": {k: list(v)
                            for k, v in sorted(self.assignments.items())},
            "capacity_rows_s": {k: round(v, 1) for k, v in
                                sorted(self.capacity_rows_s.items())},
            "model_rows_s": {k: round(v, 1) for k, v in
                             sorted(self.model_rows_s.items())},
            "pressure_bytes": dict(sorted(self.pressure_bytes.items())),
        }


class PlacementPlanner:
    """Cost-model-driven co-residency: first-fit-decreasing by artifact
    bytes under each replica's executable-cache budget, load-balanced by
    predicted per-model throughput, ``replication``-way redundant when
    the fleet is wide enough (a model must survive one replica death
    without an unhosted window)."""

    def __init__(self, cost_model=None,
                 cache_budget_bytes: Optional[int] = None,
                 replication: int = 2,
                 predict_rows: int = 512,
                 default_rows_per_s: float = DEFAULT_MODEL_ROWS_PER_S
                 ) -> None:
        if int(replication) < 1:
            raise ValueError("replication must be >= 1")
        self.cost_model = cost_model
        self.cache_budget_bytes = (
            None if cache_budget_bytes is None else int(cache_budget_bytes))
        self.replication = int(replication)
        self.predict_rows = int(predict_rows)
        self.default_rows_per_s = float(default_rows_per_s)
        self._rev = 0

    def _model_rate(self, spec: Mapping[str, Any]) -> float:
        """Predicted full-rate rows/s for one model: the spec's own
        observation wins, then the PR-13 cost model's per-model serve
        key, then the default."""
        observed = spec.get("rows_per_s")
        if observed:
            return float(observed)
        if self.cost_model is not None:
            from ..autotune.cost_model import predict_serve_rows_per_s

            predicted = predict_serve_rows_per_s(
                self.cost_model, str(spec["model_id"]),
                n_rows=self.predict_rows,
                n_features=int(spec.get("n_features", 0) or 0))
            if predicted:
                return float(predicted)
        return self.default_rows_per_s

    def plan(self, models: Sequence[Mapping[str, Any]],
             instances: Sequence[str]) -> PlacementPlan:
        """``models``: dicts with ``model_id`` (+ optional ``version``,
        ``weight_bytes``, ``rows_per_s``, ``n_features``);
        ``instances``: the live fleet membership.  Deterministic for a
        fixed input (re-planning on membership change must not shuffle
        placements gratuitously: ties break on sorted order)."""
        instances = [str(i) for i in instances]
        if not instances:
            raise ValueError("cannot place models on an empty fleet")
        rates = {str(m["model_id"]): self._model_rate(m) for m in models}
        weights = {str(m["model_id"]): int(m.get("weight_bytes", 0) or 0)
                   for m in models}
        # heaviest artifacts place first (first-fit-decreasing), rate
        # as the tiebreak so hot models spread before cold ones
        order = sorted(rates, key=lambda m: (-weights[m], -rates[m], m))
        assignments: Dict[str, List[str]] = {i: [] for i in instances}
        load: Dict[str, float] = {i: 0.0 for i in instances}
        bytes_used: Dict[str, int] = {i: 0 for i in instances}
        n_copies = min(self.replication, len(instances))
        for model_id in order:
            share = 1.0 / max(rates[model_id], 1e-9) / n_copies
            placed = 0
            # replicas with cache headroom first, least-loaded within
            # them; a fleet with no headroom anywhere still places
            # (over-budget residency is the ModelTable's LRU's problem,
            # an unhosted model would be an outage)
            for inst in sorted(
                    instances,
                    key=lambda i: (
                        self.cache_budget_bytes is not None
                        and bytes_used[i] + weights[model_id]
                        > self.cache_budget_bytes,
                        load[i], i)):
                if placed >= n_copies:
                    break
                assignments[inst].append(model_id)
                load[inst] += share
                bytes_used[inst] += weights[model_id]
                placed += 1
        # replica capacity under its mix: equal time-sharing across the
        # k hosted models is the harmonic blend k / sum(1/r_i) — one
        # slow model drags the replica's achievable aggregate, which is
        # exactly what ceil(demand/one-capacity) sizing gets wrong
        capacity: Dict[str, float] = {}
        for inst in instances:
            hosted = assignments[inst]
            if not hosted:
                capacity[inst] = self.default_rows_per_s
                continue
            inv = sum(1.0 / max(rates[m], 1e-9) for m in hosted)
            capacity[inst] = len(hosted) / inv
        self._rev += 1
        plan = PlacementPlan(
            assignments={i: sorted(a) for i, a in assignments.items()},
            capacity_rows_s=capacity,
            model_rows_s=dict(rates),
            pressure_bytes=dict(bytes_used),
            rev=self._rev,
        )
        log.info("%s placement rev %d: %s", LOG_PREFIX, plan.rev,
                 {i: len(a) for i, a in plan.assignments.items()})
        return plan
