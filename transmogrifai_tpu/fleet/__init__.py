"""Scale-out serving fleet: N replica workers behind a least-loaded
router, with fleet-wide rolling hot-swap and a full network-fault
envelope (ISSUE 14/17, ROADMAP items 1/3).

The "millions of users" tier over everything the repo already has: the
PR-1/6/12 compiled serving stack becomes N supervised worker processes
(:mod:`.worker`), a least-loaded front router dispatches over bounded
per-replica channels with at-least-once failover and health-gated
ejection/readmission (:mod:`.router` / :mod:`.channel`), and the PR-5
registry drives fleet lifecycle - rolling zero-drop hot-swap,
fleet-wide canary with rollback signals aggregated through the PR-9
obs plane and SLO engine (:mod:`.controller`).

    registry = ModelRegistry(root); registry.publish(model, stage="stable")
    with FleetController(root, "myapp:build_workflow", n_replicas=4) as fc:
        results = fc.router.score_batch(records)
        fc.rolling_deploy("v2")          # zero-drop, one replica at a time

The channel speaks AF_UNIX on-host (the fast path) or TCP cross-host
(``transport="tcp"`` / any ``host:port`` address), with per-frame
CRC32 integrity and an OP_HELLO handshake either way.

The ISSUE-19 :mod:`.autoscaler` closes the capacity loop: a
:class:`FleetAutoscaler` control loop grows and shrinks the replica
count elastically against offered load (SLO burn triggers, cost-model
sizing, hysteresis via :class:`ScaleGovernor`), with probe-gated
admission on scale-up and shed-never-hang drain on scale-down.

The ISSUE-20 :mod:`.multimodel` layer turns the fleet into a
model-multiplexed platform: each replica hosts N registry versions in
a :class:`ModelTable` (weighted LRU over the AOT executable cache -
evict cold, rehydrate by deserializing, never retrace), the router
dispatches per ``model_id`` with per-model quotas, a
:class:`PlacementPlanner` decides co-residency from the cost model,
and every hosted model keeps its own canary -> promote / rollback
lifecycle.  ``python bench.py --multimodel`` writes
MULTIMODEL_BENCH.json; the ``fleet.model_evict_storm`` fault point
proves eviction thrash stays rate-bounded.

Fault points: ``fleet.replica_kill`` (a worker dies mid-serve like a
SIGKILL), ``fleet.router_stall`` (the dispatcher wedges for a beat),
``autoscaler.crash`` (the capacity control loop dies; the data plane
keeps serving), and the ISSUE-17 socket seams - ``fleet.partition``
(both directions dark), ``fleet.half_open`` (accepts, never
responds), ``fleet.slow_peer``, ``channel.corrupt_frame``,
``fleet.reconnect_storm``.  ``tx fleet status|drain`` is the operator
surface; ``python bench.py --fleet`` writes FLEET_BENCH.json,
``--fleet-faults`` writes FLEET_FAULTS_BENCH.json, and
``--autoscale`` writes AUTOSCALE_BENCH.json.
"""
from .autoscaler import (
    AutoscaleDecision,
    FleetAutoscaler,
    ScaleGovernor,
)
from .channel import (
    ChannelClosedError,
    ChannelProtocolError,
    ChannelTimeoutError,
    FleetChannel,
    decode_records,
    decode_results,
    encode_records,
    encode_results,
    parse_address,
)
from .controller import (
    FleetController,
    merge_serving_snapshots,
)
from .multimodel import (
    ModelTable,
    MultiModelError,
    PlacementPlan,
    PlacementPlanner,
    UnhostedModelError,
    UnknownModelError,
    format_models_arg,
    parse_models_arg,
)
from .router import (
    BrownoutShedError,
    FleetBatch,
    FleetDecodeError,
    FleetError,
    FleetResult,
    FleetRouter,
    FleetWorkerError,
    ModelQuotaError,
    ReplicaHandle,
    ReplicaHealth,
)
from .worker import ReplicaWorker

__all__ = [
    "AutoscaleDecision",
    "BrownoutShedError",
    "ChannelClosedError",
    "ChannelProtocolError",
    "ChannelTimeoutError",
    "FleetBatch",
    "FleetChannel",
    "FleetController",
    "FleetDecodeError",
    "FleetAutoscaler",
    "FleetError",
    "FleetResult",
    "FleetRouter",
    "FleetWorkerError",
    "ModelQuotaError",
    "ModelTable",
    "MultiModelError",
    "PlacementPlan",
    "PlacementPlanner",
    "ReplicaHandle",
    "ReplicaHealth",
    "ReplicaWorker",
    "ScaleGovernor",
    "UnhostedModelError",
    "UnknownModelError",
    "decode_records",
    "decode_results",
    "encode_records",
    "encode_results",
    "format_models_arg",
    "merge_serving_snapshots",
    "parse_address",
    "parse_models_arg",
]
