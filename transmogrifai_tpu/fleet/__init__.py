"""Scale-out serving fleet: N replica workers behind a least-loaded
router, with fleet-wide rolling hot-swap (ISSUE 14, ROADMAP item 1).

The "millions of users" tier over everything the repo already has: the
PR-1/6/12 compiled serving stack becomes N supervised worker processes
(:mod:`.worker`), a least-loaded front router dispatches over bounded
per-replica channels with at-least-once failover (:mod:`.router` /
:mod:`.channel`), and the PR-5 registry drives fleet lifecycle -
rolling zero-drop hot-swap, fleet-wide canary with rollback signals
aggregated through the PR-9 obs plane and SLO engine
(:mod:`.controller`).

    registry = ModelRegistry(root); registry.publish(model, stage="stable")
    with FleetController(root, "myapp:build_workflow", n_replicas=4) as fc:
        results = fc.router.score_batch(records)
        fc.rolling_deploy("v2")          # zero-drop, one replica at a time

Fault points: ``fleet.replica_kill`` (a worker dies mid-serve like a
SIGKILL), ``fleet.router_stall`` (the dispatcher wedges for a beat).
``tx fleet status|drain`` is the operator surface; ``python bench.py
--fleet`` writes FLEET_BENCH.json.
"""
from .channel import (
    ChannelClosedError,
    ChannelTimeoutError,
    FleetChannel,
    decode_records,
    decode_results,
    encode_records,
    encode_results,
)
from .controller import (
    FleetController,
    merge_serving_snapshots,
)
from .router import (
    FleetBatch,
    FleetError,
    FleetResult,
    FleetRouter,
    FleetWorkerError,
    ReplicaHandle,
)
from .worker import ReplicaWorker

__all__ = [
    "ChannelClosedError",
    "ChannelTimeoutError",
    "FleetBatch",
    "FleetChannel",
    "FleetController",
    "FleetError",
    "FleetResult",
    "FleetRouter",
    "FleetWorkerError",
    "ReplicaHandle",
    "ReplicaWorker",
    "decode_records",
    "decode_results",
    "encode_records",
    "encode_results",
    "merge_serving_snapshots",
]
