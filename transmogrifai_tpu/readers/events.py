"""Event readers: aggregate / conditional / joined / streaming.

Counterparts of the reference reader stack (reference: readers/.../
DataReader.scala:173-345 - AggregateDataReader :202-266,
ConditionalDataReader :283-345; JoinedDataReader.scala:124-214;
StreamingReader.scala:54; factory DataReaders.scala:44-198): collapse
per-key event streams into one training row per key, with time-based
predictor/response separation.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

from ..features.aggregators import CutOffTime, Event, FeatureAggregator
from ..features.feature import Feature
from ..stages.feature_generator import FeatureGeneratorStage
from ..types.columns import column_from_list
from ..types.dataset import Dataset


class SimpleReader:
    """One record = one row (reference: DataReaders.Simple)."""

    def __init__(self, records: Iterable[dict], key_fn=None) -> None:
        self.records = list(records)
        self.key_fn = key_fn

    def generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        cols = {}
        for f in raw_features:
            gen = f.origin_stage
            assert isinstance(gen, FeatureGeneratorStage)
            cols[f.name] = gen.extract_column(self.records)
        return Dataset(cols)


class AggregateReader:
    """Group records by key and aggregate each feature's events relative to
    a cutoff time (reference: AggregateDataReader, DataReader.scala:202-266:
    predictors from events <= cutoff, responses from events > cutoff)."""

    def __init__(
        self,
        records: Iterable[dict],
        key_fn: Callable[[dict], Any],
        time_fn: Callable[[dict], float],
        cutoff: CutOffTime = CutOffTime(),
    ) -> None:
        self.records = list(records)
        self.key_fn = key_fn
        self.time_fn = time_fn
        self.cutoff = cutoff

    def _grouped(self) -> dict[Any, list[tuple[float, dict]]]:
        groups: dict[Any, list[tuple[float, dict]]] = {}
        for r in self.records:
            groups.setdefault(self.key_fn(r), []).append((self.time_fn(r), r))
        for events in groups.values():
            events.sort(key=lambda tr: tr[0])
        return groups

    def _cutoff_for(self, key: Any, events) -> CutOffTime:
        return self.cutoff

    def row_keys(self) -> list:
        """Group keys in output-row order (the 'key' column of the
        reference's aggregated frame, DataReader.scala:202) - what joins
        on the aggregation key align on."""
        return sorted(self._grouped(), key=str)

    def generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        groups = self._grouped()
        keys = sorted(groups, key=str)
        cols: dict[str, list] = {f.name: [] for f in raw_features}
        for f in raw_features:
            gen = f.origin_stage
            assert isinstance(gen, FeatureGeneratorStage)
            extract = gen.extract_fn or (lambda rec, _n=f.name: rec.get(_n))
            agg = FeatureAggregator(
                f.ftype,
                aggregator=gen.aggregator,
                is_response=f.is_response,
                window=gen.aggregate_window,
            )
            for key in keys:
                events = [
                    Event(ts, extract(rec)) for ts, rec in groups[key]
                ]
                cols[f.name].append(
                    agg.extract(events, self._cutoff_for(key, groups[key]))
                )
        return Dataset(
            {f.name: column_from_list(cols[f.name], f.ftype) for f in raw_features}
        )


class ConditionalReader(AggregateReader):
    """Per-key cutoff at the first (or last) record matching
    ``target_condition``; responses only within ``response_window`` after
    (reference: ConditionalDataReader, DataReader.scala:283-345).  Keys with
    no matching event are dropped."""

    def __init__(
        self,
        records: Iterable[dict],
        key_fn: Callable[[dict], Any],
        time_fn: Callable[[dict], float],
        target_condition: Callable[[dict], bool],
        response_window: Optional[float] = None,
        drop_if_no_condition: bool = True,
        use_first: bool = True,
    ) -> None:
        super().__init__(records, key_fn, time_fn)
        self.target_condition = target_condition
        self.response_window = response_window
        self.drop_if_no_condition = drop_if_no_condition
        self.use_first = use_first

    def _effective_groups(self):
        """(groups, cutoffs) after applying the target condition and the
        drop rule - shared by generate_dataset and row_keys."""
        groups = self._grouped()
        cutoffs: dict[Any, CutOffTime] = {}
        for key, events in groups.items():
            matches = [ts for ts, rec in events if self.target_condition(rec)]
            if matches:
                cutoffs[key] = CutOffTime(
                    matches[0] if self.use_first else matches[-1]
                )
        if self.drop_if_no_condition:
            groups = {k: v for k, v in groups.items() if k in cutoffs}
        return groups, cutoffs

    def row_keys(self) -> list:
        return sorted(self._effective_groups()[0], key=str)

    def generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        groups, cutoffs = self._effective_groups()
        self._per_key_cutoffs = cutoffs
        keys = sorted(groups, key=str)
        cols: dict[str, list] = {}
        for f in raw_features:
            gen = f.origin_stage
            assert isinstance(gen, FeatureGeneratorStage)
            extract = gen.extract_fn or (lambda rec, _n=f.name: rec.get(_n))
            window = gen.aggregate_window
            if f.is_response and window is None:
                window = self.response_window
            agg = FeatureAggregator(
                f.ftype, aggregator=gen.aggregator,
                is_response=f.is_response, window=window,
            )
            vals = []
            for key in keys:
                events = [Event(ts, extract(rec)) for ts, rec in groups[key]]
                vals.append(
                    agg.extract(events, cutoffs.get(key, CutOffTime()))
                )
            cols[f.name] = column_from_list(vals, f.ftype)
        return Dataset(cols)


class JoinedReader:
    """Join two readers' outputs on key columns (reference:
    JoinedDataReader.scala:124-214; JoinTypes inner/left/outer)."""

    def __init__(
        self,
        left,
        right,
        left_key: str,
        right_key: Optional[str] = None,
        join_type: str = "left",
    ) -> None:
        self.left = left
        self.right = right
        self.left_key = left_key
        self.right_key = right_key or left_key
        self.join_type = join_type

    def generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        import pandas as pd

        left_feats = [
            f for f in raw_features
            if f.name in getattr(self.left, "feature_names", set())
            or self._has_column(self.left, f)
        ]
        right_feats = [f for f in raw_features if f not in left_feats]
        lds = self.left.generate_dataset(left_feats, params)
        rds = self.right.generate_dataset(right_feats, params)
        ldf = pd.DataFrame(lds.to_pylists())
        rdf = pd.DataFrame(rds.to_pylists())
        # the join key must exist on both sides even when it is only declared
        # as a feature of one; pull it straight from the records
        for df, reader, key in (
            (ldf, self.left, self.left_key),
            (rdf, self.right, self.right_key),
        ):
            if key not in df.columns:
                # aggregate/conditional readers emit one row per GROUP -
                # their join key is the aggregation key, in row order
                if hasattr(reader, "row_keys"):
                    df[key] = reader.row_keys()
                    continue
                recs = getattr(reader, "records", None)
                if recs is None:
                    raise KeyError(f"join key {key!r} unavailable")
                df[key] = [r.get(key) for r in recs]
        how = {"inner": "inner", "left": "left", "outer": "outer"}[self.join_type]
        joined = ldf.merge(
            rdf, left_on=self.left_key, right_on=self.right_key, how=how
        )
        cols = {}
        for f in raw_features:
            vals = [
                None if (isinstance(v, float) and np.isnan(v)) else v
                for v in joined[f.name].tolist()
            ]
            cols[f.name] = column_from_list(vals, f.ftype)
        return Dataset(cols)

    @staticmethod
    def _has_column(reader, feature: Feature) -> bool:
        recs = getattr(reader, "records", None)
        if not recs:
            return False
        return any(feature.name in r for r in recs[:50])


class StreamingReader:
    """Micro-batch iterator (reference: StreamingReader.scala:54 /
    StreamingReaders.Simple): yields Datasets of up to batch_size rows,
    consumed by OpWorkflowRunner.streaming_score."""

    def __init__(self, record_stream: Iterable[dict], batch_size: int = 1000):
        self.record_stream = record_stream
        self.batch_size = batch_size

    def stream(self, raw_features: Sequence[Feature]) -> Iterator[Dataset]:
        batch: list[dict] = []
        for rec in self.record_stream:
            batch.append(rec)
            if len(batch) >= self.batch_size:
                yield SimpleReader(batch).generate_dataset(raw_features)
                batch = []
        if batch:
            yield SimpleReader(batch).generate_dataset(raw_features)
