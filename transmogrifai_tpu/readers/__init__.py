"""Reader tier: per-file readers (csv_reader/avro_reader/arrow_ingest),
the native chunked CSV scanner (fast_csv), and the async sharded input
pipeline (pipeline: shard → interleave → map → prefetch)."""
