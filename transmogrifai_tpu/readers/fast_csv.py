"""Chunked columnar CSV ingestion.

The reference streams arbitrarily large CSVs through Spark partitions
(reference: readers/src/main/scala/com/salesforce/op/readers/
DataReader.scala:173 generateDataFrame, DataReaders.scala:44-198); the
TPU-native counterpart streams fixed-size byte chunks through the C++ CSV
scanner (native/txkernels.cpp tx_csv_index/tx_csv_cells - quote-aware row
indexing + threaded cell extraction + inline numeric parsing) and
assembles columnar arrays with ZERO per-value python work for numeric
columns.  Chunk boundaries are aligned to newlines with even quote parity
so quoted embedded newlines never split a record.

Two consumers:

* :func:`read_csv_columnar` - file -> {name: Column} for Dataset ingest
  (the CSVReader fast path).
* :class:`DeviceCSVIngest` - file -> device-resident [n, d] design matrix
  with DOUBLE-BUFFERED host->device hand-off: the C++ parse of chunk i+1
  overlaps the device transfer of chunk i (the
  make_array_from_process_local_data pipelining analog, SURVEY §7).
"""
from __future__ import annotations

import queue
import threading
from typing import Mapping, Optional, Sequence, Type

import numpy as np

from ..obs import trace as _obs_trace
from ..types.columns import Column, NumericColumn, TextColumn
from ..types.feature_types import FeatureType, OPNumeric, Text
from ..utils import native

DEFAULT_CHUNK_BYTES = 64 << 20


def _count_quotes(buf: bytes) -> int:
    """Quote count for the chunk aligner: the native GIL-free counter
    when available (pipeline workers scan concurrently), bytes.count
    otherwise."""
    n = native.count_byte(buf, 0x22)
    return buf.count(b'"') if n is None else n


def _aligned_chunks(path: str, chunk_bytes: int):
    """Yield byte chunks ending on a record boundary: the cut point is a
    newline with an even number of quote bytes before it (cumulative from
    file start), so a '\\n' inside a quoted field never splits a row."""
    carry = b""
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                if carry:
                    yield carry
                return
            buf = carry + block
            # split at the last newline whose prefix has even quote parity;
            # scan newline candidates from the end (rarely more than one
            # iteration - pathological all-quoted tails degrade to carry)
            cut = -1
            search_end = len(buf)
            total_quotes = _count_quotes(buf)
            while search_end > 0:
                nl = buf.rfind(b"\n", 0, search_end)
                if nl < 0:
                    break
                quotes_after = buf.count(b'"', nl + 1)
                if (total_quotes - quotes_after) % 2 == 0:
                    cut = nl
                    break
                search_end = nl
            if cut < 0:
                carry = buf  # no safe boundary yet: grow the carry
                continue
            yield buf[: cut + 1]
            carry = buf[cut + 1 :]


def _decode_text_column(
    buf: bytes, begin: np.ndarray, end: np.ndarray
) -> np.ndarray:
    """Cell (begin, end) offsets -> object array of optional strings.
    Doubled quotes inside quoted cells are unescaped lazily (only when a
    quote byte is present in the slice)."""
    out = np.empty(len(begin), dtype=object)
    for i in range(len(begin)):
        b, e = begin[i], end[i]
        if e <= b:
            out[i] = None
            continue
        s = buf[b:e].decode("utf-8", errors="replace")
        if '"' in s:
            s = s.replace('""', '"')
        out[i] = s if s else None
    return out


def _parse_header(path: str) -> list[str]:
    with open(path, "rb") as f:
        line = f.readline()
    if line.startswith(b"\xef\xbb\xbf"):
        # Excel-style UTF-8 BOM must not leak into the first column name
        line = line[3:]
    if not line.strip():
        return []
    ncols = line.count(b",") + 1
    res = native.csv_scan(line, ncols, np.full(ncols, 2, np.uint8))
    if res is None:  # pure-python fallback
        import csv as _csv
        import io

        return next(_csv.reader(io.StringIO(line.decode("utf-8"))))
    nrows, _, _, cb, ce = res
    if nrows == 0:
        return []
    return [line[cb[c][0]:ce[c][0]].decode("utf-8").replace('""', '"')
            for c in range(cb.shape[0])]


def fast_path_available() -> bool:
    return native.csv_scan(b"x\n", 1, np.zeros(1, np.uint8)) is not None


def _retry_masked_unicode_cells(
    chunk: bytes, cb: np.ndarray, ce: np.ndarray,
    vals: np.ndarray, mask: np.ndarray,
) -> None:
    """Masked numeric cells re-tried through python float(): the C++
    parser rejects any non-ASCII byte, but float() accepts unicode
    decimal digits ('١٢٣' -> 123.0) and the python reader path uses
    float() - both native ingest routes must agree with it on every
    cell.  Mutates vals/mask in place; ASCII junk stays masked.  Callers
    gate on chunk.isascii() so pure-ASCII chunks never reach here."""
    from ..schema.quarantine import coerce_numeric

    for r in np.nonzero(~mask)[0]:
        cell = chunk[cb[r]:ce[r]]
        if not cell or cell.isascii():
            continue
        v = coerce_numeric(cell)
        if v is None:
            continue
        vals[r] = v
        mask[r] = True


class CsvChunk:
    """One decoded, keep-filtered chunk of a native CSV scan: the unit
    the sharded input pipeline (readers/pipeline.py) moves through its
    bounded queues.  ``numeric`` maps column name -> (values f64 [n],
    present-mask bool [n]); ``text`` maps name -> object array of
    optional strings.  ``row_offset`` is the chunk's first data-row
    index within its source file (header excluded)."""

    __slots__ = ("n_rows", "numeric", "text", "row_offset")

    def __init__(self, n_rows: int,
                 numeric: dict[str, tuple[np.ndarray, np.ndarray]],
                 text: dict[str, np.ndarray], row_offset: int) -> None:
        self.n_rows = n_rows
        self.numeric = numeric
        self.text = text
        self.row_offset = row_offset


def read_csv_columnar(
    path: str,
    schema: Mapping[str, Type[FeatureType]],
    headers: Optional[Sequence[str]] = None,
    has_header: bool = True,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    wanted: Optional[Sequence[str]] = None,
    errors: str = "coerce",
    quarantine=None,
    telemetry=None,
) -> dict[str, Column]:
    """One ``ingest.read`` trace span per native scan (obs/), wrapping
    the chunk iterator + columnar assembly."""
    with _obs_trace.span(
        "ingest.read", source=path, format="csv_native", errors=errors,
    ):
        names = [n for n in (wanted or list(schema)) if n in schema]
        chunks = iter_csv_chunks(
            path, schema, headers=headers, has_header=has_header,
            chunk_bytes=chunk_bytes, wanted=wanted, errors=errors,
            quarantine=quarantine, telemetry=telemetry,
        )
        return assemble_columns(names, schema, chunks)


def _concat_parts(parts: list, empty) -> np.ndarray:
    """Join chunk parts without the redundant single-part copy: chunk
    arrays are freshly allocated per scan (never reused buffers), so a
    lone part IS the column."""
    if not parts:
        return empty
    if len(parts) == 1:
        return parts[0]
    return np.concatenate(parts)


def assemble_columns(
    names: Sequence[str],
    schema: Mapping[str, Type[FeatureType]],
    chunks,
) -> dict[str, Column]:
    """Drain a :class:`CsvChunk` iterator into Dataset columns.  Shared
    by the serial fast path and the pipelined reader - one assembly
    implementation means serial and pipelined ingest cannot disagree
    about column semantics (NaN-as-missing, masked slots hold 0.0)."""
    num_parts: dict[str, list] = {}
    mask_parts: dict[str, list] = {}
    text_parts: dict[str, list] = {}
    for chunk in chunks:
        for n, (vals_c, mask_c) in chunk.numeric.items():
            num_parts.setdefault(n, []).append(vals_c)
            mask_parts.setdefault(n, []).append(mask_c)
        for n, txt in chunk.text.items():
            text_parts.setdefault(n, []).append(txt)
    out: dict[str, Column] = {}
    for n in names:
        t = schema[n]
        if issubclass(t, OPNumeric):
            vals = _concat_parts(num_parts.get(n, []), np.zeros(0))
            mask = _concat_parts(mask_parts.get(n, []),
                                 np.zeros(0, bool))
            # literal "nan" cells parse as NaN; the python path treats NaN
            # as missing (NumericColumn contract: masked slots hold 0.0)
            nan = np.isnan(vals)
            out[n] = NumericColumn(np.where(nan, 0.0, vals), mask & ~nan, t)
        elif issubclass(t, Text):
            vals = _concat_parts(text_parts.get(n, []),
                                 np.empty(0, object))
            out[n] = TextColumn(vals, t)
        else:
            raise TypeError(
                f"fast CSV path supports numeric/text columns; {n} is "
                f"{t.__name__}"
            )
    return out


def iter_csv_chunks(
    path: str,
    schema: Mapping[str, Type[FeatureType]],
    headers: Optional[Sequence[str]] = None,
    has_header: bool = True,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    wanted: Optional[Sequence[str]] = None,
    errors: str = "coerce",
    quarantine=None,
    telemetry=None,
):
    """Stream a CSV as decoded :class:`CsvChunk`s via the native scanner.

    THE chunk producer behind ``read_csv_columnar``, ``DeviceCSVIngest``,
    and the sharded input pipeline's CSV workers - one scan loop, one
    junk rule, one quarantine implementation for every consumer.

    ``schema`` types every column to materialize; ``wanted`` restricts
    which columns are materialized (all schema'd columns by default).
    Raises RuntimeError when the native path is unavailable - callers
    (CSVReader) fall back to the python reader.

    ``errors`` (schema/quarantine.py): ``"coerce"`` keeps junk numeric
    cells as missing values (legacy); ``"strict"`` raises
    MalformedRowError at the first non-empty numeric cell that fails to
    parse; ``"quarantine"`` drops such rows across ALL materialized
    columns, recording (row index, cell excerpt, reason).  The scanner
    has no per-row field counts, so ragged/truncated-row detection is
    the python reader's job (CSVReader routes checked modes there);
    this path owns type-flip detection at native speed.

    Copy discipline: chunk arrays are views into the freshly allocated
    per-scan buffers - the old per-column ``.copy()`` in the consumer
    loop is hoisted out entirely (assembly's final concatenate is the
    one copy), which closes most of the parse-vs-ingest throughput gap.
    """
    from ..schema.quarantine import (
        MalformedRowError,
        QuarantineBuffer,
        check_errors_mode,
        data_telemetry,
        excerpt_of,
    )
    from ..faults import injection as _faults

    check_errors_mode(errors)
    checked = errors != "coerce"
    if checked and quarantine is None:
        quarantine = QuarantineBuffer(source=path)
    if not fast_path_available():
        raise RuntimeError("native CSV kernels unavailable")
    header = list(headers) if headers else (
        _parse_header(path) if has_header else None
    )
    first = True
    col_idx: dict[str, int] = {}
    modes: Optional[np.ndarray] = None
    names: list[str] = []
    rows_seen = 0
    rows_kept = 0
    for chunk in _aligned_chunks(path, chunk_bytes):
        if first and chunk.startswith(b"\xef\xbb\xbf"):
            # strip the BOM on the data path too: headerless files never
            # call _parse_header, and the scanner would otherwise read
            # '﻿1' in the first cell (python fallback uses utf-8-sig)
            chunk = chunk[3:]
        if first and has_header:
            nl = chunk.find(b"\n")
            # nl == -1: header-only file with no trailing newline
            chunk = chunk[nl + 1 :] if nl >= 0 else b""
        if first:
            if header is None:
                ncols = chunk.split(b"\n", 1)[0].count(b",") + 1
                header = [f"c{i}" for i in range(ncols)]
            names = [n for n in (wanted or list(schema)) if n in schema]
            missing = [n for n in names if n not in header]
            if missing:
                raise KeyError(f"columns {missing} not in CSV {path}")
            col_idx = {n: header.index(n) for n in names}
            # per-column scan mode: 0 skip / 1 numeric / 2 text offsets -
            # unmaterialized columns cost only the delimiter walk
            modes = np.zeros(len(header), dtype=np.uint8)
            for n in names:
                modes[col_idx[n]] = (
                    1 if issubclass(schema[n], OPNumeric) else 2
                )
            first = False
        if not chunk:
            continue
        res = native.csv_scan(chunk, len(header), modes)
        if res is None:
            raise RuntimeError("native CSV kernels unavailable")
        nrows, num_vals, num_mask, cb, ce = res
        if nrows == 0:
            continue
        # pure-ASCII chunks (the hot path) skip the unicode retry check
        # entirely; isascii() short-circuits at the first high byte
        retry = not chunk.isascii()
        # copy discipline: when the numeric columns dominate the scan
        # matrix, the chunk columns stay views (the matrix IS the data;
        # the one copy is assembly's concatenate).  When they are a
        # minority — a wanted subset, or a text-heavy schema — copy the
        # wanted slices instead: a view would pin the full
        # [ncols, nrows] scan buffers until assembly drains the file
        n_numeric = sum(1 for n in names if modes[col_idx[n]] == 1)
        subset = (len(names) < len(header)
                  or n_numeric * 2 < len(header))
        chunk_num: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        chunk_text: dict[str, np.ndarray] = {}
        for n in names:
            c = col_idx[n]
            if modes[c] == 1:
                vals_c = num_vals[c].copy() if subset else num_vals[c]
                mask_c = num_mask[c].copy() if subset else num_mask[c]
                if retry:
                    # in-place mutation is safe either way: the scan
                    # buffers are fresh per call, never reused
                    _retry_masked_unicode_cells(
                        chunk, cb[c], ce[c], vals_c, mask_c
                    )
                chunk_num[n] = (vals_c, mask_c)
            else:
                chunk_text[n] = _decode_text_column(chunk, cb[c], ce[c])
        keep = None
        if checked:
            # a masked-but-NON-EMPTY cell is junk the parser refused: a
            # type flip.  Empty cells (ce <= cb) and literal-nan cells
            # (parsed, mask flows from the assembly NaN handling) are
            # legitimate missing values in every mode.
            bad = np.zeros(nrows, dtype=bool)
            bad_detail: dict[int, tuple[str, str, str]] = {}
            for n, (vals_c, mask_c) in chunk_num.items():
                c = col_idx[n]
                junk = ~mask_c & (ce[c] > cb[c])
                for r in np.nonzero(junk)[0]:
                    bad_detail.setdefault(int(r), (
                        "type_flip", n,
                        excerpt_of(chunk[cb[c][r]:ce[c][r]]),
                    ))
                bad |= junk
            # drill points: corrupt the chunk's first row so the drills
            # flow through the same quarantine/strict machinery
            if _faults.fires("reader.type_flip") is not None and nrows:
                bad_detail.setdefault(
                    0, ("type_flip", names[0], "<injected>"))
                bad[0] = True
            if _faults.fires("reader.malformed_row") is not None and nrows:
                bad_detail.setdefault(
                    0, ("malformed_row", None, "<injected>"))
                bad[0] = True
            if bad.any():
                if errors == "strict":
                    (telemetry or data_telemetry()).record_strict_error(
                        path
                    )
                    r0 = int(np.nonzero(bad)[0][0])
                    reason, col, cell = bad_detail[r0]
                    raise MalformedRowError(
                        path, rows_seen + r0, reason, col, cell
                    )
                for r in sorted(bad_detail):
                    reason, col, cell = bad_detail[r]
                    quarantine.add(rows_seen + r, reason, col, cell)
                keep = ~bad
        row_offset = rows_seen
        rows_seen += nrows
        out_rows = nrows
        if keep is not None:
            out_rows = int(keep.sum())
            chunk_num = {
                n: (v[keep], m[keep]) for n, (v, m) in chunk_num.items()
            }
            chunk_text = {n: t[keep] for n, t in chunk_text.items()}
        rows_kept += out_rows
        yield CsvChunk(out_rows, chunk_num, chunk_text, row_offset)
    if checked:
        (telemetry or data_telemetry()).record_read(
            path, rows_seen, rows_kept, quarantine
        )
    if first:
        # zero-byte file: the chunk loop never ran - surface the same
        # missing-column error the python path gives
        names = [n for n in (wanted or list(schema)) if n in schema]
        missing = [n for n in names if n not in (header or [])]
        if missing:
            raise KeyError(f"columns {missing} not in CSV {path}")


def chunk_to_block(
    chunk: CsvChunk, columns: Sequence[str]
) -> tuple[np.ndarray, np.ndarray]:
    """:class:`CsvChunk` -> ([rows, d] float32 design block, [rows, d]
    bool present-mask).  One strided cast per column straight into the
    final layout - the old double copy (fancy-index [d, rows] f64
    intermediate + ``ascontiguousarray`` transpose) is hoisted out of
    the consumer loop.  Missing slots are 0 with mask False and
    literal-NaN cells count as missing (the NumericColumn contract,
    device-side).  Shared by :class:`DeviceCSVIngest` and the sharded
    input pipeline's design-matrix consumers."""
    d = len(columns)
    block = np.empty((chunk.n_rows, d), dtype=np.float32)
    mask = np.empty((chunk.n_rows, d), dtype=bool)
    for j, name in enumerate(columns):
        vals, m = chunk.numeric[name]
        block[:, j] = vals
        mask[:, j] = m
    nan = np.isnan(block)  # literal "nan" cells -> missing
    if nan.any():
        block = np.where(nan, np.float32(0.0), block)
        mask = mask & ~nan
    return block, mask


def double_buffered_to_device(producer, n_cols: int) -> tuple:
    """Shared double-buffered host→device pump: ``producer(queue)`` runs in
    a background thread pushing (values_block [rows, d] float32, mask_block
    [rows, d] bool) tuples, then None; exceptions are forwarded.  The
    consumer issues async ``jax.device_put`` per block - the next parse
    overlaps the DMA in flight - and concatenates on device.  Returns
    (X_device [n, n_cols], mask_device, rows); empty input yields correct-
    width zero-row arrays."""
    import jax
    import jax.numpy as jnp

    q: queue.Queue = queue.Queue(maxsize=2)
    t = threading.Thread(target=producer, args=(q,), daemon=True)
    t.start()
    dev_blocks, dev_masks, total = [], [], 0
    while True:
        item = q.get()
        if item is None:
            break
        if isinstance(item, BaseException):
            raise item
        block, mask = item
        total += block.shape[0]
        dev_blocks.append(jax.device_put(block))
        dev_masks.append(jax.device_put(mask))
    t.join()
    if not dev_blocks:
        return (jnp.zeros((0, n_cols), jnp.float32),
                jnp.zeros((0, n_cols), bool), 0)
    X = jnp.concatenate(dev_blocks, axis=0)
    M = jnp.concatenate(dev_masks, axis=0)
    return X, M, total


class DeviceCSVIngest:
    """CSV -> device-resident [n, d] float32 design matrix with the parse
    of chunk i+1 overlapping the device transfer of chunk i.

    A background thread runs the C++ scanner over aligned byte chunks and
    feeds a bounded queue (depth 2 = classic double buffer); the consumer
    issues ``jax.device_put`` per chunk - JAX transfers are async, so the
    next parse starts while DMA is in flight - and concatenates on device.
    """

    def __init__(self, path: str, columns: Sequence[str],
                 schema: Mapping[str, Type[FeatureType]],
                 has_header: bool = True,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 errors: str = "coerce",
                 quarantine=None,
                 telemetry=None) -> None:
        from ..schema.quarantine import QuarantineBuffer, check_errors_mode

        self.path = path
        self.columns = list(columns)
        self.schema = dict(schema)
        self.has_header = has_header
        self.chunk_bytes = chunk_bytes
        self.errors = check_errors_mode(errors)
        if self.errors != "coerce" and quarantine is None:
            quarantine = QuarantineBuffer(source=path)
        self.quarantine = quarantine
        self.telemetry = telemetry

    def _parse_worker(self, q: queue.Queue) -> None:
        try:
            for chunk in iter_csv_chunks(
                self.path, self.schema, has_header=self.has_header,
                chunk_bytes=self.chunk_bytes, wanted=self.columns,
                errors=self.errors, quarantine=self.quarantine,
                telemetry=self.telemetry,
            ):
                q.put(chunk_to_block(chunk, self.columns))
            q.put(None)
        except BaseException as e:  # surface parse errors to the consumer
            q.put(e)

    def to_device(self):
        """Returns (X_device [n, d] float32, valid_mask_device [n, d]
        bool, rows).  Missing numeric cells are 0 with mask False (the
        NumericColumn contract, device-side)."""
        with _obs_trace.span(
            "ingest.device", source=self.path, format="csv_native",
        ):
            return double_buffered_to_device(
                self._parse_worker, len(self.columns)
            )
