"""Chunked columnar CSV ingestion.

The reference streams arbitrarily large CSVs through Spark partitions
(reference: readers/src/main/scala/com/salesforce/op/readers/
DataReader.scala:173 generateDataFrame, DataReaders.scala:44-198); the
TPU-native counterpart streams fixed-size byte chunks through the C++ CSV
scanner (native/txkernels.cpp tx_csv_index/tx_csv_cells - quote-aware row
indexing + threaded cell extraction + inline numeric parsing) and
assembles columnar arrays with ZERO per-value python work for numeric
columns.  Chunk boundaries are aligned to newlines with even quote parity
so quoted embedded newlines never split a record.

Two consumers:

* :func:`read_csv_columnar` - file -> {name: Column} for Dataset ingest
  (the CSVReader fast path).
* :class:`DeviceCSVIngest` - file -> device-resident [n, d] design matrix
  with DOUBLE-BUFFERED host->device hand-off: the C++ parse of chunk i+1
  overlaps the device transfer of chunk i (the
  make_array_from_process_local_data pipelining analog, SURVEY §7).
"""
from __future__ import annotations

import queue
import threading
from typing import Mapping, Optional, Sequence, Type

import numpy as np

from ..obs import trace as _obs_trace
from ..types.columns import Column, NumericColumn, TextColumn
from ..types.feature_types import FeatureType, OPNumeric, Text
from ..utils import native

DEFAULT_CHUNK_BYTES = 64 << 20


def _aligned_chunks(path: str, chunk_bytes: int):
    """Yield byte chunks ending on a record boundary: the cut point is a
    newline with an even number of quote bytes before it (cumulative from
    file start), so a '\\n' inside a quoted field never splits a row."""
    carry = b""
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                if carry:
                    yield carry
                return
            buf = carry + block
            # split at the last newline whose prefix has even quote parity;
            # scan newline candidates from the end (rarely more than one
            # iteration - pathological all-quoted tails degrade to carry)
            cut = -1
            search_end = len(buf)
            total_quotes = buf.count(b'"')
            while search_end > 0:
                nl = buf.rfind(b"\n", 0, search_end)
                if nl < 0:
                    break
                quotes_after = buf.count(b'"', nl + 1)
                if (total_quotes - quotes_after) % 2 == 0:
                    cut = nl
                    break
                search_end = nl
            if cut < 0:
                carry = buf  # no safe boundary yet: grow the carry
                continue
            yield buf[: cut + 1]
            carry = buf[cut + 1 :]


def _decode_text_column(
    buf: bytes, begin: np.ndarray, end: np.ndarray
) -> np.ndarray:
    """Cell (begin, end) offsets -> object array of optional strings.
    Doubled quotes inside quoted cells are unescaped lazily (only when a
    quote byte is present in the slice)."""
    out = np.empty(len(begin), dtype=object)
    for i in range(len(begin)):
        b, e = begin[i], end[i]
        if e <= b:
            out[i] = None
            continue
        s = buf[b:e].decode("utf-8", errors="replace")
        if '"' in s:
            s = s.replace('""', '"')
        out[i] = s if s else None
    return out


def _parse_header(path: str) -> list[str]:
    with open(path, "rb") as f:
        line = f.readline()
    if line.startswith(b"\xef\xbb\xbf"):
        # Excel-style UTF-8 BOM must not leak into the first column name
        line = line[3:]
    if not line.strip():
        return []
    ncols = line.count(b",") + 1
    res = native.csv_scan(line, ncols, np.full(ncols, 2, np.uint8))
    if res is None:  # pure-python fallback
        import csv as _csv
        import io

        return next(_csv.reader(io.StringIO(line.decode("utf-8"))))
    nrows, _, _, cb, ce = res
    if nrows == 0:
        return []
    return [line[cb[c][0]:ce[c][0]].decode("utf-8").replace('""', '"')
            for c in range(cb.shape[0])]


def fast_path_available() -> bool:
    return native.csv_scan(b"x\n", 1, np.zeros(1, np.uint8)) is not None


def _retry_masked_unicode_cells(
    chunk: bytes, cb: np.ndarray, ce: np.ndarray,
    vals: np.ndarray, mask: np.ndarray,
) -> None:
    """Masked numeric cells re-tried through python float(): the C++
    parser rejects any non-ASCII byte, but float() accepts unicode
    decimal digits ('١٢٣' -> 123.0) and the python reader path uses
    float() - both native ingest routes must agree with it on every
    cell.  Mutates vals/mask in place; ASCII junk stays masked.  Callers
    gate on chunk.isascii() so pure-ASCII chunks never reach here."""
    from ..schema.quarantine import coerce_numeric

    for r in np.nonzero(~mask)[0]:
        cell = chunk[cb[r]:ce[r]]
        if not cell or cell.isascii():
            continue
        v = coerce_numeric(cell)
        if v is None:
            continue
        vals[r] = v
        mask[r] = True


def read_csv_columnar(
    path: str,
    schema: Mapping[str, Type[FeatureType]],
    headers: Optional[Sequence[str]] = None,
    has_header: bool = True,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    wanted: Optional[Sequence[str]] = None,
    errors: str = "coerce",
    quarantine=None,
    telemetry=None,
) -> dict[str, Column]:
    """One ``ingest.read`` trace span per native scan (obs/), wrapping
    :func:`_read_csv_columnar`."""
    with _obs_trace.span(
        "ingest.read", source=path, format="csv_native", errors=errors,
    ):
        return _read_csv_columnar(
            path, schema, headers=headers, has_header=has_header,
            chunk_bytes=chunk_bytes, wanted=wanted, errors=errors,
            quarantine=quarantine, telemetry=telemetry,
        )


def _read_csv_columnar(
    path: str,
    schema: Mapping[str, Type[FeatureType]],
    headers: Optional[Sequence[str]] = None,
    has_header: bool = True,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    wanted: Optional[Sequence[str]] = None,
    errors: str = "coerce",
    quarantine=None,
    telemetry=None,
) -> dict[str, Column]:
    """Stream a CSV into columnar form via the native scanner.

    ``schema`` types every column to materialize; ``wanted`` restricts
    which columns are materialized (all schema'd columns by default).
    Raises RuntimeError when the native path is unavailable - callers
    (CSVReader) fall back to the python reader.

    ``errors`` (schema/quarantine.py): ``"coerce"`` keeps junk numeric
    cells as missing values (legacy); ``"strict"`` raises
    MalformedRowError at the first non-empty numeric cell that fails to
    parse; ``"quarantine"`` drops such rows across ALL materialized
    columns, recording (global row index, cell excerpt, reason).  The
    scanner has no per-row field counts, so ragged/truncated-row
    detection is the python reader's job (CSVReader routes checked
    modes there); this path owns type-flip detection at native speed.
    """
    from ..schema.quarantine import (
        MalformedRowError,
        QuarantineBuffer,
        check_errors_mode,
        data_telemetry,
        excerpt_of,
    )
    from ..faults import injection as _faults

    check_errors_mode(errors)
    checked = errors != "coerce"
    if checked and quarantine is None:
        quarantine = QuarantineBuffer(source=path)
    if not fast_path_available():
        raise RuntimeError("native CSV kernels unavailable")
    header = list(headers) if headers else (
        _parse_header(path) if has_header else None
    )
    first = True
    num_parts: dict[str, list] = {}
    mask_parts: dict[str, list] = {}
    text_parts: dict[str, list] = {}
    col_idx: dict[str, int] = {}
    modes: Optional[np.ndarray] = None
    names: list[str] = []
    rows_seen = 0
    rows_kept = 0
    for chunk in _aligned_chunks(path, chunk_bytes):
        if first and chunk.startswith(b"\xef\xbb\xbf"):
            # strip the BOM on the data path too: headerless files never
            # call _parse_header, and the scanner would otherwise read
            # '﻿1' in the first cell (python fallback uses utf-8-sig)
            chunk = chunk[3:]
        if first and has_header:
            nl = chunk.find(b"\n")
            # nl == -1: header-only file with no trailing newline
            chunk = chunk[nl + 1 :] if nl >= 0 else b""
        if first:
            if header is None:
                ncols = chunk.split(b"\n", 1)[0].count(b",") + 1
                header = [f"c{i}" for i in range(ncols)]
            names = [n for n in (wanted or list(schema)) if n in schema]
            missing = [n for n in names if n not in header]
            if missing:
                raise KeyError(f"columns {missing} not in CSV {path}")
            col_idx = {n: header.index(n) for n in names}
            # per-column scan mode: 0 skip / 1 numeric / 2 text offsets -
            # unmaterialized columns cost only the delimiter walk
            modes = np.zeros(len(header), dtype=np.uint8)
            for n in names:
                modes[col_idx[n]] = (
                    1 if issubclass(schema[n], OPNumeric) else 2
                )
            first = False
        if not chunk:
            continue
        res = native.csv_scan(chunk, len(header), modes)
        if res is None:
            raise RuntimeError("native CSV kernels unavailable")
        nrows, num_vals, num_mask, cb, ce = res
        if nrows == 0:
            continue
        # pure-ASCII chunks (the hot path) skip the unicode retry check
        # entirely; isascii() short-circuits at the first high byte
        retry = not chunk.isascii()
        chunk_num: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        chunk_text: dict[str, np.ndarray] = {}
        for n in names:
            c = col_idx[n]
            if modes[c] == 1:
                vals_c = num_vals[c].copy()
                mask_c = num_mask[c].copy()
                if retry:
                    _retry_masked_unicode_cells(
                        chunk, cb[c], ce[c], vals_c, mask_c
                    )
                chunk_num[n] = (vals_c, mask_c)
            else:
                chunk_text[n] = _decode_text_column(chunk, cb[c], ce[c])
        keep = None
        if checked:
            # a masked-but-NON-EMPTY cell is junk the parser refused: a
            # type flip.  Empty cells (ce <= cb) and literal-nan cells
            # (parsed, mask flows from the NaN handling below) are
            # legitimate missing values in every mode.
            bad = np.zeros(nrows, dtype=bool)
            bad_detail: dict[int, tuple[str, str, str]] = {}
            for n, (vals_c, mask_c) in chunk_num.items():
                c = col_idx[n]
                junk = ~mask_c & (ce[c] > cb[c])
                for r in np.nonzero(junk)[0]:
                    bad_detail.setdefault(int(r), (
                        "type_flip", n,
                        excerpt_of(chunk[cb[c][r]:ce[c][r]]),
                    ))
                bad |= junk
            # drill points: corrupt the chunk's first row so the drills
            # flow through the same quarantine/strict machinery
            if _faults.fires("reader.type_flip") is not None and nrows:
                bad_detail.setdefault(
                    0, ("type_flip", names[0], "<injected>"))
                bad[0] = True
            if _faults.fires("reader.malformed_row") is not None and nrows:
                bad_detail.setdefault(
                    0, ("malformed_row", None, "<injected>"))
                bad[0] = True
            if bad.any():
                if errors == "strict":
                    (telemetry or data_telemetry()).record_strict_error(
                        path
                    )
                    r0 = int(np.nonzero(bad)[0][0])
                    reason, col, cell = bad_detail[r0]
                    raise MalformedRowError(
                        path, rows_seen + r0, reason, col, cell
                    )
                for r in sorted(bad_detail):
                    reason, col, cell = bad_detail[r]
                    quarantine.add(rows_seen + r, reason, col, cell)
                keep = ~bad
        rows_seen += nrows
        rows_kept += nrows if keep is None else int(keep.sum())
        for n in names:
            if n in chunk_num:
                vals_c, mask_c = chunk_num[n]
                if keep is not None:
                    vals_c, mask_c = vals_c[keep], mask_c[keep]
                num_parts.setdefault(n, []).append(vals_c)
                mask_parts.setdefault(n, []).append(mask_c)
            else:
                txt = chunk_text[n]
                if keep is not None:
                    txt = txt[keep]
                text_parts.setdefault(n, []).append(txt)
    if checked:
        (telemetry or data_telemetry()).record_read(
            path, rows_seen, rows_kept, quarantine
        )
    if first:
        # zero-byte file: the chunk loop never ran - surface the same
        # missing-column error the python path gives
        names = [n for n in (wanted or list(schema)) if n in schema]
        missing = [n for n in names if n not in (header or [])]
        if missing:
            raise KeyError(f"columns {missing} not in CSV {path}")
    out: dict[str, Column] = {}
    for n in names:
        t = schema[n]
        if issubclass(t, OPNumeric):
            vals = (np.concatenate(num_parts[n]) if n in num_parts
                    else np.zeros(0))
            mask = (np.concatenate(mask_parts[n]) if n in mask_parts
                    else np.zeros(0, bool))
            # literal "nan" cells parse as NaN; the python path treats NaN
            # as missing (NumericColumn contract: masked slots hold 0.0)
            nan = np.isnan(vals)
            out[n] = NumericColumn(np.where(nan, 0.0, vals), mask & ~nan, t)
        elif issubclass(t, Text):
            vals = (np.concatenate(text_parts[n]) if n in text_parts
                    else np.empty(0, object))
            out[n] = TextColumn(vals, t)
        else:
            raise TypeError(
                f"fast CSV path supports numeric/text columns; {n} is "
                f"{t.__name__}"
            )
    return out


def double_buffered_to_device(producer, n_cols: int) -> tuple:
    """Shared double-buffered host→device pump: ``producer(queue)`` runs in
    a background thread pushing (values_block [rows, d] float32, mask_block
    [rows, d] bool) tuples, then None; exceptions are forwarded.  The
    consumer issues async ``jax.device_put`` per block - the next parse
    overlaps the DMA in flight - and concatenates on device.  Returns
    (X_device [n, n_cols], mask_device, rows); empty input yields correct-
    width zero-row arrays."""
    import jax
    import jax.numpy as jnp

    q: queue.Queue = queue.Queue(maxsize=2)
    t = threading.Thread(target=producer, args=(q,), daemon=True)
    t.start()
    dev_blocks, dev_masks, total = [], [], 0
    while True:
        item = q.get()
        if item is None:
            break
        if isinstance(item, BaseException):
            raise item
        block, mask = item
        total += block.shape[0]
        dev_blocks.append(jax.device_put(block))
        dev_masks.append(jax.device_put(mask))
    t.join()
    if not dev_blocks:
        return (jnp.zeros((0, n_cols), jnp.float32),
                jnp.zeros((0, n_cols), bool), 0)
    X = jnp.concatenate(dev_blocks, axis=0)
    M = jnp.concatenate(dev_masks, axis=0)
    return X, M, total


class DeviceCSVIngest:
    """CSV -> device-resident [n, d] float32 design matrix with the parse
    of chunk i+1 overlapping the device transfer of chunk i.

    A background thread runs the C++ scanner over aligned byte chunks and
    feeds a bounded queue (depth 2 = classic double buffer); the consumer
    issues ``jax.device_put`` per chunk - JAX transfers are async, so the
    next parse starts while DMA is in flight - and concatenates on device.
    """

    def __init__(self, path: str, columns: Sequence[str],
                 schema: Mapping[str, Type[FeatureType]],
                 has_header: bool = True,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 errors: str = "coerce",
                 quarantine=None,
                 telemetry=None) -> None:
        from ..schema.quarantine import QuarantineBuffer, check_errors_mode

        self.path = path
        self.columns = list(columns)
        self.schema = dict(schema)
        self.has_header = has_header
        self.chunk_bytes = chunk_bytes
        self.errors = check_errors_mode(errors)
        if self.errors != "coerce" and quarantine is None:
            quarantine = QuarantineBuffer(source=path)
        self.quarantine = quarantine
        self.telemetry = telemetry

    def _parse_worker(self, q: queue.Queue) -> None:
        from ..schema.quarantine import (
            MalformedRowError,
            data_telemetry,
            excerpt_of,
        )

        checked = self.errors != "coerce"
        rows_seen = rows_kept = 0
        try:
            header: Optional[list[str]] = None
            idx: Optional[list[int]] = None
            modes: Optional[np.ndarray] = None
            first = True
            for chunk in _aligned_chunks(self.path, self.chunk_bytes):
                if first:
                    if chunk.startswith(b"\xef\xbb\xbf"):
                        chunk = chunk[3:]  # same BOM strip as the
                        # columnar path (headerless files especially)
                    if self.has_header:
                        nl = chunk.find(b"\n")
                        header = _parse_header(self.path)
                        chunk = chunk[nl + 1 :] if nl >= 0 else b""
                    else:
                        n = chunk.split(b"\n", 1)[0].count(b",") + 1
                        header = [f"c{i}" for i in range(n)]
                    idx = [header.index(c) for c in self.columns]
                    modes = np.zeros(len(header), dtype=np.uint8)
                    modes[idx] = 1  # wanted numerics; everything else skips
                    first = False
                if not chunk:
                    continue
                res = native.csv_scan(chunk, len(header), modes)
                if res is None:
                    raise RuntimeError("native CSV kernels unavailable")
                nrows, num_vals, num_mask, cb, ce = res
                if nrows == 0:
                    continue
                if not chunk.isascii():
                    # same unicode-digit float() retry as the columnar
                    # path: both native ingest routes must agree with the
                    # python reader on every cell
                    for c in idx:
                        _retry_masked_unicode_cells(
                            chunk, cb[c], ce[c], num_vals[c], num_mask[c]
                        )
                keep = None
                if checked:
                    # same junk rule as read_csv_columnar: a non-empty
                    # cell the parser (plus unicode retry) refused is a
                    # type flip, not a missing value
                    bad = np.zeros(nrows, dtype=bool)
                    for c in idx:
                        bad |= ~num_mask[c] & (ce[c] > cb[c])
                    if bad.any():
                        if self.errors == "strict":
                            r0 = int(np.nonzero(bad)[0][0])
                            c0 = next(
                                c for c in idx
                                if not num_mask[c][r0]
                                and ce[c][r0] > cb[c][r0]
                            )
                            (self.telemetry or data_telemetry()
                             ).record_strict_error(self.path)
                            raise MalformedRowError(
                                self.path, rows_seen + r0, "type_flip",
                                self.columns[idx.index(c0)],
                                excerpt_of(chunk[cb[c0][r0]:ce[c0][r0]]),
                            )
                        for r in np.nonzero(bad)[0]:
                            c_bad = next(
                                c for c in idx
                                if not num_mask[c][r] and ce[c][r] > cb[c][r]
                            )
                            self.quarantine.add(
                                rows_seen + int(r), "type_flip",
                                self.columns[idx.index(c_bad)],
                                excerpt_of(chunk[cb[c_bad][r]:ce[c_bad][r]]),
                            )
                        keep = ~bad
                block = np.ascontiguousarray(
                    num_vals[idx].T, dtype=np.float32
                )  # [rows, d]
                mask = num_mask[idx].T  # [rows, d]
                if keep is not None:
                    block = block[keep]
                    mask = mask[keep]
                rows_seen += nrows
                rows_kept += block.shape[0]
                nan = np.isnan(block)  # literal "nan" cells -> missing
                if nan.any():
                    block = np.where(nan, np.float32(0.0), block)
                    mask = mask & ~nan
                q.put((block, mask))
            if checked:
                (self.telemetry or data_telemetry()).record_read(
                    self.path, rows_seen, rows_kept, self.quarantine
                )
            q.put(None)
        except BaseException as e:  # surface parse errors to the consumer
            q.put(e)

    def to_device(self):
        """Returns (X_device [n, d] float32, valid_mask_device [n, d]
        bool, rows).  Missing numeric cells are 0 with mask False (the
        NumericColumn contract, device-side)."""
        with _obs_trace.span(
            "ingest.device", source=self.path, format="csv_native",
        ):
            return double_buffered_to_device(
                self._parse_worker, len(self.columns)
            )
