"""CSV readers.

Counterpart of the reference CSV reader stack (reference: readers/.../
DataReaders.scala:44-198 factory, CSVAutoReaders auto-infer, utils/.../io/
csv/): parse a CSV into a columnar Dataset keyed by the requested raw
features.  Schema-ful (explicit {column: FeatureType}) or auto-inferring.
"""
from __future__ import annotations

import csv
from typing import Mapping, Optional, Sequence, Type

import numpy as np

from ..features.feature import Feature
from ..features.feature_builder import infer_feature_type
from ..types.columns import column_from_list
from ..types.dataset import Dataset
from ..types.feature_types import FeatureType, OPNumeric


def _parse_cell(raw: str, ftype: Type[FeatureType]):
    if raw is None or raw == "":
        return None
    if issubclass(ftype, OPNumeric):
        try:
            return float(raw)
        except ValueError:
            return None
    return raw


class CSVReader:
    """Simple batch CSV reader (reference: DataReaders.Simple.csvCase)."""

    def __init__(
        self,
        path: str,
        schema: Optional[Mapping[str, Type[FeatureType]]] = None,
        headers: Optional[Sequence[str]] = None,
        has_header: bool = True,
        key_col: Optional[str] = None,
    ) -> None:
        self.path = path
        self.schema = dict(schema) if schema else None
        self.headers = list(headers) if headers else None
        self.has_header = has_header
        self.key_col = key_col

    def read_raw(self) -> dict[str, list]:
        # utf-8-sig: an Excel-style BOM must not leak into the first
        # column name (no-op for BOM-less files)
        with open(self.path, newline="", encoding="utf-8-sig") as f:
            rows = list(csv.reader(f))
        if not rows:
            return {}
        if self.has_header and self.headers is None:
            header, rows = rows[0], rows[1:]
        elif self.headers is not None:
            header = self.headers
            if self.has_header:
                rows = rows[1:]
        else:
            header = [f"c{i}" for i in range(len(rows[0]))]
        cols: dict[str, list] = {h: [] for h in header}
        for r in rows:
            for h, v in zip(header, r):
                cols[h].append(v if v != "" else None)
            for h in header[len(r):]:
                cols[h].append(None)
        return cols

    def generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        """Reader hand-off (reference: DataReader.generateDataFrame:173-199).
        Numeric/text schemas stream through the chunked C++ scanner
        (readers/fast_csv.py) - no per-value python work for numeric
        columns; anything else (or no native lib) takes the python path."""
        if all(f.ftype.kind in ("numeric", "text") for f in raw_features):
            try:
                from .fast_csv import read_csv_columnar

                cols = read_csv_columnar(
                    self.path,
                    schema={f.name: f.ftype for f in raw_features},
                    headers=self.headers,
                    has_header=self.has_header,
                )
                return Dataset(cols)
            except RuntimeError:
                pass  # native kernels unavailable: python fallback
        raw = self.read_raw()
        out = {}
        for feat in raw_features:
            if feat.name not in raw:
                raise KeyError(f"column {feat.name!r} not in CSV {self.path}")
            parsed = [_parse_cell(v, feat.ftype) for v in raw[feat.name]]
            out[feat.name] = column_from_list(parsed, feat.ftype)
        return Dataset(out)

    def infer_schema(
        self, raw: Optional[dict[str, list]] = None
    ) -> dict[str, Type[FeatureType]]:
        """``raw`` lets callers that already read the file (cli.generate)
        skip a second full parse."""
        if raw is None:
            raw = self.read_raw()
        schema = {}
        for name, vals in raw.items():
            typed = []
            for v in vals[:1000]:
                if v is None:
                    typed.append(None)
                    continue
                try:
                    fv = float(v)
                    typed.append(int(fv) if fv.is_integer() else fv)
                except ValueError:
                    typed.append(v)
            schema[name] = infer_feature_type(typed)
        return schema
