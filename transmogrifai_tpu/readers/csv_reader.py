"""CSV readers.

Counterpart of the reference CSV reader stack (reference: readers/.../
DataReaders.scala:44-198 factory, CSVAutoReaders auto-infer, utils/.../io/
csv/): parse a CSV into a columnar Dataset keyed by the requested raw
features.  Schema-ful (explicit {column: FeatureType}) or auto-inferring.

Error policy (``errors=``, schema/quarantine.py): ``"coerce"`` keeps the
legacy behavior (junk numeric cells become missing values), ``"strict"``
raises :class:`~..schema.quarantine.MalformedRowError` naming the row
index/column, ``"quarantine"`` drops malformed / type-flipped /
truncated rows into a bounded QuarantineBuffer with exact counts in
DataTelemetry.  Strict/quarantine validation needs per-row structure
(ragged-row detection), so those modes always run the python path — the
native scanner stays the coerce-mode fast path (fast_csv.py carries its
own ``errors=`` support for direct columnar callers).
"""
from __future__ import annotations

import csv
from typing import Mapping, Optional, Sequence, Type

import numpy as np

from ..faults import injection as _faults
from ..features.feature import Feature
from ..features.feature_builder import infer_feature_type
from ..obs import trace as _obs_trace
from ..schema.quarantine import (
    MalformedRowError,
    QuarantineBuffer,
    check_errors_mode,
    coerce_numeric,
    data_telemetry,
    excerpt_of,
)
from ..types.columns import column_from_list
from ..types.dataset import Dataset
from ..types.feature_types import FeatureType, OPNumeric


def _parse_cell(raw: str, ftype: Type[FeatureType]):
    if raw is None or raw == "":
        return None
    if issubclass(ftype, OPNumeric):
        try:
            return float(raw)
        except ValueError:
            return None
    return raw


def _cell_is_numeric(raw: str) -> bool:
    """True when a non-empty CSV cell parses as the coerce path would
    parse it (shared rule: schema.quarantine.coerce_numeric - float(),
    which also accepts 'nan'/'inf' and unicode digits)."""
    return coerce_numeric(raw) is not None


INJECTED_JUNK = "\x00<injected-junk>"


class CSVReader:
    """Simple batch CSV reader (reference: DataReaders.Simple.csvCase)."""

    def __init__(
        self,
        path: str,
        schema: Optional[Mapping[str, Type[FeatureType]]] = None,
        headers: Optional[Sequence[str]] = None,
        has_header: bool = True,
        key_col: Optional[str] = None,
        errors: str = "coerce",
        quarantine: Optional[QuarantineBuffer] = None,
        telemetry=None,
        use_native: bool = True,
    ) -> None:
        self.path = path
        self.schema = dict(schema) if schema else None
        self.headers = list(headers) if headers else None
        self.has_header = has_header
        self.key_col = key_col
        self.errors = check_errors_mode(errors)
        self.quarantine = quarantine
        self.telemetry = telemetry
        # use_native=False pins the python path even for numeric/text
        # schemas: apples-to-apples timing (bench) and path-parity tests
        self.use_native = bool(use_native)

    def read_raw(self) -> dict[str, list]:
        # utf-8-sig: an Excel-style BOM must not leak into the first
        # column name (no-op for BOM-less files)
        with open(self.path, newline="", encoding="utf-8-sig") as f:
            rows = list(csv.reader(f))
        if not rows:
            return {}
        if self.has_header and self.headers is None:
            header, rows = rows[0], rows[1:]
        elif self.headers is not None:
            header = self.headers
            if self.has_header:
                rows = rows[1:]
        else:
            header = [f"c{i}" for i in range(len(rows[0]))]
        cols: dict[str, list] = {h: [] for h in header}
        for r in rows:
            for h, v in zip(header, r):
                cols[h].append(v if v != "" else None)
            for h in header[len(r):]:
                cols[h].append(None)
        return cols

    def generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        """Reader hand-off (reference: DataReader.generateDataFrame:173-199).
        Numeric/text schemas stream through the chunked C++ scanner
        (readers/fast_csv.py) - no per-value python work for numeric
        columns; anything else (or no native lib) takes the python path.
        Strict/quarantine error modes run the checked python path (row
        structure is required for ragged-row detection).  Each read is
        one ``ingest.read`` trace span on the ambient run trace
        (obs/)."""
        with _obs_trace.span(
            "ingest.read", source=self.path, format="csv",
            errors=self.errors,
        ) as sp:
            ds = self._generate_dataset(raw_features, params)
            sp.set_attr("rows", len(ds))
            return ds

    def _generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        if self.errors != "coerce":
            return self._generate_checked(raw_features)
        if self.use_native and all(
            f.ftype.kind in ("numeric", "text") for f in raw_features
        ):
            try:
                from .fast_csv import read_csv_columnar

                cols = read_csv_columnar(
                    self.path,
                    schema={f.name: f.ftype for f in raw_features},
                    headers=self.headers,
                    has_header=self.has_header,
                )
                return Dataset(cols)
            except RuntimeError as e:
                # native kernels unavailable: python fallback below
                import logging

                logging.getLogger("transmogrifai_tpu.readers").debug(
                    "fast CSV path unavailable (%s); python fallback", e
                )
        raw = self.read_raw()
        out = {}
        for feat in raw_features:
            if feat.name not in raw:
                raise KeyError(f"column {feat.name!r} not in CSV {self.path}")
            parsed = [_parse_cell(v, feat.ftype) for v in raw[feat.name]]
            out[feat.name] = column_from_list(parsed, feat.ftype)
        return Dataset(out)

    # -- checked ingestion (errors = strict | quarantine) -------------------
    def _read_rows(self) -> tuple[list[str], list[list[str]]]:
        """(header, raw rows) WITHOUT the read_raw padding - checked
        modes need each row's true field count."""
        with open(self.path, newline="", encoding="utf-8-sig") as f:
            rows = list(csv.reader(f))
        if not rows:
            return (self.headers or []), []
        if self.has_header and self.headers is None:
            return rows[0], rows[1:]
        if self.headers is not None:
            return list(self.headers), rows[1:] if self.has_header else rows
        return [f"c{i}" for i in range(len(rows[0]))], rows

    def _generate_checked(
        self, raw_features: Sequence[Feature]
    ) -> Dataset:
        """Row-validated ingest: malformed rows (field-count mismatch)
        and type-flipped numeric cells either raise (strict) or land in
        the quarantine buffer (quarantine).  Fault points
        ``reader.malformed_row`` / ``reader.type_flip`` corrupt live
        rows so drills exercise the REAL detection path."""
        header, rows = self._read_rows()
        missing = [f.name for f in raw_features if f.name not in header]
        if missing:
            raise KeyError(f"columns {missing} not in CSV {self.path}")
        col_idx = {f.name: header.index(f.name) for f in raw_features}
        numeric = [
            (f.name, col_idx[f.name]) for f in raw_features
            if issubclass(f.ftype, OPNumeric)
        ]
        buf = self.quarantine
        if buf is None:
            buf = self.quarantine = QuarantineBuffer(source=self.path)
        ncols = len(header)
        parsed: dict[str, list] = {f.name: [] for f in raw_features}
        kept = 0
        for i, r in enumerate(rows):
            if _faults.fires("reader.malformed_row") is not None:
                r = r[: max(len(r) - 1, 0)]  # chop a field: truncated row
            if numeric and _faults.fires("reader.type_flip") is not None:
                r = list(r)
                if numeric[0][1] < len(r):
                    r[numeric[0][1]] = INJECTED_JUNK
            bad_reason = bad_col = bad_cell = None
            if len(r) != ncols:
                bad_reason = (
                    "truncated_row" if len(r) < ncols else "extra_fields"
                )
                bad_cell = ",".join(r)
            else:
                for name, c in numeric:
                    cell = r[c]
                    if cell and not _cell_is_numeric(cell):
                        bad_reason, bad_col, bad_cell = (
                            "type_flip", name, cell
                        )
                        break
            if bad_reason is not None:
                if self.errors == "strict":
                    (self.telemetry or data_telemetry()).record_strict_error(
                        self.path
                    )
                    raise MalformedRowError(
                        self.path, i, bad_reason, bad_col,
                        excerpt_of(bad_cell),
                    )
                buf.add(i, bad_reason, bad_col, excerpt_of(bad_cell))
                continue
            kept += 1
            for f in raw_features:
                v = r[col_idx[f.name]]
                parsed[f.name].append(_parse_cell(v, f.ftype))
        (self.telemetry or data_telemetry()).record_read(
            self.path, len(rows), kept, buf
        )
        return Dataset({
            f.name: column_from_list(parsed[f.name], f.ftype)
            for f in raw_features
        })

    def infer_schema(
        self, raw: Optional[dict[str, list]] = None
    ) -> dict[str, Type[FeatureType]]:
        """``raw`` lets callers that already read the file (cli.generate)
        skip a second full parse."""
        if raw is None:
            raw = self.read_raw()
        schema = {}
        for name, vals in raw.items():
            typed = []
            for v in vals[:1000]:
                if v is None:
                    typed.append(None)
                    continue
                try:
                    fv = float(v)
                    typed.append(int(fv) if fv.is_integer() else fv)
                except ValueError:
                    typed.append(v)
            schema[name] = infer_feature_type(typed)
        return schema
