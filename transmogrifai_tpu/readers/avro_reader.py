"""Avro Object Container File reader (pure python, no external deps).

Counterpart of the reference's Avro ingestion (reference: readers/.../
AvroReaders (DataReaders.scala:44-110), utils/.../io/avro/AvroInOut.scala):
decodes the standard OCF layout - header magic ``Obj\\x01``, file metadata
(embedded JSON schema, codec null/deflate), sync-marker-delimited blocks of
zigzag-varint-encoded records - into python dicts / a columnar Dataset.
Supports null, boolean, int, long, float, double, bytes, string, enum,
fixed, array, map, union, and nested record schemas.
"""
from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, BinaryIO, Iterator, Optional, Sequence

from ..features.feature import Feature
from ..types.columns import column_from_list
from ..types.dataset import Dataset

MAGIC = b"Obj\x01"


class _Decoder:
    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        out = self.buf[self.pos : self.pos + n]
        if len(out) != n:
            raise EOFError("truncated avro data")
        self.pos += n
        return out

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    read_int = read_long

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def read_float(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def read_double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def read_boolean(self) -> bool:
        return self.read(1) != b"\x00"


def _decode_value(dec: _Decoder, schema: Any) -> Any:
    if isinstance(schema, list):  # union
        idx = dec.read_long()
        return _decode_value(dec, schema[idx])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {
                f["name"]: _decode_value(dec, f["type"])
                for f in schema["fields"]
            }
        if t == "enum":
            return schema["symbols"][dec.read_long()]
        if t == "fixed":
            return dec.read(schema["size"])
        if t == "array":
            out = []
            while True:
                n = dec.read_long()
                if n == 0:
                    break
                if n < 0:
                    dec.read_long()  # block size, ignored
                    n = -n
                for _ in range(n):
                    out.append(_decode_value(dec, schema["items"]))
            return out
        if t == "map":
            out = {}
            while True:
                n = dec.read_long()
                if n == 0:
                    break
                if n < 0:
                    dec.read_long()
                    n = -n
                for _ in range(n):
                    out[dec.read_string()] = _decode_value(dec, schema["values"])
            return out
        return _decode_value(dec, t)  # {"type": "string"} style
    # primitive
    if schema == "null":
        return None
    if schema == "boolean":
        return dec.read_boolean()
    if schema in ("int", "long"):
        return dec.read_long()
    if schema == "float":
        return dec.read_float()
    if schema == "double":
        return dec.read_double()
    if schema == "bytes":
        return dec.read_bytes()
    if schema == "string":
        return dec.read_string()
    raise ValueError(f"unsupported avro type: {schema!r}")


def read_avro_records(path: str) -> tuple[dict, list[dict]]:
    """Read all records + the parsed schema from an OCF file."""
    with open(path, "rb") as f:
        data = f.read()
    dec = _Decoder(data)
    if dec.read(4) != MAGIC:
        raise ValueError(f"{path} is not an avro object container file")
    meta: dict[str, bytes] = {}
    while True:
        n = dec.read_long()
        if n == 0:
            break
        if n < 0:
            dec.read_long()
            n = -n
        for _ in range(n):
            key = dec.read_string()
            meta[key] = dec.read_bytes()
    sync = dec.read(16)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    records: list[dict] = []
    while not dec.at_end():
        count = dec.read_long()
        size = dec.read_long()
        block = dec.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        bdec = _Decoder(block)
        for _ in range(count):
            records.append(_decode_value(bdec, schema))
        if dec.read(16) != sync:
            raise ValueError("bad sync marker (corrupt avro file)")
    return schema, records


class AvroReader:
    """Batch reader over an avro file (reference: DataReaders.Simple.avro)."""

    def __init__(self, path: str, key_field: Optional[str] = None) -> None:
        self.path = path
        self.key_field = key_field
        self._schema: Optional[dict] = None
        self._records: Optional[list[dict]] = None

    @property
    def records(self) -> list[dict]:
        if self._records is None:
            self._schema, self._records = read_avro_records(self.path)
        return self._records

    def generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        recs = self.records
        cols = {}
        for f in raw_features:
            vals = [_coerce(r.get(f.name), f) for r in recs]
            cols[f.name] = column_from_list(vals, f.ftype)
        return Dataset(cols)


def _coerce(v: Any, f: Feature) -> Any:
    if v is None:
        return None
    if f.ftype.kind == "numeric":
        if isinstance(v, bool):
            return float(v)
        if isinstance(v, (int, float)):
            return float(v)
        try:
            return float(v)
        except (TypeError, ValueError):
            return None
    if f.ftype.kind == "text":
        return str(v)
    return v


class ParquetReader:
    """Batch reader over parquet (reference: ParquetProductReader) - via
    pyarrow when available."""

    def __init__(self, path: str) -> None:
        self.path = path

    def generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        import numpy as np
        import pyarrow.parquet as pq
        import pyarrow.types as pat

        table = pq.read_table(
            self.path, columns=[f.name for f in raw_features]
        )
        cols = {}
        for f in raw_features:
            col = table.column(f.name)
            arrow_numeric = (
                pat.is_integer(col.type) or pat.is_floating(col.type)
                or pat.is_boolean(col.type) or pat.is_decimal(col.type)
            )
            if f.ftype.kind == "numeric" and arrow_numeric:
                # vectorized Arrow decode (string-typed numerics hit the
                # fallback): nulls surface as NaN after the float cast,
                # and column_from_list's ndarray branch owns the
                # NaN->masked NumericColumn contract
                cols[f.name] = column_from_list(
                    np.asarray(col.to_numpy(zero_copy_only=False),
                               np.float64),
                    f.ftype,
                )
                continue
            vals = [_coerce(v, f) for v in col.to_pylist()]
            cols[f.name] = column_from_list(vals, f.ftype)
        return Dataset(cols)
