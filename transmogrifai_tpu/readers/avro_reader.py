"""Avro Object Container File reader (pure python, no external deps).

Counterpart of the reference's Avro ingestion (reference: readers/.../
AvroReaders (DataReaders.scala:44-110), utils/.../io/avro/AvroInOut.scala):
decodes the standard OCF layout - header magic ``Obj\\x01``, file metadata
(embedded JSON schema, codec null/deflate), sync-marker-delimited blocks of
zigzag-varint-encoded records - into python dicts / a columnar Dataset.
Supports null, boolean, int, long, float, double, bytes, string, enum,
fixed, array, map, union, and nested record schemas.

Error policy (``errors=``, schema/quarantine.py): ``"coerce"`` keeps
legacy behavior (type-mismatched values silently become missing,
truncation raises raw EOFError), ``"strict"`` raises MalformedRowError
naming the record index, ``"quarantine"`` isolates type-flipped records
and a truncated/corrupt trailing block into a bounded QuarantineBuffer
instead of aborting the whole ingest.
"""
from __future__ import annotations

import json
import logging
import struct
import zlib
from typing import Any, Optional, Sequence

from ..faults import injection as _faults
from ..features.feature import Feature
from ..obs import trace as _obs_trace
from ..schema.quarantine import (
    MalformedRowError,
    QuarantineBuffer,
    check_errors_mode,
    coerce_numeric,
    data_telemetry,
    excerpt_of,
)
from ..types.columns import column_from_list
from ..types.dataset import Dataset

log = logging.getLogger("transmogrifai_tpu.readers")

MAGIC = b"Obj\x01"


class _Decoder:
    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        out = self.buf[self.pos : self.pos + n]
        if len(out) != n:
            raise EOFError("truncated avro data")
        self.pos += n
        return out

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    read_int = read_long

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def read_float(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def read_double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def read_boolean(self) -> bool:
        return self.read(1) != b"\x00"


def _decode_value(dec: _Decoder, schema: Any) -> Any:
    if isinstance(schema, list):  # union
        idx = dec.read_long()
        return _decode_value(dec, schema[idx])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {
                f["name"]: _decode_value(dec, f["type"])
                for f in schema["fields"]
            }
        if t == "enum":
            return schema["symbols"][dec.read_long()]
        if t == "fixed":
            return dec.read(schema["size"])
        if t == "array":
            out = []
            while True:
                n = dec.read_long()
                if n == 0:
                    break
                if n < 0:
                    dec.read_long()  # block size, ignored
                    n = -n
                for _ in range(n):
                    out.append(_decode_value(dec, schema["items"]))
            return out
        if t == "map":
            out = {}
            while True:
                n = dec.read_long()
                if n == 0:
                    break
                if n < 0:
                    dec.read_long()
                    n = -n
                for _ in range(n):
                    # key MUST be read before the value: python evaluates
                    # the RHS of `out[k()] = v()` first, which silently
                    # decoded value-then-key and scrambled every non-empty
                    # map (caught by the writer round-trip test)
                    key = dec.read_string()
                    out[key] = _decode_value(dec, schema["values"])
            return out
        return _decode_value(dec, t)  # {"type": "string"} style
    # primitive
    if schema == "null":
        return None
    if schema == "boolean":
        return dec.read_boolean()
    if schema in ("int", "long"):
        return dec.read_long()
    if schema == "float":
        return dec.read_float()
    if schema == "double":
        return dec.read_double()
    if schema == "bytes":
        return dec.read_bytes()
    if schema == "string":
        return dec.read_string()
    raise ValueError(f"unsupported avro type: {schema!r}")


class _ByteWindow:
    """Bounded read-ahead over a binary file.

    Exposes FILE-ABSOLUTE offsets so the decoder and the damage-resync
    scan can reason in the same coordinates the materializing reader
    used, while only ever buffering from the current block head forward.
    """

    def __init__(self, f, read_bytes: int = 1 << 20) -> None:
        self._f = f
        self._read_bytes = read_bytes
        self.buf = bytearray()
        self.base = 0  # file offset of buf[0]
        self.eof = False

    def _fill(self) -> bool:
        if self.eof:
            return False
        b = self._f.read(self._read_bytes)
        if not b:
            self.eof = True
            return False
        self.buf += b
        return True

    def ensure(self, end: int) -> bool:
        """Buffer through file offset ``end`` (exclusive); False at EOF."""
        while self.base + len(self.buf) < end:
            if not self._fill():
                return False
        return True

    def drop_to(self, pos: int) -> None:
        cut = pos - self.base
        if cut > 0:
            del self.buf[:cut]
            self.base = pos

    def find(self, needle: bytes, start: int) -> int:
        """File-absolute ``find`` from ``start``, discarding scanned
        bytes as it goes (a len(needle)-1 overlap survives each read so
        a marker straddling two reads still matches); -1 when absent —
        at which point the window has reached EOF, so ``base + len(buf)``
        is the total file size."""
        self.drop_to(start)
        while True:
            i = self.buf.find(needle)
            if i >= 0:
                return self.base + i
            keep = len(needle) - 1
            if len(self.buf) > keep:
                cut = len(self.buf) - keep
                del self.buf[:cut]
                self.base += cut
            if not self._fill():
                return -1


class _WindowDecoder(_Decoder):
    """The _Decoder API over a _ByteWindow; ``pos`` is file-absolute.
    The inherited compound reads (read_bytes/string/float/double/
    boolean) all route through the three primitives overridden here."""

    def __init__(self, win: _ByteWindow, pos: int = 0) -> None:
        self.win = win
        self.pos = pos

    def read(self, n: int) -> bytes:
        if not self.win.ensure(self.pos + n):
            raise EOFError("truncated avro data")
        s = self.pos - self.win.base
        out = bytes(self.win.buf[s : s + n])
        self.pos += n
        return out

    def at_end(self) -> bool:
        return not self.win.ensure(self.pos + 1)

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            if not self.win.ensure(self.pos + 1):
                raise EOFError("truncated avro data")
            b = self.win.buf[self.pos - self.win.base]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    read_int = read_long


class AvroBlockStream:
    """Incremental OCF block decoder — ONE implementation serving both
    the materializing batch reader (:func:`read_avro_records`) and the
    input pipeline's chunked avro ingest.

    The header (magic, metadata map, schema, codec, sync marker) parses
    eagerly in ``__init__``; :meth:`blocks` then yields each block's
    decoded record list while holding only the current block (plus a
    bounded read-ahead window) in memory, so an avro shard streams
    exactly like a CSV shard instead of materializing the whole file.

    Error policy matches the old whole-file reader byte for byte:
    ``"coerce"`` raises raw, ``"strict"`` raises MalformedRowError
    naming the clean-record index, ``"quarantine"`` rolls the suspect
    block back, records the damage (same excerpt strings), and resyncs
    forward past the next sync marker — scanned incrementally, never
    by loading the tail.  ``records_decoded`` counts cleanly decoded
    records and ``damaged`` counts quarantined block-level events so
    callers can reconcile rows_seen without owning the buffer.
    """

    def __init__(self, path: str, errors: str = "coerce",
                 quarantine: Optional[QuarantineBuffer] = None,
                 read_bytes: int = 1 << 20) -> None:
        check_errors_mode(errors)
        self.path = path
        self.errors = errors
        self.quarantine = quarantine
        self.records_decoded = 0
        self.damaged = 0
        self._f = open(path, "rb")
        try:
            self._win = _ByteWindow(self._f, read_bytes)
            dec = _WindowDecoder(self._win)
            if dec.read(4) != MAGIC:
                raise ValueError(
                    f"{path} is not an avro object container file")
            meta: dict[str, bytes] = {}
            while True:
                n = dec.read_long()
                if n == 0:
                    break
                if n < 0:
                    dec.read_long()
                    n = -n
                for _ in range(n):
                    key = dec.read_string()
                    meta[key] = dec.read_bytes()
            self._sync = dec.read(16)
            self.schema = json.loads(meta["avro.schema"].decode("utf-8"))
            self.codec = meta.get("avro.codec", b"null").decode("utf-8")
            if self.codec not in ("null", "deflate"):
                # configuration error, NOT block damage: checked once up
                # front so quarantine mode can never misread a whole
                # valid file in an unsupported codec as wall-to-wall
                # corrupt blocks
                raise ValueError(f"unsupported avro codec {self.codec!r}")
            self._dec = dec
        except BaseException:
            self._f.close()
            raise

    def close(self) -> None:
        self._f.close()

    def blocks(self):
        """Yield each block's decoded records (a list per block)."""
        dec, win, sync = self._dec, self._win, self._sync
        while True:
            block_start = dec.pos
            # nothing before the current block head is ever needed again
            # (the resync scan searches FORWARD from it), so release it:
            # this is what bounds memory to one block + read-ahead
            win.drop_to(block_start)
            if dec.at_end():
                return
            out: list = []
            try:
                count = dec.read_long()
                size = dec.read_long()
                block = dec.read(size)
                if self.codec == "deflate":
                    block = zlib.decompress(block, -15)
                bdec = _Decoder(block)
                for _ in range(count):
                    out.append(_decode_value(bdec, self.schema))
                if dec.read(16) != sync:
                    raise ValueError("bad sync marker (corrupt avro file)")
            except (EOFError, IndexError, ValueError, KeyError, zlib.error,
                    struct.error, UnicodeDecodeError) as e:
                if self.errors == "coerce":
                    raise
                truncated = isinstance(
                    e, (EOFError, IndexError, struct.error))
                reason = "truncated_block" if truncated else "corrupt_block"
                if self.errors == "strict":
                    data_telemetry().record_strict_error(self.path)
                    # the old whole-file reader's index counted the
                    # damaged block's partially decoded records too
                    # (nothing rolled back before a strict raise) -
                    # keep that contract exactly
                    raise MalformedRowError(
                        self.path, self.records_decoded + len(out),
                        reason, None, excerpt_of(str(e)),
                    ) from e
                # quarantine: the whole damaged block is suspect - its
                # records never left this frame, so dropping the block
                # is just not yielding it.  Search for the next sync
                # marker from the block HEAD, not the failure point:
                # when damage hits early payload (or just the trailing
                # marker) this finds THIS block's own boundary, so the
                # next healthy block is never skipped.  A false match
                # inside payload just fails the next decode and resyncs
                # again - strictly forward progress either way.
                self.damaged += 1
                nxt = win.find(sync, block_start)
                if nxt < 0:
                    total = win.base + len(win.buf)  # find() hit EOF
                    if self.quarantine is not None:
                        self.quarantine.add(
                            self.records_decoded, reason, None,
                            excerpt_of(f"{e}; no later sync marker - "
                                       f"{total - block_start} trailing "
                                       "bytes undecodable"),
                        )
                    log.warning(
                        "avro %s: %s at record %d; no sync marker after "
                        "byte %d - keeping the %d-record clean prefix",
                        self.path, reason, self.records_decoded,
                        block_start, self.records_decoded,
                    )
                    return
                if self.quarantine is not None:
                    self.quarantine.add(
                        self.records_decoded, reason, None,
                        excerpt_of(f"{e}; block dropped, resynced past "
                                   f"{nxt + 16 - block_start} bytes"),
                    )
                log.warning(
                    "avro %s: %s at record %d; dropping the damaged "
                    "block (%d bytes) and resyncing",
                    self.path, reason, self.records_decoded,
                    nxt + 16 - block_start,
                )
                dec.pos = nxt + 16  # just past the marker: next block
                continue
            self.records_decoded += len(out)
            yield out


def read_avro_records(
    path: str,
    errors: str = "coerce",
    quarantine: Optional[QuarantineBuffer] = None,
) -> tuple[dict, list[dict]]:
    """Read all records + the parsed schema from an OCF file (a
    materializing wrapper over :class:`AvroBlockStream`).

    A truncated or corrupt trailing block: raw EOFError/ValueError under
    ``"coerce"`` (legacy), :class:`MalformedRowError` naming the record
    index under ``"strict"``, or — under ``"quarantine"`` — the cleanly
    decoded prefix is returned and the damage recorded in the buffer.
    """
    stream = AvroBlockStream(path, errors=errors, quarantine=quarantine)
    try:
        records: list[dict] = []
        for block in stream.blocks():
            records.extend(block)
        return stream.schema, records
    finally:
        stream.close()


class AvroReader:
    """Batch reader over an avro file (reference: DataReaders.Simple.avro)."""

    def __init__(self, path: str, key_field: Optional[str] = None,
                 errors: str = "coerce",
                 quarantine: Optional[QuarantineBuffer] = None,
                 telemetry=None) -> None:
        self.path = path
        self.key_field = key_field
        self.errors = check_errors_mode(errors)
        self.quarantine = quarantine
        self.telemetry = telemetry
        self._schema: Optional[dict] = None
        self._records: Optional[list[dict]] = None
        self._checked_cache: dict[tuple, list] = {}

    def _buffer(self) -> QuarantineBuffer:
        if self.quarantine is None:
            self.quarantine = QuarantineBuffer(source=self.path)
        return self.quarantine

    @property
    def records(self) -> list[dict]:
        if self._records is None:
            self._schema, self._records = read_avro_records(
                self.path, errors=self.errors,
                quarantine=(
                    self._buffer() if self.errors == "quarantine" else None
                ),
            )
        return self._records

    def generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        with _obs_trace.span(
            "ingest.read", source=self.path, format="avro",
            errors=self.errors,
        ) as sp:
            ds = self._generate_dataset(raw_features)
            sp.set_attr("rows", len(ds))
            return ds

    def _generate_dataset(
        self, raw_features: Sequence[Feature]
    ) -> Dataset:
        recs = self.records
        if self.errors != "coerce":
            # memoized PER FEATURE SET: a repeat call with the same
            # features (train + compute_data_up_to on one reader) must
            # not re-validate and double every quarantine/telemetry
            # count, while a different feature list (new numeric
            # columns = new type-flip surface) validates afresh
            key = tuple(
                (f.name, f.ftype.kind) for f in raw_features
            )
            if key not in self._checked_cache:
                self._checked_cache[key] = self._checked_records(
                    recs, raw_features
                )
            recs = self._checked_cache[key]
        cols = {}
        for f in raw_features:
            vals = [_coerce(r.get(f.name), f) for r in recs]
            cols[f.name] = column_from_list(vals, f.ftype)
        return Dataset(cols)

    def _checked_records(
        self, recs: list, raw_features: Sequence[Feature]
    ) -> list:
        """Per-record validation: a non-null value in a numeric feature
        that fails the coerce path's float() is a type flip (the coerce
        mode would silently null it); a non-record entry is malformed.
        Strict raises at the first offense naming the record index;
        quarantine drops the record and keeps exact counts."""
        buf = self._buffer()
        # entries already in the buffer are file-level damage from
        # read_avro_records (a truncated/corrupt tail block): count each
        # as a read-and-quarantined row so rows_read - rows_kept always
        # agrees with the buffer's by_reason totals
        file_level = buf.total
        numeric = [f.name for f in raw_features
                   if f.ftype.kind == "numeric"]
        kept = []
        for i, r in enumerate(recs):
            reason = col = cell = None
            if _faults.fires("reader.malformed_row") is not None:
                reason, cell = "malformed_record", "<injected>"
            elif (_faults.fires("reader.type_flip") is not None
                    and numeric):
                reason, col, cell = "type_flip", numeric[0], "<injected>"
            elif not isinstance(r, dict):
                reason, cell = "malformed_record", excerpt_of(r)
            else:
                for name in numeric:
                    v = r.get(name)
                    if v is None or isinstance(v, (bool, int, float)):
                        continue
                    if coerce_numeric(v) is None:
                        reason, col, cell = (
                            "type_flip", name, excerpt_of(v)
                        )
                        break
            if reason is not None:
                if self.errors == "strict":
                    (self.telemetry or data_telemetry()
                     ).record_strict_error(self.path)
                    raise MalformedRowError(
                        self.path, i, reason, col, cell
                    )
                buf.add(i, reason, col, cell)
                continue
            kept.append(r)
        (self.telemetry or data_telemetry()).record_read(
            self.path, len(recs) + file_level, len(kept), buf
        )
        return kept


def _coerce(v: Any, f: Feature) -> Any:
    if v is None:
        return None
    if f.ftype.kind == "numeric":
        if isinstance(v, bool):
            return float(v)
        if isinstance(v, (int, float)):
            return float(v)
        return coerce_numeric(v)
    if f.ftype.kind == "text":
        return str(v)
    return v


class ParquetReader:
    """Batch reader over parquet (reference: ParquetProductReader) - via
    pyarrow when available."""

    def __init__(self, path: str, errors: str = "coerce",
                 quarantine: Optional[QuarantineBuffer] = None,
                 telemetry=None) -> None:
        self.path = path
        self.errors = check_errors_mode(errors)
        self.quarantine = quarantine
        self.telemetry = telemetry

    def _checked_take(self, table, raw_features: Sequence[Feature]):
        """Row-validated parquet ingest: parquet's own types make most
        flips impossible, but a string-typed column serving a numeric
        feature can still carry junk the coerce path would silently
        null.  Drops (quarantine) or names (strict) those rows."""
        import pyarrow.types as pat

        buf = self.quarantine
        if buf is None:
            buf = self.quarantine = QuarantineBuffer(source=self.path)
        n = table.num_rows
        bad: dict[int, tuple[str, Optional[str], str]] = {}
        for f in raw_features:
            if f.ftype.kind != "numeric":
                continue
            col = table.column(f.name)
            if (pat.is_integer(col.type) or pat.is_floating(col.type)
                    or pat.is_boolean(col.type) or pat.is_decimal(col.type)):
                continue
            for i, v in enumerate(col.to_pylist()):
                if v is None or isinstance(v, (bool, int, float)):
                    continue
                if coerce_numeric(v) is None and i not in bad:
                    bad[i] = ("type_flip", f.name, excerpt_of(v))
        if _faults.fires("reader.type_flip") is not None and n:
            bad.setdefault(0, ("type_flip", raw_features[0].name,
                               "<injected>"))
        if _faults.fires("reader.malformed_row") is not None and n:
            bad.setdefault(0, ("malformed_record", None, "<injected>"))
        if bad and self.errors == "strict":
            i0 = min(bad)
            reason, col_name, cell = bad[i0]
            (self.telemetry or data_telemetry()).record_strict_error(
                self.path
            )
            raise MalformedRowError(self.path, i0, reason, col_name, cell)
        for i in sorted(bad):
            reason, col_name, cell = bad[i]
            buf.add(i, reason, col_name, cell)
        (self.telemetry or data_telemetry()).record_read(
            self.path, n, n - len(bad), buf
        )
        if not bad:
            return table
        keep = [i for i in range(n) if i not in bad]
        return table.take(keep)

    def generate_dataset(
        self, raw_features: Sequence[Feature], params: Optional[dict] = None
    ) -> Dataset:
        with _obs_trace.span(
            "ingest.read", source=self.path, format="parquet",
            errors=self.errors,
        ) as sp:
            ds = self._generate_dataset(raw_features)
            sp.set_attr("rows", len(ds))
            return ds

    def _generate_dataset(
        self, raw_features: Sequence[Feature]
    ) -> Dataset:
        import numpy as np
        import pyarrow.parquet as pq
        import pyarrow.types as pat

        table = pq.read_table(
            self.path, columns=[f.name for f in raw_features]
        )
        if self.errors != "coerce":
            table = self._checked_take(table, raw_features)
        cols = {}
        for f in raw_features:
            col = table.column(f.name)
            arrow_numeric = (
                pat.is_integer(col.type) or pat.is_floating(col.type)
                or pat.is_boolean(col.type) or pat.is_decimal(col.type)
            )
            if f.ftype.kind == "numeric" and arrow_numeric:
                # vectorized Arrow decode (string-typed numerics hit the
                # fallback): nulls surface as NaN after the float cast,
                # and column_from_list's ndarray branch owns the
                # NaN->masked NumericColumn contract
                cols[f.name] = column_from_list(
                    np.asarray(col.to_numpy(zero_copy_only=False),
                               np.float64),
                    f.ftype,
                )
                continue
            vals = [_coerce(v, f) for v in col.to_pylist()]
            cols[f.name] = column_from_list(vals, f.ftype)
        return Dataset(cols)


# ---------------------------------------------------------------------------
# Avro OCF WRITER (inverse of the reader above; reference counterparts:
# utils/.../io/avro/AvroInOut.scala saveAvro and utils/.../io/csv/
# CSVToAvro.scala).  Encodes the same subset the decoder reads: null,
# boolean, int, long, float, double, bytes, string, enum, fixed, array,
# map, union, nested record; codec null or deflate.
# ---------------------------------------------------------------------------
class _Encoder:
    def __init__(self) -> None:
        self.parts: list[bytes] = []

    def write(self, b: bytes) -> None:
        self.parts.append(b)

    def write_long(self, v: int) -> None:
        v = (v << 1) ^ (v >> 63)  # zigzag (python ints: arithmetic shift)
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        self.parts.append(bytes(out))

    write_int = write_long

    def write_bytes(self, b: bytes) -> None:
        self.write_long(len(b))
        self.write(b)

    def write_string(self, s: str) -> None:
        self.write_bytes(s.encode("utf-8"))

    def write_float(self, v: float) -> None:
        self.write(struct.pack("<f", v))

    def write_double(self, v: float) -> None:
        self.write(struct.pack("<d", v))

    def write_boolean(self, v: bool) -> None:
        self.write(b"\x01" if v else b"\x00")

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


def _union_branch(schema_list: list, value: Any) -> int:
    """Pick the union branch for a value.  ['null', T] optionals take the
    single non-null branch; wider unions match the VALUE's python type
    against the branch kinds (the reader supports arbitrary unions, so the
    writer must not silently coerce - e.g. ['null','string','long'] with 5
    picks 'long', not 'string'; advisor r3 finding)."""
    names = [s if isinstance(s, str) else s.get("type") for s in schema_list]
    if value is None:
        if "null" in names:
            return names.index("null")
        raise ValueError("None for a union without a null branch")
    non_null = [(i, nm) for i, nm in enumerate(names) if nm != "null"]
    if not non_null:
        raise ValueError("union has only a null branch")
    if len(non_null) == 1:
        return non_null[0][0]
    if isinstance(value, bool):
        prefs = ("boolean",)
    elif isinstance(value, int):
        prefs = ("long", "int", "double", "float")
    elif isinstance(value, float):
        prefs = ("double", "float")
    elif isinstance(value, str):
        prefs = ("string", "enum")
    elif isinstance(value, (bytes, bytearray)):
        prefs = ("bytes", "fixed")
    elif isinstance(value, dict):
        prefs = ("record", "map")
    elif isinstance(value, (list, tuple)):
        prefs = ("array",)
    else:
        prefs = ()
    for p in prefs:
        for i, nm in non_null:
            if nm == p:
                return i
    raise ValueError(
        f"no union branch matches {type(value).__name__} value: {names}"
    )


def _encode_value(enc: _Encoder, schema: Any, value: Any) -> None:
    if isinstance(schema, list):  # union
        idx = _union_branch(schema, value)
        enc.write_long(idx)
        _encode_value(enc, schema[idx], value)
        return
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                _encode_value(enc, f["type"], (value or {}).get(f["name"]))
            return
        if t == "enum":
            enc.write_long(schema["symbols"].index(value))
            return
        if t == "fixed":
            if len(value) != schema["size"]:
                raise ValueError("fixed value has wrong size")
            enc.write(bytes(value))
            return
        if t == "array":
            items = list(value or [])
            if items:
                enc.write_long(len(items))
                for it in items:
                    _encode_value(enc, schema["items"], it)
            enc.write_long(0)
            return
        if t == "map":
            entries = dict(value or {})
            if entries:
                enc.write_long(len(entries))
                for k, v in entries.items():
                    enc.write_string(k)
                    _encode_value(enc, schema["values"], v)
            enc.write_long(0)
            return
        _encode_value(enc, t, value)  # {"type": "string"} style
        return
    if schema == "null":
        if value is not None:
            raise ValueError(f"non-null value {value!r} for null schema")
        return
    if schema == "boolean":
        enc.write_boolean(bool(value))
        return
    if schema in ("int", "long"):
        iv = int(value)
        if iv != value:
            # a double landing in a long field must error, not silently
            # round-trip with lost precision (advisor r3 finding)
            raise ValueError(f"non-integral value {value!r} for avro {schema}")
        enc.write_long(iv)
        return
    if schema == "float":
        enc.write_float(float(value))
        return
    if schema == "double":
        enc.write_double(float(value))
        return
    if schema == "bytes":
        enc.write_bytes(bytes(value))
        return
    if schema == "string":
        enc.write_string(str(value))
        return
    raise ValueError(f"unsupported avro type: {schema!r}")


def write_avro_records(
    path: str,
    schema: dict,
    records: Sequence[dict],
    codec: str = "deflate",
    block_records: int = 4096,
) -> int:
    """Write records to an Avro Object Container File; returns the count.
    The layout mirrors read_avro_records: magic, metadata map (schema JSON
    + codec), random sync marker, then blocks of (count, byte-length,
    payload, sync)."""
    import os as _os

    if codec not in ("null", "deflate"):
        raise ValueError(f"codec must be 'null' or 'deflate', got {codec!r}")
    sync = _os.urandom(16)
    head = _Encoder()
    head.write(MAGIC)
    meta = {
        "avro.schema": json.dumps(schema).encode(),
        "avro.codec": codec.encode(),
    }
    head.write_long(len(meta))
    for k, v in meta.items():
        head.write_string(k)
        head.write_bytes(v)
    head.write_long(0)
    head.write(sync)
    out = [head.getvalue()]
    n = 0
    for start in range(0, len(records), block_records):
        chunk = records[start : start + block_records]
        body = _Encoder()
        for rec in chunk:
            _encode_value(body, schema, rec)
        payload = body.getvalue()
        if codec == "deflate":
            # raw deflate (no zlib header), per the avro spec
            comp = zlib.compressobj(wbits=-15)
            payload = comp.compress(payload) + comp.flush()
        blk = _Encoder()
        blk.write_long(len(chunk))
        blk.write_bytes(payload)
        blk.write(sync)
        out.append(blk.getvalue())
        n += len(chunk)
    with open(path, "wb") as f:
        f.write(b"".join(out))
    return n


def _avro_field_name(name: str, seen: set) -> str:
    """Sanitize to the Avro name spec [A-Za-z_][A-Za-z0-9_]* - generated
    feature names contain '-' and would make the file unreadable by
    spec-conforming Avro implementations (java avro, spark, fastavro)."""
    import re as _re

    s = _re.sub(r"[^A-Za-z0-9_]", "_", name)
    if not s or not (s[0].isalpha() or s[0] == "_"):
        s = "_" + s
    base, k = s, 2
    while s in seen:
        s = f"{base}_{k}"
        k += 1
    seen.add(s)
    return s


def schema_for_dataset(ds: Dataset, name: str = "Row") -> dict:
    """An optional-field record schema for a Dataset's columns (every field
    ['null', T] - the reference's nullable-by-design contract).  Field
    names are sanitized to the Avro name spec; when renamed, the original
    column name is kept in the field's ``doc``."""
    from ..types.columns import (
        GeolocationColumn,
        ListColumn,
        MapColumn,
        NumericColumn,
        PredictionColumn,
        TextColumn,
    )
    from ..types import feature_types as ft

    fields = []
    seen: set = set()
    for col_name in ds.column_names():
        col = ds[col_name]
        if isinstance(col, NumericColumn):
            t = "long" if issubclass(col.feature_type, ft.Integral) else "double"
        elif isinstance(col, TextColumn):
            t = "string"
        elif isinstance(col, GeolocationColumn):
            t = {"type": "array", "items": "double"}
        elif isinstance(col, ListColumn):
            items = (
                "long"
                if issubclass(col.feature_type, (ft.DateList,))
                else "string"
            )
            t = {"type": "array", "items": items}
        elif isinstance(col, PredictionColumn):
            # Prediction rows serialize as {prediction, raw_i, prob_i}
            t = {"type": "map", "values": "double"}
        elif isinstance(col, MapColumn):
            vt = col.feature_type.value_type
            values = (
                "double"
                if vt is not None and issubclass(vt, ft.OPNumeric)
                else "string"
            )
            t = {"type": "map", "values": values}
        else:  # vectors -> array of doubles
            t = {"type": "array", "items": "double"}
        fname = _avro_field_name(col_name, seen)
        field = {"name": fname, "type": ["null", t]}
        if fname != col_name:
            field["doc"] = col_name
        fields.append(field)
    return {"type": "record", "name": name, "fields": fields}


def rows_from_dataset(ds: Dataset, schema: dict) -> list[dict]:
    """Transpose a Dataset into row dicts keyed by the schema's (possibly
    sanitized) field names; fields pair with columns positionally."""
    cols = ds.to_pylists()
    names = list(cols)
    fnames = [f["name"] for f in schema["fields"]]
    assert len(fnames) == len(names)
    return [
        {fn: cols[nm][i] for fn, nm in zip(fnames, names)}
        for i in range(len(ds))
    ]


def csv_to_avro(csv_path: str, avro_path: str, features: Sequence[Feature],
                codec: str = "deflate", **reader_kw) -> int:
    """CSV -> Avro OCF conversion (reference: utils/.../io/csv/
    CSVToAvro.scala): reads through CSVReader's typed columns and writes
    an optional-field record file; returns the row count."""
    from .csv_reader import CSVReader

    ds = CSVReader(csv_path, **reader_kw).generate_dataset(features)
    return save_dataset_avro(ds, avro_path, codec=codec)


def save_dataset_avro(ds: Dataset, path: str, name: str = "Row",
                      codec: str = "deflate") -> int:
    """Save a Dataset as an Avro OCF (the reference's df.saveAvro analog);
    returns the row count."""
    schema = schema_for_dataset(ds, name)
    return write_avro_records(path, schema, rows_from_dataset(ds, schema),
                              codec=codec)
