"""Arrow/Parquet → device ingestion.

SURVEY §7 names "chunked Arrow → make_array_from_process_local_data
double-buffering" a hard part of the rebuild; the CSV half lives in
fast_csv.DeviceCSVIngest, this is the Parquet/Arrow half (reference
contract: ParquetProductReader → Spark partitions → executor memory).

Row groups stream through ``pyarrow.parquet.ParquetFile.iter_batches`` in
a background thread; each batch converts to a float32 block + validity
mask at Arrow speed (no per-value python) and ships via the shared
double-buffered pump, so the decode of batch i+1 overlaps the DMA of
batch i.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from .fast_csv import double_buffered_to_device


def batch_to_numeric_block(batch, columns: Sequence[str]):
    """One Arrow record batch -> ([rows, d] float32 values, [rows, d] bool
    mask).  Nulls (and NaNs) are masked and zeroed - the NumericColumn
    contract."""
    cols_v, cols_m = [], []
    for name in columns:
        arr = batch.column(name)
        np_vals = arr.to_numpy(zero_copy_only=False)
        vals = np.asarray(np_vals, dtype=np.float32)
        # nulls surface as NaN after the float cast; Arrow's own null
        # bitmap covers types whose to_numpy uses sentinels
        mask = ~np.isnan(vals)
        if arr.null_count:
            mask &= ~np.asarray(arr.is_null())
        cols_v.append(np.where(mask, vals, np.float32(0.0)))
        cols_m.append(mask)
    return np.stack(cols_v, axis=1), np.stack(cols_m, axis=1)


class DeviceParquetIngest:
    """Parquet file -> device-resident [n, d] float32 design matrix with
    double-buffered transfer (the Arrow sibling of DeviceCSVIngest)."""

    def __init__(self, path: str, columns: Sequence[str],
                 batch_rows: int = 1 << 20) -> None:
        self.path = path
        self.columns = list(columns)
        self.batch_rows = batch_rows

    def _producer(self, q) -> None:
        try:
            import pyarrow.parquet as pq

            pf = pq.ParquetFile(self.path)
            for batch in pf.iter_batches(batch_size=self.batch_rows,
                                         columns=self.columns):
                if batch.num_rows == 0:
                    continue
                q.put(batch_to_numeric_block(batch, self.columns))
            q.put(None)
        except BaseException as e:
            q.put(e)

    def to_device(self):
        """Returns (X_device [n, d] float32, valid_mask [n, d] bool,
        rows)."""
        return double_buffered_to_device(self._producer, len(self.columns))
