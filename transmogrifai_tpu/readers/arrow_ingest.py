"""Arrow/Parquet → device ingestion.

SURVEY §7 names "chunked Arrow → make_array_from_process_local_data
double-buffering" a hard part of the rebuild; the CSV half lives in
fast_csv.DeviceCSVIngest, this is the Parquet/Arrow half (reference
contract: ParquetProductReader → Spark partitions → executor memory).

Row groups stream through ``pyarrow.parquet.ParquetFile.iter_batches`` in
a background thread; each batch converts to a float32 block + validity
mask at Arrow speed (no per-value python) and ships via the shared
double-buffered pump, so the decode of batch i+1 overlaps the DMA of
batch i.

Error policy (``errors=``, schema/quarantine.py): ``"coerce"`` is the
legacy vectorized path (a string-typed column either casts or raises
raw); ``"strict"`` raises MalformedRowError naming the global row
index/column of the first junk cell; ``"quarantine"`` drops junk rows
from the device block and records them in a bounded QuarantineBuffer.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..faults import injection as _faults
from ..obs import trace as _obs_trace
from ..schema.quarantine import (
    MalformedRowError,
    QuarantineBuffer,
    check_errors_mode,
    coerce_numeric,
    data_telemetry,
    excerpt_of,
)
from .fast_csv import double_buffered_to_device


def batch_to_numeric_block(batch, columns: Sequence[str]):
    """One Arrow record batch -> ([rows, d] float32 values, [rows, d] bool
    mask).  Nulls (and NaNs) are masked and zeroed - the NumericColumn
    contract."""
    cols_v, cols_m = [], []
    for name in columns:
        arr = batch.column(name)
        np_vals = arr.to_numpy(zero_copy_only=False)
        vals = np.asarray(np_vals, dtype=np.float32)
        # nulls surface as NaN after the float cast; Arrow's own null
        # bitmap covers types whose to_numpy uses sentinels
        mask = ~np.isnan(vals)
        if arr.null_count:
            mask &= ~np.asarray(arr.is_null())
        cols_v.append(np.where(mask, vals, np.float32(0.0)))
        cols_m.append(mask)
    return np.stack(cols_v, axis=1), np.stack(cols_m, axis=1)


def checked_batch_to_numeric_block(
    batch,
    columns: Sequence[str],
    errors: str,
    quarantine: QuarantineBuffer,
    row_offset: int,
    source: str,
    telemetry=None,
):
    """The validated sibling of :func:`batch_to_numeric_block`: columns
    that refuse the vectorized float cast (string-typed numerics) parse
    per-value; a non-null cell that fails the parse is a type flip —
    strict raises naming the global row index, quarantine drops the row.
    Returns (values, mask, n_bad)."""
    cols_v, cols_m = [], []
    bad: dict[int, tuple[str, str]] = {}
    for name in columns:
        arr = batch.column(name)
        np_vals = arr.to_numpy(zero_copy_only=False)
        try:
            vals = np.asarray(np_vals, dtype=np.float32)
        except (TypeError, ValueError):
            raw = arr.to_pylist()
            vals = np.empty(len(raw), dtype=np.float32)
            for i, v in enumerate(raw):
                p = None if v is None else coerce_numeric(v)
                if p is None:
                    vals[i] = np.nan
                    if v is not None and i not in bad:
                        bad[i] = (name, excerpt_of(v))
                else:
                    vals[i] = p
        mask = ~np.isnan(vals)
        if arr.null_count:
            mask &= ~np.asarray(arr.is_null())
        cols_v.append(np.where(mask, vals, np.float32(0.0)))
        cols_m.append(mask)
    values = np.stack(cols_v, axis=1)
    masks = np.stack(cols_m, axis=1)
    n = values.shape[0]
    if _faults.fires("reader.type_flip") is not None and n:
        bad.setdefault(0, (columns[0], "<injected>"))
    if _faults.fires("reader.malformed_row") is not None and n:
        bad.setdefault(0, ("", "<injected>"))
    if not bad:
        return values, masks, 0
    if errors == "strict":
        i0 = min(bad)
        col, cell = bad[i0]
        (telemetry or data_telemetry()).record_strict_error(source)
        raise MalformedRowError(
            source, row_offset + i0, "type_flip", col or None, cell
        )
    for i in sorted(bad):
        col, cell = bad[i]
        quarantine.add(row_offset + i, "type_flip", col or None, cell)
    keep = np.ones(n, dtype=bool)
    keep[list(bad)] = False
    return values[keep], masks[keep], len(bad)


class DeviceParquetIngest:
    """Parquet file -> device-resident [n, d] float32 design matrix with
    double-buffered transfer (the Arrow sibling of DeviceCSVIngest)."""

    def __init__(self, path: str, columns: Sequence[str],
                 batch_rows: int = 1 << 20,
                 errors: str = "coerce",
                 quarantine: Optional[QuarantineBuffer] = None,
                 telemetry=None) -> None:
        self.path = path
        self.columns = list(columns)
        self.batch_rows = batch_rows
        self.errors = check_errors_mode(errors)
        if self.errors != "coerce" and quarantine is None:
            quarantine = QuarantineBuffer(source=path)
        self.quarantine = quarantine
        self.telemetry = telemetry

    def _producer(self, q) -> None:
        checked = self.errors != "coerce"
        rows_seen = rows_kept = 0
        try:
            import pyarrow.parquet as pq

            pf = pq.ParquetFile(self.path)
            for batch in pf.iter_batches(batch_size=self.batch_rows,
                                         columns=self.columns):
                if batch.num_rows == 0:
                    continue
                if checked:
                    vals, mask, n_bad = checked_batch_to_numeric_block(
                        batch, self.columns, self.errors, self.quarantine,
                        rows_seen, self.path, telemetry=self.telemetry,
                    )
                    rows_seen += batch.num_rows
                    rows_kept += batch.num_rows - n_bad
                    if vals.shape[0]:
                        q.put((vals, mask))
                else:
                    q.put(batch_to_numeric_block(batch, self.columns))
            if checked:
                (self.telemetry or data_telemetry()).record_read(
                    self.path, rows_seen, rows_kept, self.quarantine
                )
            q.put(None)
        except BaseException as e:
            q.put(e)

    def to_device(self):
        """Returns (X_device [n, d] float32, valid_mask [n, d] bool,
        rows)."""
        with _obs_trace.span(
            "ingest.device", source=self.path, format="parquet",
        ):
            return double_buffered_to_device(
                self._producer, len(self.columns)
            )
